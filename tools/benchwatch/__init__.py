"""benchwatch: schema-validated bench ledger + regression watch.

The committed ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` records are the
repo's only longitudinal performance record, and until now nothing read
them: a PR could halve throughput and tier-1 would stay green. This
tool ingests the ledger, validates every record against the schema the
bench harness actually emits, and runs a noise-tolerant regression
check:

- **Usable** records: ``rc == 0``, non-null ``parsed``, and no
  ``platform_fallback`` marker (a CPU-fallback number is not comparable
  to TPU history). Unusable records are SKIPPED AND REPORTED with a
  reason — an rc!=0 TPU-init flake (BENCH_r05) is not a regression, but
  it is not silently dropped either.
- **Regression** per metric: the median of the newest
  ``recent_window`` usable values vs the median of the
  ``baseline_window`` values before them; flagged when recent <
  baseline x (1 - tolerance) for higher-is-better series, or recent >
  baseline x (1 + tolerance) for lower-is-better overheads (see
  ``EXTRA_METRIC_FIELDS``). Medians tolerate single-run noise; the
  windows are configurable per invocation.

Surfaces: ``python -m tools.benchwatch`` (scripts/lint.sh gate 4 runs
``--validate-only``; scripts/tier1.sh runs the full check) and
``cli perf check`` (same code, same verdict). Exit codes: 0 pass,
1 malformed ledger, 2 regression. Deliberately jax-free so the lint
gate stays cheap.
"""

from __future__ import annotations

import glob
import json
import os
import statistics

__all__ = [
    "EXTRA_METRIC_FIELDS",
    "check_regressions",
    "load_ledger",
    "load_profile_ledger",
    "render_markdown",
    "validate_profile_record",
    "validate_record",
]

#: field -> required type(s) for the two record kinds (the shape
#: bench.py emits and the committed history carries; ``parsed`` extras
#: beyond the core four keys are allowed — newer bench.py versions
#: append fields like fetch_qps/mfu and old records must stay valid).
_BENCH_FIELDS = {"n": int, "cmd": str, "rc": int, "tail": str}
_PARSED_FIELDS = {"metric": str, "value": (int, float), "unit": str}
_MULTICHIP_FIELDS = {"n_devices": int, "rc": int, "ok": bool,
                     "skipped": bool, "tail": str}

#: Secondary series lifted out of ``parsed`` extras and watched
#: alongside the headline metric: field name -> unit string (plain
#: higher-is-better series) or ``{"unit", "direction": "lower"}`` for
#: overheads that regress UPWARD (recent > baseline x (1 + tolerance)).
#: Optional by design — records that predate a field (or record it
#: null) simply don't contribute a point, so a new field starts at
#: insufficient_history and only gates once enough rounds carry it.
#: ``codec_mb_per_s`` (ISSUE 14) is the device-resident push codec's
#: encode throughput; ``fanout_qps`` (ISSUE 17) is the edge-replica
#: delta-serve rate of the two-tier fan-out probe;
#: ``journal_write_us``/``journal_bytes_per_tick`` (ISSUE 18) are the
#: durable journal's per-record append latency and per-snapshot disk
#: cost — both lower-is-better, gating the <2% overhead claim.
#: ``goodput_fraction`` (ISSUE 20) is the productive fraction of the
#: bench's timed-trial wall (harness overhead shows up as the gap below
#: 1.0) — higher-is-better like the headline metric.
EXTRA_METRIC_FIELDS = {"codec_mb_per_s": "MB/s",
                       "fanout_qps": "fetch/s",
                       "journal_write_us": {"unit": "us",
                                            "direction": "lower"},
                       "journal_bytes_per_tick": {"unit": "B",
                                                  "direction": "lower"},
                       "goodput_fraction": "fraction"}


def _field_spec(spec) -> tuple[str, str]:
    """(unit, direction) for one EXTRA_METRIC_FIELDS value — a bare
    string means higher-is-better, the dict form names its direction."""
    if isinstance(spec, dict):
        return str(spec.get("unit", "")), str(spec.get("direction",
                                                       "higher"))
    return str(spec), "higher"


def _type_errors(obj: dict, fields: dict, ctx: str) -> list:
    errs = []
    for key, typ in fields.items():
        if key not in obj:
            errs.append(f"{ctx}: missing required field {key!r}")
        elif not isinstance(obj[key], typ) or isinstance(obj[key], bool) \
                and typ is int:
            errs.append(f"{ctx}: field {key!r} has type "
                        f"{type(obj[key]).__name__}, wanted "
                        f"{getattr(typ, '__name__', typ)}")
    return errs


def validate_record(kind: str, obj) -> list:
    """Schema errors for one record ('' list = valid). ``kind`` is
    'bench' or 'multichip'."""
    if not isinstance(obj, dict):
        return [f"{kind} record is {type(obj).__name__}, wanted object"]
    if kind == "multichip":
        return _type_errors(obj, _MULTICHIP_FIELDS, "multichip")
    errs = _type_errors(obj, _BENCH_FIELDS, "bench")
    if "parsed" not in obj:
        errs.append("bench: missing required field 'parsed'")
    elif obj["parsed"] is not None:
        if not isinstance(obj["parsed"], dict):
            errs.append("bench: 'parsed' must be null or object")
        else:
            errs += _type_errors(obj["parsed"], _PARSED_FIELDS,
                                 "bench.parsed")
            if "vs_baseline" not in obj["parsed"]:
                errs.append("bench.parsed: missing required field "
                            "'vs_baseline'")
    return errs


def load_ledger(root: str) -> dict:
    """All committed records under ``root``, in run order, each entry
    ``{"file", "kind", "record"|None, "errors": [...]}``."""
    entries = []
    for kind, pat in (("bench", "BENCH_*.json"),
                      ("multichip", "MULTICHIP_*.json")):
        for path in sorted(glob.glob(os.path.join(root, pat))):
            entry = {"file": os.path.basename(path), "kind": kind,
                     "record": None, "errors": []}
            try:
                with open(path) as f:
                    obj = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                entry["errors"] = [f"unreadable: {e}"]
                entries.append(entry)
                continue
            entry["record"] = obj
            entry["errors"] = validate_record(kind, obj)
            entries.append(entry)
    return {"root": root, "entries": entries,
            "malformed": [e for e in entries if e["errors"]]}


#: Required shape of one committed ``profiles/PROFILE_*.json`` ledger
#: record (the ProfileTrigger writes these; field semantics are
#: drift-pinned in telemetry/proftrigger.py PROFILE_RECORD_FIELDS —
#: NOT imported here, benchwatch stays jax-free by construction).
_PROFILE_FIELDS = {"id": str, "created_ts": (int, float), "rule": str,
                   "profile": dict}


def validate_profile_record(obj) -> list:
    """Schema errors for one profile-ledger record ('' list = valid)."""
    if not isinstance(obj, dict):
        return [f"profile record is {type(obj).__name__}, wanted object"]
    errs = _type_errors(obj, _PROFILE_FIELDS, "profile")
    prof = obj.get("profile")
    if isinstance(prof, dict):
        ocs = prof.get("op_classes")
        if not isinstance(ocs, dict):
            errs.append("profile.profile: missing 'op_classes' object")
        else:
            for cls, row in ocs.items():
                t = row.get("time_s") if isinstance(row, dict) else None
                if not isinstance(t, (int, float)) \
                        or isinstance(t, bool):
                    errs.append(f"profile.profile.op_classes[{cls!r}]: "
                                f"missing numeric 'time_s'")
    return errs


def load_profile_ledger(root: str) -> dict:
    """All committed ``PROFILE_*.json`` records under ``root``, oldest
    first (the id stamp sorts lexically), same entry shape as
    :func:`load_ledger`."""
    entries = []
    for path in sorted(glob.glob(os.path.join(root, "PROFILE_*.json"))):
        entry = {"file": os.path.basename(path), "kind": "profile",
                 "record": None, "errors": []}
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            entry["errors"] = [f"unreadable: {e}"]
            entries.append(entry)
            continue
        entry["record"] = obj
        entry["errors"] = validate_profile_record(obj)
        entries.append(entry)
    return {"root": root, "entries": entries,
            "malformed": [e for e in entries if e["errors"]]}


def _profile_points(profile_ledger: dict, skipped: list) -> dict:
    """Per-op-class ``time_s`` series from the profile ledger, keyed
    ``profile:<class>.time_s`` (lower-is-better — a class whose device
    time grows across captures regressed). Bases must agree to compare:
    records whose attribution basis differs from the NEWEST usable
    record's are skipped and reported, never silently mixed — the same
    honesty rule ``cli perf diff`` enforces with a refusal."""
    usable = []
    for entry in profile_ledger["entries"]:
        if entry["errors"]:
            continue
        rec = entry["record"]
        basis = (rec.get("profile") or {}).get("basis")
        if basis in (None, "none"):
            skipped.append({"file": entry["file"],
                            "reason": "basis=none (attribution failed; "
                                      "not comparable)"})
            continue
        usable.append((entry["file"], basis, rec))
    if not usable:
        return {}
    ref_basis = usable[-1][1]
    by_metric: dict[str, list] = {}
    for fname, basis, rec in usable:
        if basis != ref_basis:
            skipped.append({"file": fname,
                            "reason": f"basis={basis!r} != newest "
                                      f"{ref_basis!r} (different "
                                      f"measurements; not comparable)"})
            continue
        for cls, row in rec["profile"]["op_classes"].items():
            by_metric.setdefault(f"profile:{cls}.time_s", []).append(
                {"file": fname, "value": float(row["time_s"]),
                 "unit": "s", "direction": "lower"})
    return by_metric


def _usable_bench(entry: dict) -> tuple[bool, str]:
    """(usable, reason-if-not) for one valid bench entry."""
    rec = entry["record"]
    if rec.get("rc") != 0:
        return False, f"rc={rec.get('rc')} (run failed; not comparable)"
    parsed = rec.get("parsed")
    if not isinstance(parsed, dict):
        return False, "parsed=null (no metric extracted)"
    if parsed.get("platform_fallback"):
        return False, (f"platform_fallback="
                       f"{parsed.get('platform_fallback')!r} "
                       f"(not comparable to accelerator history)")
    return True, ""


def check_regressions(ledger: dict, tolerance: float = 0.05,
                      baseline_window: int = 3,
                      recent_window: int = 1,
                      profile_ledger: dict | None = None) -> dict:
    """The verdict over one loaded ledger (see module docstring). With
    ``profile_ledger`` (:func:`load_profile_ledger`), the committed
    per-op-class ``time_s`` series regression-check alongside the bench
    metrics — lower-is-better, same median windows."""
    if tolerance < 0 or baseline_window < 1 or recent_window < 1:
        raise ValueError("tolerance must be >= 0 and windows >= 1")
    skipped = []
    by_metric: dict[str, list] = {}
    if profile_ledger is not None:
        by_metric.update(_profile_points(profile_ledger, skipped))
    for entry in ledger["entries"]:
        if entry["kind"] != "bench" or entry["errors"]:
            continue
        ok, reason = _usable_bench(entry)
        if not ok:
            skipped.append({"file": entry["file"], "reason": reason})
            continue
        parsed = entry["record"]["parsed"]
        by_metric.setdefault(parsed["metric"], []).append(
            {"file": entry["file"], "value": float(parsed["value"]),
             "unit": parsed.get("unit", ""), "direction": "higher"})
        for field, spec in EXTRA_METRIC_FIELDS.items():
            unit, direction = _field_spec(spec)
            v = parsed.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                by_metric.setdefault(field, []).append(
                    {"file": entry["file"], "value": float(v),
                     "unit": unit, "direction": direction})
    metrics = {}
    regressions = []
    for metric, points in by_metric.items():
        values = [p["value"] for p in points]
        direction = points[0].get("direction", "higher")
        row: dict = {"unit": points[0]["unit"], "runs": len(points),
                     "values": values, "direction": direction,
                     "files": [p["file"] for p in points]}
        if len(values) < baseline_window + recent_window:
            row["status"] = "insufficient_history"
            row["needed"] = baseline_window + recent_window
        else:
            recent = statistics.median(values[-recent_window:])
            base = statistics.median(
                values[-(recent_window + baseline_window):-recent_window])
            if direction == "lower":
                ceiling = base * (1.0 + tolerance)
                regressed = recent > ceiling
                bound = {"ceiling": round(ceiling, 3)}
            else:
                floor = base * (1.0 - tolerance)
                regressed = recent < floor
                bound = {"floor": round(floor, 3)}
            row.update({
                "recent_median": round(recent, 3),
                "baseline_median": round(base, 3),
                "change_fraction": round((recent - base) / base, 4)
                if base else None,
                "status": "regression" if regressed else "ok",
                **bound,
            })
            if row["status"] == "regression":
                regressions.append(metric)
        metrics[metric] = row
    malformed = [{"file": e["file"], "errors": e["errors"]}
                 for e in ledger["malformed"]]
    if profile_ledger is not None:
        malformed += [{"file": e["file"], "errors": e["errors"]}
                      for e in profile_ledger["malformed"]]
    status = "malformed" if malformed else (
        "regression" if regressions else "pass")
    return {
        "status": status,
        "tolerance": tolerance,
        "baseline_window": baseline_window,
        "recent_window": recent_window,
        "metrics": metrics,
        "regressions": sorted(regressions),
        "skipped": skipped,
        "malformed": malformed,
    }


def render_markdown(verdict: dict) -> str:
    """Markdown verdict for humans / PR comments."""
    icon = {"pass": "PASS", "regression": "REGRESSION",
            "malformed": "MALFORMED LEDGER"}
    label = icon.get(verdict["status"], verdict["status"])
    lines = [f"## benchwatch: {label}", ""]
    if verdict["metrics"]:
        lines += ["| metric | runs | baseline | recent | change | "
                  "status |", "|---|---|---|---|---|---|"]
        for name in sorted(verdict["metrics"]):
            m = verdict["metrics"][name]
            if m["status"] == "insufficient_history":
                lines.append(f"| `{name}` | {m['runs']} | - | - | - | "
                             f"insufficient history "
                             f"(need {m['needed']}) |")
                continue
            chg = m["change_fraction"]
            chg_s = "-" if chg is None else f"{chg*100:+.1f}%"
            lines.append(
                f"| `{name}` | {m['runs']} | {m['baseline_median']} | "
                f"{m['recent_median']} | {chg_s} | {m['status']} |")
    else:
        lines.append("_no usable bench records_")
    if verdict["skipped"]:
        lines += ["", "Skipped records (reported, never compared):"]
        lines += [f"- `{s['file']}`: {s['reason']}"
                  for s in verdict["skipped"]]
    if verdict["malformed"]:
        lines += ["", "Malformed records (fail the gate):"]
        lines += [f"- `{m['file']}`: {'; '.join(m['errors'])}"
                  for m in verdict["malformed"]]
    lines += ["", f"tolerance {verdict['tolerance']*100:.0f}% · baseline "
                  f"window {verdict['baseline_window']} · recent window "
                  f"{verdict['recent_window']}"]
    return "\n".join(lines)
