"""CLI for the bench regression watch (see package docstring).

Exit codes: 0 pass, 1 malformed ledger, 2 regression — distinct so
scripts/lint.sh (schema gate) and scripts/tier1.sh (full check) can
both consume the same entry point.
"""

from __future__ import annotations

import argparse
import json
import sys

import os

from . import (check_regressions, load_ledger, load_profile_ledger,
               render_markdown)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.benchwatch",
        description="Validate the committed bench ledger and check for "
                    "throughput regressions.")
    ap.add_argument("--root", default=".",
                    help="directory holding BENCH_*.json / "
                         "MULTICHIP_*.json (default: .)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed fractional drop before a regression "
                         "flags (default: 0.05)")
    ap.add_argument("--baseline-window", type=int, default=3,
                    help="usable runs in the baseline median (default: 3)")
    ap.add_argument("--recent-window", type=int, default=1,
                    help="usable runs in the recent median (default: 1)")
    ap.add_argument("--format", choices=("md", "json"), default="md",
                    help="verdict output format (default: md)")
    ap.add_argument("--validate-only", action="store_true",
                    help="schema-validate the ledger and stop (the "
                         "scripts/lint.sh gate)")
    ap.add_argument("--profiles-root", default=None,
                    help="committed profile ledger directory (default: "
                         "<root>/profiles when it exists); PROFILE_*."
                         "json records are schema-validated and their "
                         "per-op-class time_s series regression-checked "
                         "lower-is-better")
    args = ap.parse_args(argv)

    ledger = load_ledger(args.root)
    profiles_root = args.profiles_root \
        or os.path.join(args.root, "profiles")
    profiles = load_profile_ledger(profiles_root) \
        if os.path.isdir(profiles_root) else None
    if args.validate_only:
        malformed = list(ledger["malformed"])
        if profiles is not None:
            malformed += profiles["malformed"]
        if malformed:
            for e in malformed:
                for err in e["errors"]:
                    print(f"benchwatch: {e['file']}: {err}",
                          file=sys.stderr)
            return 1
        n = len(ledger["entries"])
        np_ = len(profiles["entries"]) if profiles is not None else 0
        print(f"benchwatch: ledger OK ({n} records, "
              f"{np_} profile records)")
        return 0

    verdict = check_regressions(
        ledger, tolerance=args.tolerance,
        baseline_window=args.baseline_window,
        recent_window=args.recent_window,
        profile_ledger=profiles)
    if args.format == "json":
        print(json.dumps(verdict, indent=2))
    else:
        print(render_markdown(verdict))
    return {"pass": 0, "malformed": 1, "regression": 2}[verdict["status"]]


if __name__ == "__main__":
    sys.exit(main())
