"""Lock-discipline race detector (rules ``lock-guard``, ``thread-shared``).

Two complementary checks over every class in the package:

1. **Declared guards** (``lock-guard``, error). A field annotated at its
   assignment with ``# guarded by: self._lock`` (same line or the line
   directly above) may only be touched — read OR written; a dict read
   during another thread's resize is just as racy as a write — inside a
   ``with`` block on that exact lock. Exemptions, both load-bearing
   conventions of this codebase:

   - ``__init__`` and ``_init*`` helpers (constructor-phase: no other
     thread can hold a reference yet; ``ps/store.py``'s
     ``_init_round_state`` et al), and
   - methods whose name ends ``_locked`` (the caller holds the lock;
     ``ps/store.py``'s ``_arm_deadline_locked`` et al).

   Guards may be declared on a ``self.x = ...`` assignment in any
   method, or on a class-body (ann-)assignment — mixins like
   ``AggregationBase`` declare contracts for state their concrete
   subclasses construct. Declarations inherit through MODULE-LOCAL base
   classes (``ParameterStore`` is checked against ``AggregationBase``'s
   contracts); a subclass in another module re-declares the inherited
   contracts it touches.

2. **Undeclared sharing** (``thread-shared``, warning). Any attribute
   written outside ``__init__``/``start`` that is reachable both from a
   ``threading.Thread``/``Timer`` entry point (``target=self.x``, the
   ``Timer`` function argument, or ``run`` on a Thread subclass —
   transitively through ``self.method()`` calls) and from a method no
   thread entry reaches, with no declared guard. Attributes that ARE the
   synchronization (locks, events, conditions), thread/timer handles, and
   telemetry instruments (internally locked) are recognized by their
   ``__init__`` assignment and skipped.

``start`` is treated like ``__init__`` on the write side because this
codebase's lifecycle convention is bind-then-spawn: ``start()`` fills
fields (bound port, advertise address) strictly before the thread it
starts can observe them.
"""

from __future__ import annotations

import ast

from .core import GUARD_RE, Finding, SourceFile

#: Constructors whose result makes an attribute "synchronization, not
#: state" for the thread-shared heuristic.
_SYNC_TYPES = {"Lock", "RLock", "Event", "Condition", "Semaphore",
               "BoundedSemaphore", "Barrier", "local", "Thread", "Timer",
               "Queue", "deque"}

#: Registry factory methods whose products carry their own locks.
_INSTRUMENT_FACTORIES = {"counter", "gauge", "histogram"}

#: Container methods that mutate their receiver: ``self.x.append(...)``
#: is a write of ``self.x`` for race purposes.
_MUTATORS = {"append", "appendleft", "add", "clear", "discard", "extend",
             "insert", "pop", "popleft", "popitem", "remove", "setdefault",
             "update"}

_WRITE_EXEMPT = {"__init__", "start"}


def _call_name(node: ast.Call) -> str:
    """Trailing identifier of the called object: Thread, Timer, counter…"""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.guards: dict[str, tuple[str, int]] = {}  # field -> (lock, ln)
        self.sync_attrs: set[str] = set()
        self.methods: dict[str, ast.FunctionDef] = {}
        self.thread_entries: set[str] = set()
        # method -> attr -> [lines], split by access kind
        self.reads: dict[str, dict[str, list[int]]] = {}
        self.writes: dict[str, dict[str, list[int]]] = {}
        self.calls: dict[str, set[str]] = {}  # method -> self.m() callees


def _collect_class(src: SourceFile, node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(node)
    # Class-body declarations: `x: T  # guarded by: self._lock` lets a
    # mixin declare the contract for attributes its subclasses assign.
    for item in node.body:
        if isinstance(item, (ast.Assign, ast.AnnAssign)):
            targets = item.targets if isinstance(item, ast.Assign) \
                else [item.target]
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names:
                continue
            comment = src.comment_at(item.lineno) or \
                src.own_line_comment(item.lineno - 1)
            m = GUARD_RE.search(comment)
            if m:
                for name in names:
                    info.guards[name] = (m.group(1), item.lineno)
    for base in node.bases:
        tail = base.attr if isinstance(base, ast.Attribute) else \
            base.id if isinstance(base, ast.Name) else ""
        if tail in ("Thread", "Timer"):
            info.thread_entries.add("run")
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
    for meth_name, meth in info.methods.items():
        reads = info.reads.setdefault(meth_name, {})
        writes = info.writes.setdefault(meth_name, {})
        callees = info.calls.setdefault(meth_name, set())
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Attribute):
                attr = _self_attr(sub)
                if attr is None:
                    continue
                bucket = writes if isinstance(
                    sub.ctx, (ast.Store, ast.Del)) else reads
                bucket.setdefault(attr, []).append(sub.lineno)
            elif isinstance(sub, ast.Subscript) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                # self.d[k] = v rebinds an ITEM: a write of self.d for
                # race purposes even though the attribute load is a read.
                attr = _self_attr(sub.value)
                if attr is not None:
                    writes.setdefault(attr, []).append(sub.lineno)
            elif isinstance(sub, ast.Call):
                callee = _self_attr(sub.func)
                if callee is not None:
                    callees.add(callee)
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _MUTATORS:
                    attr = _self_attr(sub.func.value)
                    if attr is not None:
                        writes.setdefault(attr, []).append(sub.lineno)
                name = _call_name(sub)
                if name in ("Thread", "Timer"):
                    for kw in sub.keywords:
                        if kw.arg in ("target", "function"):
                            t = _self_attr(kw.value)
                            if t:
                                info.thread_entries.add(t)
                    if name == "Timer" and len(sub.args) >= 2:
                        t = _self_attr(sub.args[1])
                        if t:
                            info.thread_entries.add(t)
            # Guard annotations + sync-typed attributes, from assignments.
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                attrs = [a for a in (_self_attr(t) for t in targets) if a]
                if not attrs:
                    continue
                comment = src.comment_at(sub.lineno) or \
                    src.own_line_comment(sub.lineno - 1)
                m = GUARD_RE.search(comment)
                if m:
                    for a in attrs:
                        info.guards[a] = (m.group(1), sub.lineno)
                value = getattr(sub, "value", None)
                if isinstance(value, ast.Call):
                    cname = _call_name(value)
                    if cname in _SYNC_TYPES \
                            or cname in _INSTRUMENT_FACTORIES:
                        info.sync_attrs.update(attrs)
    return info


def _thread_reachable(info: _ClassInfo) -> set[str]:
    seen = set(info.thread_entries & set(info.methods))
    frontier = list(seen)
    while frontier:
        m = frontier.pop()
        for callee in info.calls.get(m, ()):
            if callee in info.methods and callee not in seen:
                seen.add(callee)
                frontier.append(callee)
    return seen


class _GuardChecker(ast.NodeVisitor):
    """Walk one method tracking which ``with self.<lock>:`` blocks the
    current node is lexically inside."""

    def __init__(self, info: _ClassInfo, meth_name: str,
                 src: SourceFile, out: list[Finding]):
        self.info = info
        self.meth = meth_name
        self.src = src
        self.out = out
        self.held: list[str] = []

    def visit_With(self, node: ast.With):
        locks = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None:
                locks.append(attr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.extend(locks)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(locks):]

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None and attr in self.info.guards:
            lock = self.info.guards[attr][0]
            if lock not in self.held:
                verb = "written" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "read"
                self.out.append(Finding(
                    "lock-guard", self.src.rel, node.lineno,
                    f"{self.info.name}.{self.meth}.{attr}",
                    f"{self.info.name}.{attr} is declared guarded by "
                    f"self.{lock} but is {verb} in {self.meth}() outside "
                    f"a `with self.{lock}:` block"))
        self.generic_visit(node)


def _inherit_guards(infos_by_name: dict[str, _ClassInfo],
                    info: _ClassInfo, seen: set[str]) -> dict:
    """Base-class guard declarations, module-local only (an imported base
    is invisible — its subclass re-declares what it touches)."""
    out: dict = {}
    for base in info.node.bases:
        if isinstance(base, ast.Name) and base.id in infos_by_name \
                and base.id not in seen:
            seen.add(base.id)
            out.update(_inherit_guards(
                infos_by_name, infos_by_name[base.id], seen))
    out.update(info.guards)
    return out


def run(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        infos: list[_ClassInfo] = []
        by_name: dict[str, _ClassInfo] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                ci = _collect_class(src, node)
                infos.append(ci)
                by_name.setdefault(ci.name, ci)
        for info in infos:
            info.guards = _inherit_guards(by_name, info, {info.name})
            if info.guards:
                for meth_name, meth in info.methods.items():
                    if meth_name == "__init__" \
                            or meth_name.startswith("_init") \
                            or meth_name.endswith("_locked"):
                        continue
                    _GuardChecker(info, meth_name, src, findings).visit(
                        meth)
            if not info.thread_entries:
                continue
            reachable = _thread_reachable(info)
            others = set(info.methods) - reachable - _WRITE_EXEMPT
            for attr in sorted(
                    {a for m in info.methods
                     for a in (*info.reads.get(m, ()),
                               *info.writes.get(m, ()))}):
                if attr in info.guards or attr in info.sync_attrs:
                    continue
                writers = {m for m, w in info.writes.items() if attr in w}
                if not writers - _WRITE_EXEMPT:
                    continue  # config: filled before any thread exists
                touched = {m for m in info.methods
                           if attr in info.reads.get(m, ())
                           or attr in info.writes.get(m, ())}
                t_side = touched & reachable
                o_side = touched & others
                if not t_side or not o_side:
                    continue
                lines = sorted(
                    ln for m in (t_side | o_side) - _WRITE_EXEMPT
                    for ln in (*info.reads.get(m, {}).get(attr, ()),
                               *info.writes.get(m, {}).get(attr, ())))
                findings.append(Finding(
                    "thread-shared", src.rel, lines[0],
                    f"{info.name}.{attr}",
                    f"{info.name}.{attr} is shared between thread "
                    f"target(s) {sorted(t_side)} and {sorted(o_side)} "
                    f"with no `# guarded by:` declaration"))
    return findings
