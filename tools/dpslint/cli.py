"""dpslint entry point: ``python -m tools.dpslint`` (and ``cli lint``).

Exit codes:

- ``0`` — no live findings (inline-suppressed and baselined ones are
  reported as counts but don't fail the run);
- ``1`` — live findings, or stale baseline entries (the debt register
  may only shrink: an entry matching nothing must be deleted);
- ``2`` — the analyzer itself failed (unparseable source, malformed
  baseline) — loud, never a silent pass.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import capability, catalog_drift, hot_path, jax_pitfalls, \
    lock_discipline
from .core import (BaselineError, apply_baseline, load_baseline,
                   load_sources, split_suppressed)

#: Repo root (tools/dpslint/cli.py -> tools/dpslint -> tools -> root).
REPO_ROOT = Path(__file__).resolve().parents[2]
PACKAGE = "distributed_parameter_server_for_ml_training_tpu"
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

_PASSES = (lock_discipline.run, hot_path.run, capability.run,
           jax_pitfalls.run)


def run_lint(root: Path | None = None,
             baseline_path: Path | None = None) -> dict:
    """Run every pass; returns the full result dict the CLI renders.

    ``exit_code`` in the result follows the module contract above.
    Importable (tests, bench.py, cli lint) so every consumer shares one
    definition of "clean".
    """
    root = Path(root) if root is not None else REPO_ROOT
    baseline_path = (Path(baseline_path) if baseline_path is not None
                     else DEFAULT_BASELINE)
    t0 = time.perf_counter()
    sources = load_sources(root / PACKAGE, root)
    findings = []
    for run_pass in _PASSES:
        findings.extend(run_pass(sources))
    findings.extend(catalog_drift.run(sources, root))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    live, suppressed = split_suppressed(findings, sources)
    baseline = load_baseline(baseline_path)
    live, baselined, stale = apply_baseline(live, baseline)
    return {
        "live": live,
        "suppressed": suppressed,
        "baselined": baselined,
        "stale_baseline": stale,
        "files_scanned": len(sources),
        "runtime_s": round(time.perf_counter() - t0, 3),
        "exit_code": 1 if (live or stale) else 0,
    }


def _render_human(result: dict, out) -> None:
    for f in result["live"]:
        print(f.render(), file=out)
    for entry in result["stale_baseline"]:
        print(f"{entry['file']}: [baseline] stale entry "
              f"({entry['rule']} {entry['symbol']}) matches nothing — "
              f"delete it", file=out)
    n = len(result["live"])
    print(f"dpslint: {n} finding{'s' if n != 1 else ''} "
          f"({len(result['baselined'])} baselined, "
          f"{len(result['suppressed'])} suppressed, "
          f"{len(result['stale_baseline'])} stale baseline) across "
          f"{result['files_scanned']} files in "
          f"{result['runtime_s']}s", file=out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dpslint",
        description="Framework-aware static analysis for the DPS "
                    "package (lock discipline, hot-path allocations, "
                    "capability gating, JAX pitfalls, catalog drift).")
    parser.add_argument("--root", type=Path, default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--baseline", type=Path, default=None,
                        help=f"baseline file (default: "
                             f"{DEFAULT_BASELINE.name} next to the tool)")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report instead of human lines")
    args = parser.parse_args(argv)
    try:
        result = run_lint(args.root, args.baseline)
    except (BaselineError, SyntaxError, OSError, LookupError) as e:
        print(f"dpslint: error: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump({
            "findings": [f.to_json() for f in result["live"]],
            "baselined": [f.to_json() for f in result["baselined"]],
            "suppressed": [f.to_json() for f in result["suppressed"]],
            "stale_baseline": result["stale_baseline"],
            "files_scanned": result["files_scanned"],
            "runtime_s": result["runtime_s"],
            "clean": result["exit_code"] == 0,
        }, sys.stdout, indent=2)
        print()
    else:
        _render_human(result, sys.stdout)
    return result["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
