"""Hot-path allocation guard (rule ``hot-path-alloc``).

Generalizes the zero-copy copy-count test (``tests/test_wire_zero_copy.py``
pins the wire codec's at-most-one-copy invariant at runtime) into a static
rule over every function marked ``# dpslint: hot-path`` — the wire codec,
store push/fetch, replica serve, and NM-reply cache paths, where a stray
whole-tensor copy silently doubles the host-side cost THC identifies as
the post-codec bottleneck.

Inside a marked function (marker on the ``def`` line or the line above),
these calls are findings:

- ``np.copy(...)`` and ``<x>.tobytes()`` — always a full copy;
- ``<x>.astype(...)`` without ``copy=False`` — numpy copies by default
  even for a same-dtype cast;
- ``np.array(...)`` — copies existing arrays; ``np.asarray`` /
  ``np.frombuffer`` are the no-copy spellings.

The marker is opt-in per function: the rule is a contract for paths whose
budget is "one copy per tensor or less", not a global style ban.

The ``# dpslint: hot-path device`` variant (rule ``hot-path-sync``) marks
DEVICE-resident hot paths — jit/Pallas codec kernels (ops/device_codec.py,
ops/pallas/quantize.py) whose whole point is keeping tensors on the
accelerator until the final packed-bytes pull. There the numpy allocation
rules don't apply (``jnp`` ``.astype`` never copies on device), and the
findings are host materializations instead:

- ``jax.device_get(...)`` — a blocking device->host transfer;
- ``np.asarray(...)`` / ``np.array(...)`` — silently pull a device array
  to the host (and block on it) to build the numpy view.
"""

from __future__ import annotations

import ast

from .core import HOT_PATH_DEVICE_RE, HOT_PATH_RE, Finding, SourceFile

_NP_NAMES = {"np", "numpy"}
_JAX_NAMES = {"jax"}


def _marker(src: SourceFile, node: ast.FunctionDef) -> str:
    deco_top = min((d.lineno for d in node.decorator_list),
                   default=node.lineno)
    text = src.comment_at(node.lineno) + "\n" \
        + src.own_line_comment(deco_top - 1)
    if HOT_PATH_DEVICE_RE.search(text):
        return "device"
    if HOT_PATH_RE.search(text):
        return "host"
    return ""


def _violation(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and f.value.id in _NP_NAMES:
            if f.attr == "copy":
                return "np.copy() buffers a full copy"
            if f.attr == "array":
                return ("np.array() copies existing arrays — use "
                        "np.asarray/np.frombuffer")
        if f.attr == "tobytes":
            return ".tobytes() copies the whole buffer"
        if f.attr == "astype":
            for kw in node.keywords:
                if kw.arg == "copy" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return None
            return (".astype() without copy=False copies even on a "
                    "same-dtype cast")
    return None


def _device_violation(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in _JAX_NAMES and f.attr == "device_get":
            return ("jax.device_get() blocks on a device->host transfer "
                    "inside a device-resident path")
        if f.value.id in _NP_NAMES and f.attr in ("asarray", "array"):
            return (f"np.{f.attr}() on a device array pulls it to the "
                    "host (and blocks) to build the numpy view")
    return None


def run(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        hot: list[tuple[str, str, ast.FunctionDef]] = []
        parents = {src.tree: None}

        def qualname(fn: ast.AST) -> str:
            parts = []
            cur = fn
            while cur is not None and not isinstance(cur, ast.Module):
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    parts.append(cur.name)
                cur = parents.get(cur)
            return ".".join(reversed(parts))

        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                kind = _marker(src, node)
                if kind:
                    hot.append((kind, qualname(node), node))
        for kind, qual, fn in hot:
            rule = "hot-path-sync" if kind == "device" \
                else "hot-path-alloc"
            check = _device_violation if kind == "device" else _violation
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                why = check(sub)
                if why is not None:
                    findings.append(Finding(
                        rule, src.rel, sub.lineno,
                        f"{qual}", f"hot-path {qual}(): {why}"))
    return findings
