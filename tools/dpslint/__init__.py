"""dpslint: framework-aware static analysis for the DPS package.

Five stdlib-``ast`` passes over the whole package (no jax import, no
third-party deps — runs in the offline build environment inside
tier-1): lock discipline, hot-path allocations, capability gating, JAX
side-effect pitfalls, and catalog<->doc drift. See
docs/STATIC_ANALYSIS.md for the rule catalog and suppression syntax.

Run as ``python -m tools.dpslint`` or ``cli lint``.
"""

from .core import RULE_CATALOG, Finding  # noqa: F401  (public API)
from .cli import main, run_lint  # noqa: F401
