"""``python -m tools.dpslint`` entry point."""

import sys

from .cli import main

sys.exit(main())
