"""Capability-gating pass (rules ``meta-key``, ``cap-gate``).

The wire's envelope meta is the negotiation surface: every optional
feature (delta fetch, trace context, health reports, compressed-domain
scales, directives, shard maps) rides it, and the degradation discipline
— either peer missing a capability degrades to the legacy wire — only
holds if every key is (a) cataloged and (b) read behind its gate.

:data:`META_KEY_CATALOG` pins the full set of envelope-meta keys READ
anywhere in ``comms/`` (docs/WIRE_PROTOCOL.md carries the same table,
pinned both directions by the doc-drift pass). Each key maps to a tuple
of *gate tokens*: identifiers the enclosing function must reference
(as a name, attribute, or string) for the read to count as gated. An
empty tuple means the key is part of the core protocol (registration
negotiation, push/fetch core fields) and needs no gate.

Rules:

- ``meta-key``: a read of an uncataloged key on an envelope receiver —
  a new wire field skipped the catalog (and therefore the doc table and
  the gating review).
- ``cap-gate``: a read of a gated key in a function that references none
  of its gate tokens — the degradation discipline was skipped.

Only READS count: ``meta.get("k")`` calls and ``meta["k"]`` subscript
loads on receivers named ``meta`` / ``rmeta`` / ``reply`` /
``reply_meta``. Stores (``meta["k"] = v``) are the SEND side — building
an envelope is how capabilities are exercised, not where gating is
checked — and ``"k" in meta`` membership tests are themselves the
presence-gate idiom. ``comms/wire.py`` is excluded: its ``meta`` is the
per-tensor frame table (dtype/shape/name), a different namespace below
the envelope.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

#: Envelope-meta key -> gate tokens (ANY one referenced in the enclosing
#: function satisfies the gate; empty tuple = ungated core field).
#: docs/WIRE_PROTOCOL.md's "Envelope meta keys" table is pinned to the
#: KEYS of this dict in both directions by the doc-drift pass.
META_KEY_CATALOG: dict[str, tuple[str, ...]] = {
    # -- registration negotiation (server -> client, register reply) ----
    "worker_id": (),
    "total_workers": (),
    "push_codec": (),
    "fetch_codec": (),
    "delta_fetch": (),
    "trace_context": (),
    "health_report": (),
    "compressed_domain": (),
    "elastic": (),
    "mode": (),
    "learning_rate": (),
    "staleness_bound": (),
    # -- client -> server request fields --------------------------------
    "worker_name": (),
    "capabilities": (),
    "fetched_step": (),
    "push_token": (),
    "have_step": (),
    "have_qscales": (),
    "have_shard_map": (),
    "directives_ack": (),
    # piggybacked worker health report: the server only ingests it when
    # it runs a cluster monitor (fetch/heartbeat path) or when nonfinite
    # rejection is on (push path).
    "health": ("monitor", "reject_nonfinite"),
    # replica announce riding fetch meta: only meaningful on a sharded
    # primary (ShardingState present) or an interior fan-out-tree node
    # (which ingests child announces tier-tagged; docs/SHARDING.md
    # "Fan-out trees").
    "replica": ("sharding", "tier"),
    # fan-out tree fields (docs/SHARDING.md "Fan-out trees"): parent /
    # tier ride the replica announce (and the replica's re-packed reply
    # head); a node only acts on them when it tracks tree position.
    "parent": ("sharding", "tier"),
    "tier": ("replica", "sharding"),
    # topology refresh handshake: same delta idiom as have_shard_map —
    # the request side is an ungated core field, the reply attachment
    # is only adopted by a subscribing replica.
    "have_topology": (),
    "topology": ("replica",),
    # trace context on the envelope: attached/read only when tracing is
    # enabled end to end.
    "trace": ("trace_enabled", "supports_trace_context"),
    # -- reply piggyback (server -> client, fetch/push reply meta) ------
    "accepted": (),
    "not_modified": (),
    # global_step on a fetch reply is only trustworthy after the
    # not_modified branch was considered — a NOT_MODIFIED reply carries
    # no payload and the step echoes have_step.
    "global_step": ("not_modified",),
    "active_workers": (),
    # directive stream: the client must have advertised (and the server
    # echoed) the capability before adopting directives off reply meta.
    "directives": ("supports_directives",),
    # shared-scale table: compressed-domain capability gates adoption.
    "qscales": ("supports_compressed_domain",),
    "qscale_step": ("supports_compressed_domain",),
    # shard map: presence IS the capability (docs/SHARDING.md) — an
    # unsharded server never attaches one.
    "shard_map": (),
    # CRC trailer capability (docs/WIRE_PROTOCOL.md "Checksum trailer"):
    # the server advertises that it verifies push-frame checksums; only
    # then does the client attach the FLAG_CRC trailer (a legacy server
    # would mistake it for buffer slack).
    "checksum": (),
    # -- live migration (admin plane + push-race surfacing) --------------
    # Reshard request fields: only a shard primary (ShardingState
    # present) serves the admin plane (docs/SHARDING.md "Migration
    # protocol").
    "op": ("sharding",),
    "slot_lo": ("sharding",),
    "slot_hi": ("sharding",),
    "journal": ("sharding",),
    "ranges": ("sharding",),
    "map_version": ("sharding",),
    # The coordinator's full migration plan (id, range, target
    # partition, lease TTL) — one nested object, journaled per phase on
    # each primary (docs/ROBUSTNESS.md "Migration failure matrix").
    "migration": ("sharding",),
    # Reshard reply fields are read only by the coordinator (cli.py,
    # outside comms/): export_step / exported / adopted / journal_loaded
    # / dropped never appear as comms-side reads.
    # A push reply's disowned list only means something to a client that
    # holds a shard map to re-route against.
    "disowned": ("shard_map",),
    # -- serve tier (canary-gated inference; docs/SHARDING.md) ----------
    "infer": ("canary",),
    "quality": ("canary",),
    "arm": ("canary",),
    "serving_step": ("canary",),
    # -- multi-job tenancy (docs/TENANCY.md) ----------------------------
    # A request's job id is only routed when the server actually runs a
    # JobManager; a job-less server treats every envelope as the default
    # job, so reads must sit behind the jobs handle.
    "job": ("jobs",),
    # SubmitJob admin op payload / drain marker: same gate — only a
    # tenancy-enabled primary serves the job admin plane.
    "job_spec": ("jobs",),
    "drain_job": ("jobs",),
    # Register-reply echo: the server advertises tenancy support (and
    # the adopted job name) so legacy clients keep ignoring it — an
    # ungated core field like the other negotiation echoes.
    "jobs": (),
}

#: Variable names treated as envelope-meta receivers in comms/.
_RECEIVERS = {"meta", "rmeta", "reply", "reply_meta"}


def _read_sites(tree: ast.AST) -> list[tuple[str, int, ast.AST]]:
    """(key, line, node) for every envelope-meta READ in the module."""
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _RECEIVERS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                sites.append((node.args[0].value, node.lineno, node))
        elif isinstance(node, ast.Subscript):
            if (isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in _RECEIVERS
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                sites.append((node.slice.value, node.lineno, node))
    return sites


def _enclosing_functions(tree: ast.AST) -> dict[ast.AST, ast.FunctionDef]:
    """node -> nearest enclosing function def, for every node."""
    owner: dict[ast.AST, ast.FunctionDef] = {}

    def walk(node: ast.AST, fn) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node
        for child in ast.iter_child_nodes(node):
            owner[child] = fn
            walk(child, fn)

    walk(tree, None)
    return owner


def _references(fn: ast.AST, tokens: tuple[str, ...]) -> bool:
    """Does ``fn`` mention any gate token as a name/attribute/string?"""
    want = set(tokens)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in want:
            return True
        if isinstance(node, ast.Attribute) and node.attr in want:
            return True
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str) and node.value in want:
            return True
    return False


def run(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        parts = src.rel.split("/")
        if "comms" not in parts or parts[-1] == "wire.py":
            continue
        owner = _enclosing_functions(src.tree)
        for key, line, node in _read_sites(src.tree):
            fn = owner.get(node)
            where = fn.name if fn is not None else "<module>"
            if key not in META_KEY_CATALOG:
                findings.append(Finding(
                    "meta-key", src.rel, line, f"{where}:{key}",
                    f"envelope-meta key {key!r} read in {where}() is not "
                    f"in META_KEY_CATALOG — catalog it (with its gate) "
                    f"before putting it on the wire"))
                continue
            gates = META_KEY_CATALOG[key]
            if gates and (fn is None or not _references(fn, gates)):
                findings.append(Finding(
                    "cap-gate", src.rel, line, f"{where}:{key}",
                    f"gated envelope-meta key {key!r} read in {where}() "
                    f"which references none of its gate tokens "
                    f"{sorted(gates)} — the capability degradation "
                    f"discipline was skipped"))
    return findings
