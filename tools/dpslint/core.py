"""dpslint core: finding model, rule catalog, suppressions, baseline.

The analyzer is stdlib-only (``ast`` + ``tokenize``): it must run in the
offline build environment where neither ruff nor jax is guaranteed, and
it must stay cheap enough to sit inside tier-1. Every rule lives in
:data:`RULE_CATALOG` — the single source of truth docs/STATIC_ANALYSIS.md
is pinned against (both directions, by the ``doc-drift`` pass itself).

Suppression model, two tiers:

- inline: ``# dpslint: ignore[rule]`` (comma list allowed) on the finding
  line silences exactly those rules there — for accepted one-off
  exceptions whose justification fits in the surrounding code comment;
- baseline: ``tools/dpslint/baseline.json`` entries match findings by
  ``(rule, file, symbol)`` — line numbers drift, symbols don't — and every
  entry MUST carry a non-empty ``justification`` string: a baseline is a
  reviewed debt register, not a mute button. Stale entries (matching
  nothing) are reported so the register can only shrink.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path

#: rule id -> (severity, one-line rationale). docs/STATIC_ANALYSIS.md's
#: rule table is pinned to this dict in both directions by the doc-drift
#: pass (and tests/test_docs_drift.py).
RULE_CATALOG = {
    "lock-guard": (
        "error", "a field declared `# guarded by: self._<lock>` is read "
                 "or written outside a `with` block on that lock"),
    "thread-shared": (
        "warning", "an attribute is written from a threading.Thread/Timer "
                   "target and touched by another method with no declared "
                   "guard — an undeclared cross-thread contract"),
    "hot-path-alloc": (
        "error", "a `# dpslint: hot-path` function calls np.copy / "
                 ".tobytes() / .astype without copy=False / np.array — "
                 "allocations the zero-copy wire discipline forbids"),
    "hot-path-sync": (
        "error", "a `# dpslint: hot-path device` function calls "
                 "jax.device_get / np.asarray / np.array — a host "
                 "materialization that stalls the device pipeline the "
                 "function exists to keep full"),
    "meta-key": (
        "error", "an envelope-meta key read in comms/ is missing from "
                 "META_KEY_CATALOG — new wire fields must be cataloged "
                 "with their capability gate"),
    "cap-gate": (
        "error", "a capability-gated envelope-meta key is read in a "
                 "function that never references its gate — the "
                 "degradation discipline was skipped"),
    "jax-side-effect": (
        "error", "a side-effecting call (print / time.* / metric "
                 "inc/observe / flight-recorder write) inside a "
                 "jit/pjit/shard_map-compiled function runs at trace "
                 "time, not per step"),
    "doc-drift": (
        "error", "a pinned catalog (metrics, spans, health rules, codecs, "
                 "directives, actions, shard-map fields, lint rules) "
                 "disagrees with its documentation"),
}

#: Annotation comment declaring a field's guard:  # guarded by: self._lock
GUARD_RE = re.compile(r"#\s*guarded by:\s*(?:self\.)?(\w+)")

#: Hot-path marker comment (same line as the def or the line above).
HOT_PATH_RE = re.compile(r"#\s*dpslint:\s*hot-path\b")

#: Device-resident hot-path marker: the function body is jnp/lax device
#: code (ops/device_codec.py, ops/pallas/quantize.py wire codec). The
#: numpy allocation rules don't apply (jnp .astype never copies on
#: device); what must never appear is a host materialization.
HOT_PATH_DEVICE_RE = re.compile(r"#\s*dpslint:\s*hot-path\s+device\b")

#: Inline suppression:  # dpslint: ignore[rule-a, rule-b]
IGNORE_RE = re.compile(r"#\s*dpslint:\s*ignore\[([a-z\-,\s]+)\]")


@dataclass
class Finding:
    """One diagnostic: rule id + location + a stable baseline anchor."""

    rule: str
    file: str      # repo-relative, '/'-separated
    line: int
    symbol: str    # e.g. 'Class.method.attr' — stable across line drift
    message: str

    @property
    def severity(self) -> str:
        return RULE_CATALOG[self.rule][0]

    def key(self) -> tuple:
        return (self.rule, self.file, self.symbol)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.rule}] "
                f"{self.severity}: {self.message}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "file": self.file, "line": self.line,
                "symbol": self.symbol, "message": self.message}


class SourceFile:
    """One parsed module: AST + per-line comment map (tokenize, so
    string literals containing '#' can't fake an annotation)."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.tree = ast.parse(self.text, filename=str(path))
        self.lines = self.text.splitlines()
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover - parse succeeded
            pass

    def comment_at(self, line: int) -> str:
        return self.comments.get(line, "")

    def own_line_comment(self, line: int) -> str:
        """The comment at ``line`` ONLY if the line holds nothing else.
        Annotations that accept a comment "on the line above" must use
        this: a trailing comment up there belongs to THAT line's code
        (e.g. a guard annotation on the previous field's assignment),
        not to the statement below."""
        if 1 <= line <= len(self.lines) \
                and self.lines[line - 1].lstrip().startswith("#"):
            return self.comments.get(line, "")
        return ""

    def suppressed_rules(self, line: int) -> set[str]:
        m = IGNORE_RE.search(self.comment_at(line))
        if not m:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}


def load_sources(pkg_dir: Path, root: Path) -> list[SourceFile]:
    out = []
    for path in sorted(pkg_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        out.append(SourceFile(path, root))
    return out


# -- baseline ----------------------------------------------------------------

class BaselineError(ValueError):
    """The baseline file itself is malformed (treated as exit code 2:
    a broken debt register must fail loudly, not silently match)."""


def load_baseline(path: Path) -> list[dict]:
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    if not isinstance(data, list):
        raise BaselineError(f"{path}: baseline must be a JSON list")
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise BaselineError(f"{path}: entry {i} is not an object")
        for field in ("rule", "file", "symbol"):
            if not isinstance(entry.get(field), str) or not entry[field]:
                raise BaselineError(
                    f"{path}: entry {i} missing {field!r}")
        if entry["rule"] not in RULE_CATALOG:
            raise BaselineError(
                f"{path}: entry {i} names unknown rule "
                f"{entry['rule']!r}")
        just = entry.get("justification")
        if not isinstance(just, str) or len(just.strip()) < 10:
            raise BaselineError(
                f"{path}: entry {i} ({entry['rule']} {entry['symbol']}) "
                f"needs a real justification string (>= 10 chars) — a "
                f"baseline is a reviewed register, not a mute button")
    return data


def apply_baseline(findings: list[Finding], baseline: list[dict]
                   ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """-> (live, baselined, stale_entries). Matching is by
    (rule, file, symbol) so findings survive line drift."""
    index = {(e["rule"], e["file"], e["symbol"]): e for e in baseline}
    live, matched = [], []
    used = set()
    for f in findings:
        if f.key() in index:
            matched.append(f)
            used.add(f.key())
        else:
            live.append(f)
    stale = [e for k, e in index.items() if k not in used]
    return live, matched, stale


def split_suppressed(findings: list[Finding], sources: list[SourceFile]
                     ) -> tuple[list[Finding], list[Finding]]:
    """Drop findings whose line carries a matching inline ignore."""
    by_rel = {s.rel: s for s in sources}
    live, suppressed = [], []
    for f in findings:
        src = by_rel.get(f.file)
        if src is not None and f.rule in src.suppressed_rules(f.line):
            suppressed.append(f)
        else:
            live.append(f)
    return live, suppressed
