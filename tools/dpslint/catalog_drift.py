"""Catalog-drift pass (rule ``doc-drift``).

One engine behind every code<->doc pin the repo accumulated
(tests/test_docs_drift.py now delegates here): metric names, span
catalog + call sites, health rules with severities, wire codecs,
directives, remediation actions + the default policy table, shard-map
schema fields — plus the two catalogs this tool itself introduces
(dpslint's RULE_CATALOG vs docs/STATIC_ANALYSIS.md, META_KEY_CATALOG vs
docs/WIRE_PROTOCOL.md's envelope-meta table).

Catalogs are extracted from the source FILES via ``ast`` — never by
importing the package — so the pass stays jax-free and runs in the
offline build environment at lint speed. Every pinned catalog is a pure
literal; ``tests/test_dpslint.py`` would fail loudly (extraction error)
if one stopped being extractable.

Each named check is independently callable (``CHECKS[name](ctx)``) so
the tier-1 drift tests can keep their one-failure-per-contract
granularity on top of the shared engine.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .capability import META_KEY_CATALOG
from .core import RULE_CATALOG, Finding, SourceFile

_PKG = "distributed_parameter_server_for_ml_training_tpu"

# Regexes shared with the legacy drift tests (same semantics; see
# tests/test_docs_drift.py for the rationale comments).
REG_RE = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*"(dps_[a-z0-9_]+)"', re.S)
DOC_METRIC_RE = re.compile(r"dps_[a-z0-9_]+")
DOC_SPAN_RE = re.compile(
    r"`((?:worker|rpc|store|pipeline|trainer)\.[a-z_]+)`")
CALLSITE_RE = re.compile(r'trace_span\(\s*"([a-z_.]+)"', re.S)
DOC_RULE_RE = re.compile(
    r"\|\s*`([a-z_]+)`\s*\|\s*(critical|warning|info)\s*\|")
DOC_NAME_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_\-]+)`\s*\|", re.M)
#: dpslint rule-table row in docs/STATIC_ANALYSIS.md: | `id` | severity |
DOC_LINT_RULE_RE = re.compile(
    r"\|\s*`([a-z\-]+)`\s*\|\s*(error|warning)\s*\|")

#: The sharding metric families pinned as an explicit contract on top of
#: the catch-all metric diff (ISSUE 9).
SHARDING_METRIC_FAMILIES = frozenset({
    "dps_shard_id", "dps_shard_count", "dps_shard_map_version",
    "dps_shard_replicas", "dps_replica_lag_steps",
    "dps_replica_lag_seconds"})


class DriftContext:
    """Lazily-loaded repo state shared by the checks."""

    def __init__(self, root: Path, sources: list[SourceFile]):
        self.root = Path(root)
        self.sources = sources
        self._docs: dict[str, str] = {}

    def doc(self, rel: str) -> str:
        if rel not in self._docs:
            self._docs[rel] = (self.root / rel).read_text()
        return self._docs[rel]

    def doc_line(self, rel: str, needle: str) -> int:
        """1-based line of the first occurrence (1 if absent)."""
        text = self.doc(rel)
        pos = text.find(needle)
        return 1 if pos < 0 else text.count("\n", 0, pos) + 1

    def catalog_node(self, rel: str, name: str) -> ast.AST:
        """The value node of module-level ``NAME = <literal>``."""
        path = self.root / rel
        for node in ast.parse(path.read_text()).body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return node.value
        raise LookupError(f"{rel} has no module-level {name}")

    def catalog(self, rel: str, name: str):
        """literal_eval of a catalog assignment (pure-literal contract)."""
        return ast.literal_eval(self.catalog_node(rel, name))


def _section(text: str, heading: str, stop: str = "\n#") -> str | None:
    """Doc text from ``heading`` to the next ``stop`` marker, or None.
    ``stop`` defaults to ANY next heading; pass ``"\\n## "`` to keep a
    section's own sub-headings inside it (the codec table lives under a
    ``###`` inside its ``##`` section)."""
    if heading not in text:
        return None
    rest = text.split(heading, 1)[1]
    return rest.split(stop, 1)[0]


def _diff(ctx: DriftContext, check: str, code: set, doc: set,
          doc_rel: str, what: str, anchor: str = "") -> list[Finding]:
    """Symmetric-difference findings for a both-directions pin."""
    line = ctx.doc_line(doc_rel, anchor) if anchor else 1
    out = []
    for name in sorted(code - doc):
        out.append(Finding(
            "doc-drift", doc_rel, line, f"{check}:{name}",
            f"{what} {name!r} exists in code but is absent from "
            f"{doc_rel}"))
    for name in sorted(doc - code):
        out.append(Finding(
            "doc-drift", doc_rel, line, f"{check}:{name}",
            f"{doc_rel} documents {what} {name!r} which no longer exists "
            f"in code (renamed or removed?)"))
    return out


# -- checks ------------------------------------------------------------------

def check_metrics(ctx: DriftContext) -> list[Finding]:
    registered = {m for s in ctx.sources for m in REG_RE.findall(s.text)}
    if not registered:
        return [Finding("doc-drift", f"{_PKG}", 1, "metrics:<none>",
                        "no dps_* instrument registrations found — the "
                        "registration regex rotted")]
    documented = set(DOC_METRIC_RE.findall(ctx.doc("docs/OBSERVABILITY.md")))
    return _diff(ctx, "metrics", registered, documented,
                 "docs/OBSERVABILITY.md", "metric")


def check_spans(ctx: DriftContext) -> list[Finding]:
    catalog = set(ctx.catalog(f"{_PKG}/telemetry/trace.py", "SPAN_CATALOG"))
    doc = {n for n in DOC_SPAN_RE.findall(ctx.doc("docs/OBSERVABILITY.md"))
           if not n.endswith(".py")}
    return _diff(ctx, "spans", catalog, doc, "docs/OBSERVABILITY.md",
                 "span")


def check_span_call_sites(ctx: DriftContext) -> list[Finding]:
    catalog = set(ctx.catalog(f"{_PKG}/telemetry/trace.py", "SPAN_CATALOG"))
    out = []
    for src in ctx.sources:
        for m in CALLSITE_RE.finditer(src.text):
            name = m.group(1)
            if name not in catalog:
                line = src.text.count("\n", 0, m.start()) + 1
                out.append(Finding(
                    "doc-drift", src.rel, line, f"span-site:{name}",
                    f"trace_span({name!r}) uses a name missing from "
                    f"SPAN_CATALOG (add it there AND to "
                    f"docs/OBSERVABILITY.md)"))
    return out


def check_health_rules(ctx: DriftContext) -> list[Finding]:
    catalog = {r: sev for r, (sev, _) in
               ctx.catalog(f"{_PKG}/telemetry/health.py",
                           "RULE_CATALOG").items()}
    doc_rows = dict(DOC_RULE_RE.findall(ctx.doc("docs/OBSERVABILITY.md")))
    out = _diff(ctx, "health-rule", set(catalog), set(doc_rows),
                "docs/OBSERVABILITY.md", "health rule")
    for rule in sorted(set(catalog) & set(doc_rows)):
        if catalog[rule] != doc_rows[rule]:
            out.append(Finding(
                "doc-drift", "docs/OBSERVABILITY.md",
                ctx.doc_line("docs/OBSERVABILITY.md", f"`{rule}`"),
                f"health-rule-severity:{rule}",
                f"health rule {rule!r} severity disagrees: code says "
                f"{catalog[rule]!r}, doc says {doc_rows[rule]!r}"))
    return out


def check_codecs(ctx: DriftContext) -> list[Finding]:
    catalog = set(ctx.catalog(f"{_PKG}/ops/compression.py",
                              "CODEC_CATALOG"))
    section = _section(ctx.doc("docs/WIRE_PROTOCOL.md"), "## Push codecs",
                       stop="\n## ")
    if section is None:
        return [Finding("doc-drift", "docs/WIRE_PROTOCOL.md", 1,
                        "codecs:<section>",
                        "'## Push codecs' section heading rotted")]
    doc = set(DOC_NAME_ROW_RE.findall(section))
    return _diff(ctx, "codec", catalog, doc, "docs/WIRE_PROTOCOL.md",
                 "codec", "## Push codecs")


def _table_check(ctx: DriftContext, check: str, src_rel: str,
                 catalog_name: str, doc_rel: str, heading: str,
                 what: str) -> list[Finding]:
    catalog = set(ctx.catalog(src_rel, catalog_name))
    section = _section(ctx.doc(doc_rel), heading)
    if section is None:
        return [Finding("doc-drift", doc_rel, 1, f"{check}:<section>",
                        f"{heading!r} section heading rotted in "
                        f"{doc_rel}")]
    doc = set(DOC_NAME_ROW_RE.findall(section))
    return _diff(ctx, check, catalog, doc, doc_rel, what, heading)


def check_directives(ctx: DriftContext) -> list[Finding]:
    return _table_check(ctx, "directive", f"{_PKG}/comms/service.py",
                        "DIRECTIVE_CATALOG", "docs/ROBUSTNESS.md",
                        "#### Directive catalog", "directive")


def check_actions(ctx: DriftContext) -> list[Finding]:
    return _table_check(ctx, "action", f"{_PKG}/telemetry/remediation.py",
                        "ACTION_CATALOG", "docs/ROBUSTNESS.md",
                        "#### Action catalog", "remediation action")


def check_policy_table(ctx: DriftContext) -> list[Finding]:
    health = set(ctx.catalog(f"{_PKG}/telemetry/health.py",
                             "RULE_CATALOG"))
    actions = set(ctx.catalog(f"{_PKG}/telemetry/remediation.py",
                              "ACTION_CATALOG"))
    code_policy = {r: tuple(a) for r, a in
                   ctx.catalog(f"{_PKG}/telemetry/remediation.py",
                               "DEFAULT_POLICY_RULES").items()}
    heading = "#### Policy table (defaults)"
    section = _section(ctx.doc("docs/ROBUSTNESS.md"), heading)
    if section is None:
        return [Finding("doc-drift", "docs/ROBUSTNESS.md", 1,
                        "policy:<section>",
                        f"{heading!r} section heading rotted")]
    line = ctx.doc_line("docs/ROBUSTNESS.md", heading)
    doc_policy = {}
    for rule, cell in re.findall(r"^\|\s*`([a-z_]+)`\s*\|\s*(.+?)\s*\|",
                                 section, re.M):
        doc_policy[rule] = tuple(re.findall(r"`([a-z_]+)`", cell))
    out = []
    if not doc_policy:
        return [Finding("doc-drift", "docs/ROBUSTNESS.md", line,
                        "policy:<rows>", "policy table has no rows — "
                        "format rotted")]
    for rule, acts in doc_policy.items():
        if rule not in health:
            out.append(Finding(
                "doc-drift", "docs/ROBUSTNESS.md", line,
                f"policy:{rule}",
                f"policy table maps unknown health rule {rule!r}"))
        for a in acts:
            if a not in actions:
                out.append(Finding(
                    "doc-drift", "docs/ROBUSTNESS.md", line,
                    f"policy:{rule}:{a}",
                    f"policy table maps {rule!r} to unknown action "
                    f"{a!r}"))
    if doc_policy != code_policy:
        for rule in sorted(set(doc_policy) ^ set(code_policy)) + sorted(
                r for r in set(doc_policy) & set(code_policy)
                if doc_policy[r] != code_policy[r]):
            out.append(Finding(
                "doc-drift", "docs/ROBUSTNESS.md", line,
                f"policy-row:{rule}",
                f"policy row {rule!r} disagrees with "
                f"DEFAULT_POLICY_RULES: doc="
                f"{doc_policy.get(rule)} code={code_policy.get(rule)}"))
    return out


def check_shard_map_fields(ctx: DriftContext) -> list[Finding]:
    return _table_check(ctx, "shard-field", f"{_PKG}/ps/sharding.py",
                        "SHARD_MAP_FIELDS", "docs/SHARDING.md",
                        "### Shard map schema", "shard-map field")


def check_sharding_metric_families(ctx: DriftContext) -> list[Finding]:
    registered = {m for s in ctx.sources for m in REG_RE.findall(s.text)}
    documented = set(DOC_METRIC_RE.findall(ctx.doc("docs/OBSERVABILITY.md")))
    out = []
    for name in sorted(SHARDING_METRIC_FAMILIES - registered):
        out.append(Finding(
            "doc-drift", f"{_PKG}/ps/sharding.py", 1,
            f"shard-metric:{name}",
            f"sharding metric family {name!r} is no longer registered"))
    for name in sorted(SHARDING_METRIC_FAMILIES - documented):
        out.append(Finding(
            "doc-drift", "docs/OBSERVABILITY.md", 1,
            f"shard-metric-doc:{name}",
            f"sharding metric family {name!r} missing from "
            f"docs/OBSERVABILITY.md"))
    return out


def check_lint_rules(ctx: DriftContext) -> list[Finding]:
    """dpslint's own catalog, same discipline: docs/STATIC_ANALYSIS.md's
    rule table pinned to core.RULE_CATALOG in both directions, with
    severities."""
    catalog = {r: sev for r, (sev, _) in RULE_CATALOG.items()}
    doc_rows = dict(DOC_LINT_RULE_RE.findall(
        ctx.doc("docs/STATIC_ANALYSIS.md")))
    out = _diff(ctx, "lint-rule", set(catalog), set(doc_rows),
                "docs/STATIC_ANALYSIS.md", "lint rule")
    for rule in sorted(set(catalog) & set(doc_rows)):
        if catalog[rule] != doc_rows[rule]:
            out.append(Finding(
                "doc-drift", "docs/STATIC_ANALYSIS.md",
                ctx.doc_line("docs/STATIC_ANALYSIS.md", f"`{rule}`"),
                f"lint-rule-severity:{rule}",
                f"lint rule {rule!r} severity disagrees: code says "
                f"{catalog[rule]!r}, doc says {doc_rows[rule]!r}"))
    return out


def check_op_classes(ctx: DriftContext) -> list[Finding]:
    return _table_check(ctx, "op-class",
                        f"{_PKG}/analysis/device_profile.py",
                        "OP_CLASSES", "docs/OBSERVABILITY.md",
                        "#### Op classes", "profiler op class")


def check_job_spec_fields(ctx: DriftContext) -> list[Finding]:
    return _table_check(ctx, "job-spec-field", f"{_PKG}/ps/tenancy.py",
                        "JOB_SPEC_FIELDS", "docs/TENANCY.md",
                        "### Job spec fields", "job spec field")


def check_fleet_rollup_fields(ctx: DriftContext) -> list[Finding]:
    """FLEET_ROLLUP_FIELDS pinned to docs/OBSERVABILITY.md's rollup-
    semantics table — a ``/fleet`` rollup field cannot appear without
    documented merge semantics, or stay documented after removal."""
    return _table_check(ctx, "fleet-rollup-field",
                        f"{_PKG}/telemetry/fleet.py",
                        "FLEET_ROLLUP_FIELDS", "docs/OBSERVABILITY.md",
                        "### Rollup semantics", "fleet rollup field")


def check_event_catalog(ctx: DriftContext) -> list[Finding]:
    """EVENT_CATALOG (telemetry/journal.py) pinned to the
    docs/OBSERVABILITY.md event-catalog table — a journal record type
    cannot exist without documented semantics (postmortems are read by
    humans who were not there), or stay documented after removal."""
    return _table_check(ctx, "journal-event",
                        f"{_PKG}/telemetry/journal.py",
                        "EVENT_CATALOG", "docs/OBSERVABILITY.md",
                        "### Event catalog", "journal event type")


def check_incident_manifest(ctx: DriftContext) -> list[Finding]:
    """MANIFEST_FIELDS (telemetry/incidents.py) pinned to the
    docs/OBSERVABILITY.md incident-manifest table."""
    return _table_check(ctx, "incident-manifest",
                        f"{_PKG}/telemetry/incidents.py",
                        "MANIFEST_FIELDS", "docs/OBSERVABILITY.md",
                        "### Incident manifest", "incident manifest field")


def check_meta_keys(ctx: DriftContext) -> list[Finding]:
    """META_KEY_CATALOG pinned to docs/WIRE_PROTOCOL.md's envelope-meta
    table — a wire field cannot be cataloged without being documented,
    or documented without existing."""
    heading = "### Envelope meta keys"
    section = _section(ctx.doc("docs/WIRE_PROTOCOL.md"), heading)
    if section is None:
        return [Finding("doc-drift", "docs/WIRE_PROTOCOL.md", 1,
                        "meta-key-doc:<section>",
                        f"{heading!r} section heading rotted in "
                        f"docs/WIRE_PROTOCOL.md")]
    doc = set(DOC_NAME_ROW_RE.findall(section))
    return _diff(ctx, "meta-key-doc", set(META_KEY_CATALOG), doc,
                 "docs/WIRE_PROTOCOL.md", "envelope-meta key", heading)


def check_goodput_categories(ctx: DriftContext) -> list[Finding]:
    """GOODPUT_CATEGORIES (telemetry/goodput.py) pinned to the
    docs/OBSERVABILITY.md goodput-categories table — a wall-clock
    category cannot be charged without documented semantics (the ledger
    is read by humans attributing badput), or stay documented after
    removal."""
    return _table_check(ctx, "goodput-category",
                        f"{_PKG}/telemetry/goodput.py",
                        "GOODPUT_CATEGORIES", "docs/OBSERVABILITY.md",
                        "### Goodput categories", "goodput category")


def check_profile_record(ctx: DriftContext) -> list[Finding]:
    """PROFILE_RECORD_FIELDS (telemetry/proftrigger.py) pinned to the
    docs/OBSERVABILITY.md profile-ledger table — the committed
    PROFILE_*.json records are longitudinal evidence; their schema
    cannot drift undocumented."""
    return _table_check(ctx, "profile-record",
                        f"{_PKG}/telemetry/proftrigger.py",
                        "PROFILE_RECORD_FIELDS", "docs/OBSERVABILITY.md",
                        "### Profile ledger", "profile record field")


CHECKS = {
    "metrics": check_metrics,
    "spans": check_spans,
    "span-call-sites": check_span_call_sites,
    "health-rules": check_health_rules,
    "codecs": check_codecs,
    "directives": check_directives,
    "actions": check_actions,
    "policy-table": check_policy_table,
    "shard-map-fields": check_shard_map_fields,
    "sharding-metric-families": check_sharding_metric_families,
    "lint-rules": check_lint_rules,
    "op-classes": check_op_classes,
    "job-spec-fields": check_job_spec_fields,
    "meta-keys": check_meta_keys,
    "fleet-rollup-fields": check_fleet_rollup_fields,
    "event-catalog": check_event_catalog,
    "incident-manifest": check_incident_manifest,
    "goodput-categories": check_goodput_categories,
    "profile-record": check_profile_record,
}


def run(sources: list[SourceFile], root: Path) -> list[Finding]:
    ctx = DriftContext(root, sources)
    findings: list[Finding] = []
    for fn in CHECKS.values():
        findings.extend(fn(ctx))
    return findings
