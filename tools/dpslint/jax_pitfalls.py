"""JAX-pitfall pass (rule ``jax-side-effect``).

A call with Python-level side effects inside a ``jit``/``pjit``/
``shard_map``-compiled function runs ONCE at trace time, then never
again — a metrics counter bumped there records one increment per
recompile instead of one per step, a ``print`` shows tracer reprs, and
``time.*`` measures tracing, not execution. The classic symptom is a
counter that works in eager tests and silently flatlines under jit.

Detection, scoped to ``parallel/``, ``train/``, ``ops/``:

- compiled functions: decorated ``@jax.jit`` / ``@jit`` / ``@pjit`` /
  ``@partial(jax.jit, ...)`` / ``@shard_map(...)``, plus any local
  ``def f`` later passed by name to ``jax.jit(f)`` / ``pjit(f)`` /
  ``shard_map(f, ...)`` anywhere in the module;
- side effects inside them: ``print(...)``, any ``time.<attr>(...)``
  call, ``trace_span``/``get_recorder`` (flight-recorder writes),
  ``.inc(...)`` / ``.observe(...)`` method calls (registry instruments),
  and ``.set(...)`` only on ``_tm*``-named receivers (so JAX's
  functional ``x.at[i].set(v)`` never matches).

``jax.debug.print`` / ``jax.debug.callback`` / ``io_callback`` are the
sanctioned spellings and are not flagged.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

_JIT_NAMES = {"jit", "pjit"}
_WRAP_NAMES = _JIT_NAMES | {"shard_map"}
_RECORDER_CALLS = {"trace_span", "get_recorder"}
_METRIC_METHODS = {"inc", "observe"}
_SCOPE_DIRS = {"parallel", "train", "ops"}


def _tail(node: ast.AST) -> str:
    """Trailing identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_jit_decorator(deco: ast.AST) -> bool:
    if _tail(deco) in _WRAP_NAMES:            # @jax.jit / @jit / @pjit
        return True
    if isinstance(deco, ast.Call):
        if _tail(deco.func) in _WRAP_NAMES:   # @shard_map(...) / @jit(...)
            return True
        if _tail(deco.func) == "partial" and deco.args \
                and _tail(deco.args[0]) in _WRAP_NAMES:
            return True                       # @partial(jax.jit, ...)
    return False


def _wrapped_names(tree: ast.AST) -> set[str]:
    """Local function names passed BY NAME to jit/pjit/shard_map calls
    (``sharded = jax.jit(step_fn)`` / ``shard_map(step_fn, mesh, ...)``).
    """
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _tail(node.func) in _WRAP_NAMES:
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


def _violation(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "print":
        return "print() runs at trace time (use jax.debug.print)"
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and f.value.id == "time":
            return (f"time.{f.attr}() measures tracing, not execution "
                    f"(time outside the compiled function)")
        if f.attr in _METRIC_METHODS:
            return (f".{f.attr}() on a registry instrument records once "
                    f"per recompile, not per step")
        if f.attr == "set" and isinstance(f.value, ast.Name) \
                and f.value.id.startswith("_tm"):
            return ".set() on a telemetry gauge records once per recompile"
    if _tail(f) in _RECORDER_CALLS:
        return (f"{_tail(f)}() writes the flight recorder at trace time "
                f"(span durations would be tracing artifacts)")
    return None


def run(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        parts = src.rel.split("/")
        if not (set(parts[:-1]) & _SCOPE_DIRS):
            continue
        wrapped = _wrapped_names(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            jitted = node.name in wrapped or any(
                _is_jit_decorator(d) for d in node.decorator_list)
            if not jitted:
                continue
            for sub in ast.walk(node):
                # Nested defs still trace with the parent; walk them too.
                if not isinstance(sub, ast.Call):
                    continue
                why = _violation(sub)
                if why is not None:
                    findings.append(Finding(
                        "jax-side-effect", src.rel, sub.lineno,
                        f"{node.name}",
                        f"in compiled {node.name}(): {why}"))
    return findings
