"""Worker process supervisor: spawn N workers, respawn the ones that die.

The missing rung of the self-healing ladder (docs/ROBUSTNESS.md): PR 4
made a *surviving* worker ride through a server restart (session resume),
and the cluster monitor can *detect* a dead one — but nothing brought a
dead worker back. ``cli supervise`` runs this supervisor next to the
worker processes (the place a process can actually be restarted):

- spawns N ``cli worker`` children from one argv template, each with its
  own ``--worker-name`` slot;
- watches them; a child that exits 0 is done, a child that dies is
  **respawned after exponential backoff** (``backoff_initial`` doubling to
  ``backoff_max``; a child that stayed alive ``healthy_after`` seconds
  resets its slot's backoff);
- **crash-loop latch**: ``crash_loop_after`` consecutive fast deaths
  (lived < ``healthy_after``) latch the slot — a worker that can never
  come up stops burning respawns and the latch is visible in the status
  and the ``crash_loop`` outcome counter;
- each respawn (and latch) lands in
  ``dps_remediation_actions_total{action="respawn",outcome}`` — the same
  metric the server-side remediation engine uses, so the healing loop
  reads as one system across processes — plus greppable
  ``SUPERVISOR_RESPAWN`` / ``SUPERVISOR_CRASH_LOOP`` log lines.

The respawned process re-registers through the ordinary lifecycle: under
``--elastic`` + ``--worker-timeout`` it takes the dead session's freed id
slot (and therefore its data shard), and the PR 4 push-token journal
dedupes any pre-death push retry — the supervisor needs no protocol of its
own. Chaos drills use per-slot **first-spawn-only** fault specs/env
(``first_spawn_faults``/``first_spawn_env``): the injected ``push.kill``
that proves the respawn path runs once, and the replacement runs clean.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

__all__ = ["ReplicaPool", "SupervisorConfig", "WorkerSupervisor",
           "build_replica_argv", "build_worker_argv"]


@dataclass
class SupervisorConfig:
    """Respawn discipline knobs (documented in docs/ROBUSTNESS.md)."""

    respawn: bool = True
    backoff_initial: float = 1.0
    backoff_max: float = 30.0
    #: A child alive at least this long counts as having come up: its
    #: slot's backoff and crash-loop count reset.
    healthy_after: float = 5.0
    #: Consecutive fast deaths (lived < healthy_after) before the slot
    #: latches as crash-looping and stops respawning.
    crash_loop_after: int = 3
    poll_interval: float = 0.2
    #: SIGTERM -> SIGKILL grace when stopping children.
    graceful_timeout: float = 10.0


@dataclass
class _Slot:
    index: int
    proc: subprocess.Popen | None = None
    attempt: int = 0              # spawns so far (0 before the first)
    started_ts: float = 0.0
    backoff: float = 0.0
    fast_crashes: int = 0
    respawns: int = 0
    last_rc: int | None = None
    next_spawn_ts: float = 0.0    # backoff gate
    done: bool = False            # exited 0 (or latched/retired)
    latched: bool = False
    retired: bool = False         # removed by a worker_shrink


class WorkerSupervisor:
    """Spawn-and-babysit loop over N worker subprocess slots.

    ``argv_for(slot_index, attempt)`` returns ``(argv, env_overrides)``
    for one spawn — ``env_overrides`` (or None) is merged over
    ``os.environ``. The builder sees the attempt number, so chaos drills
    can inject faults into the first spawn only.
    """

    def __init__(self, argv_for, n_workers: int,
                 config: SupervisorConfig | None = None,
                 clock=time.monotonic, spawn=None,
                 log=print):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.argv_for = argv_for
        self.config = config or SupervisorConfig()
        self.clock = clock
        self.log = log
        self._spawn_fn = spawn or self._default_spawn
        self.slots = [_Slot(index=i) for i in range(n_workers)]
        # Next index for a grown slot — indices are never reused, so a
        # grown worker's ``--worker-name sup-w{slot}`` never collides
        # with a retired one's. guarded by: self._slots_lock
        self._next_slot_index = n_workers
        self._stop = threading.Event()
        # Serializes supervision passes against stop(): stop() is called
        # from signal handlers / other threads, and snapshotting the
        # children while a pass was mid-respawn let the fresh child miss
        # the snapshot — spawned a moment later, never terminated.
        self._slots_lock = threading.Lock()
        from ..telemetry import get_registry
        reg = get_registry()
        self._tm_children = reg.gauge("dps_supervisor_children")
        # The respawn half of dps_remediation_actions_total lives here —
        # the supervisor is the process that can actually restart one.
        from ..telemetry.remediation import note_action
        self._note_action = note_action

    @staticmethod
    def _default_spawn(argv, env):
        full_env = dict(os.environ)
        if env:
            full_env.update({k: str(v) for k, v in env.items()})
        return subprocess.Popen(argv, env=full_env)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Initial spawn of every slot."""
        with self._slots_lock:
            for slot in self.slots:
                self._spawn(slot)
        self._tm_children.set(self.running_count())

    def _spawn(self, slot: _Slot) -> None:
        argv, env = self._normalize(self.argv_for(slot.index, slot.attempt))
        slot.proc = self._spawn_fn(argv, env)
        slot.started_ts = self.clock()
        slot.attempt += 1
        self.log(f"SUPERVISOR_SPAWN slot={slot.index} "
                 f"attempt={slot.attempt} pid={getattr(slot.proc, 'pid', '?')}",
                 flush=True)

    @staticmethod
    def _normalize(built):
        if isinstance(built, tuple):
            argv, env = built
            return list(argv), env
        return list(built), None

    def poll_once(self) -> None:
        """One supervision pass: reap exits, schedule/execute respawns.
        The whole pass holds the slots lock (every step is non-blocking
        polls and bookkeeping) so stop() can never interleave with a
        respawn."""
        with self._slots_lock:
            self._poll_locked()
        self._tm_children.set(self.running_count())

    def _poll_locked(self) -> None:
        now = self.clock()
        cfg = self.config
        for slot in self.slots:
            if slot.done:
                continue
            if slot.proc is not None:
                rc = slot.proc.poll()
                if rc is None:
                    if slot.fast_crashes \
                            and now - slot.started_ts >= cfg.healthy_after:
                        # Came up for real: the slot earned its reset.
                        slot.fast_crashes = 0
                        slot.backoff = 0.0
                    continue
                # Child exited.
                lived = now - slot.started_ts
                slot.last_rc = rc
                slot.proc = None
                if rc == 0:
                    slot.done = True
                    self.log(f"SUPERVISOR_DONE slot={slot.index} rc=0",
                             flush=True)
                    continue
                if not cfg.respawn:
                    slot.done = True
                    self.log(f"SUPERVISOR_EXIT slot={slot.index} rc={rc} "
                             f"(respawn disabled)", flush=True)
                    continue
                if lived < cfg.healthy_after:
                    slot.fast_crashes += 1
                    # Latch AT crash_loop_after consecutive fast crashes
                    # (what the flag help and docs promise — not one
                    # extra).
                    if slot.fast_crashes >= cfg.crash_loop_after:
                        slot.latched = True
                        slot.done = True
                        self._note_action("respawn", "crash_loop")
                        self.log(f"SUPERVISOR_CRASH_LOOP slot={slot.index} "
                                 f"rc={rc} fast_crashes={slot.fast_crashes}"
                                 f" (latched, no further respawns)",
                                 flush=True)
                        continue
                else:
                    slot.fast_crashes = 0
                    slot.backoff = 0.0
                slot.backoff = (cfg.backoff_initial if slot.backoff <= 0
                                else min(slot.backoff * 2.0,
                                         cfg.backoff_max))
                slot.next_spawn_ts = now + slot.backoff
                self.log(f"SUPERVISOR_CHILD_DIED slot={slot.index} rc={rc} "
                         f"lived={lived:.1f}s respawn_in={slot.backoff:.1f}s",
                         flush=True)
                continue
            # No process: a respawn is pending its backoff.
            if now >= slot.next_spawn_ts:
                slot.respawns += 1
                self._spawn(slot)
                self._note_action("respawn", "ok")
                self.log(f"SUPERVISOR_RESPAWN slot={slot.index} "
                         f"attempt={slot.attempt} "
                         f"after_rc={slot.last_rc}", flush=True)

    # -- elastic slots (worker autoscaling) ------------------------------------

    def add_slot(self) -> int:
        """Grow by one slot: append a fresh slot and spawn it NOW, under
        the slots lock — a grow landing mid-supervision-pass (or during
        a respawn) either fully precedes or fully follows the pass, so
        the new child can never miss stop()'s snapshot. Returns the new
        slot index (never a reused one)."""
        with self._slots_lock:
            slot = _Slot(index=self._next_slot_index)
            self._next_slot_index += 1
            self.slots.append(slot)
            self._spawn(slot)
        self._tm_children.set(self.running_count())
        self.log(f"SUPERVISOR_GROW slot={slot.index}", flush=True)
        return slot.index

    def remove_slot(self) -> int | None:
        """Shrink by one: retire the YOUNGEST live slot (highest index
        not yet done — the replica-pool discipline: the worker the job
        has depended on for the shortest time). The slot stays in the
        list marked done (its history keeps rendering in status); the
        child gets SIGTERM then SIGKILL after the grace window. Returns
        the retired index, or None when no slot is removable."""
        with self._slots_lock:
            live = [s for s in self.slots if not s.done]
            if not live:
                return None
            slot = max(live, key=lambda s: s.index)
            slot.done = True
            slot.retired = True
            proc, slot.proc = slot.proc, None
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass
            try:
                proc.wait(timeout=self.config.graceful_timeout)
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._tm_children.set(self.running_count())
        self.log(f"SUPERVISOR_SHRINK slot={slot.index}", flush=True)
        return slot.index

    # WorkerAutoscaler actuator surface (telemetry/remediation.py) —
    # the same verbs ReplicaPool exposes to the replica autoscaler.
    def grow(self) -> int:
        return self.add_slot()

    def shrink(self) -> int | None:
        return self.remove_slot()

    def count(self) -> int:
        return self.running_count()

    def run(self) -> int:
        """Supervise until every slot is done. Exit code: 0 when all
        slots finished cleanly, 1 when any latched as crash-looping or
        ended on a nonzero rc with respawn disabled."""
        try:
            while not self._stop.is_set():
                self.poll_once()
                if all(s.done for s in self.slots):
                    break
                self._stop.wait(self.config.poll_interval)
        finally:
            self.stop()
        # A slot only ends on a nonzero rc by latching (respawn on) or by
        # dying with respawn disabled — either way the run is degraded.
        # Retired slots are a deliberate shrink, not a failure (their
        # last_rc may be stale from a pre-retirement respawn).
        bad = [s for s in self.slots
               if s.latched or (s.done and not s.retired
                                and s.last_rc not in (0, None))]
        latched = [s.index for s in self.slots if s.latched]
        if latched:
            self.log(f"SUPERVISOR_EXIT latched_slots={latched}",
                     flush=True)
        return 1 if bad else 0

    def stop(self) -> None:
        """Terminate every running child (SIGTERM, then SIGKILL after the
        grace window)."""
        self._stop.set()
        # Taken AFTER setting the stop flag: an in-flight pass finishes
        # (possibly spawning), then the snapshot sees its child too.
        with self._slots_lock:
            procs = [s.proc for s in self.slots if s.proc is not None]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.time() + self.config.graceful_timeout
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._tm_children.set(0)

    # -- read side ------------------------------------------------------------

    def running_count(self) -> int:
        return sum(1 for s in self.slots
                   if s.proc is not None and s.proc.poll() is None)

    def status(self) -> dict:
        return {
            "slots": [{
                "slot": s.index,
                "running": s.proc is not None and s.proc.poll() is None,
                "pid": getattr(s.proc, "pid", None) if s.proc else None,
                "attempt": s.attempt,
                "respawns": s.respawns,
                "fast_crashes": s.fast_crashes,
                "last_rc": s.last_rc,
                "latched": s.latched,
                "done": s.done,
                "retired": s.retired,
            } for s in self.slots],
            "running": self.running_count(),
        }


def install_signal_stop(supervisor: WorkerSupervisor) -> None:
    """SIGTERM/SIGINT -> stop children then exit (cli supervise).
    Installed only on the main thread; no-op elsewhere."""
    if threading.current_thread() is not threading.main_thread():
        return

    def _handler(signum, frame):  # noqa: ARG001
        supervisor.stop()
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def build_worker_argv(base_args: list[str], slot: int,
                      first_spawn_faults: dict[int, str] | None = None,
                      first_spawn_env: dict[int, dict] | None = None,
                      attempt: int = 0,
                      python: str | None = None) -> tuple[list, dict | None]:
    """cli supervise's argv builder: one ``cli worker`` command line per
    (slot, attempt). ``base_args`` is everything the operator wrote after
    ``--``, passed to every child verbatim; the slot's ``--worker-name``
    is appended unless already present. First-spawn-only fault specs and
    env vars implement the chaos drills (the respawned replacement runs
    clean)."""
    pkg = __name__.rsplit(".", 2)[0]
    argv = [python or sys.executable, "-m", f"{pkg}.cli", "worker"]
    argv += list(base_args)
    if "--worker-name" not in base_args:
        argv += ["--worker-name", f"sup-w{slot}"]
    env = None
    if attempt == 0:
        spec = (first_spawn_faults or {}).get(slot)
        if spec:
            argv += ["--faults", spec]
        env = (first_spawn_env or {}).get(slot)
    return argv, env


def build_replica_argv(primary: str, base_args: list[str] | None = None,
                       index: int = 0,
                       python: str | None = None,
                       parent: str | None = None) -> tuple[list, None]:
    """One ``cli replica`` command line for a pool slot — the autoscaler's
    spawn template (telemetry/autoscale.py). ``base_args`` pass through
    verbatim (``--shard-id``, ``--poll-interval``, ...); the bound port is
    always ephemeral — a grown replica announces itself to the primary,
    clients learn it from the published shard map, so no port coordination
    is needed. ``parent`` points the new replica's SUBSCRIPTION at an
    interior node of the fan-out tree (tree-aware grow placement);
    ``--primary`` stays the authority writes redirect to either way."""
    pkg = __name__.rsplit(".", 2)[0]
    argv = [python or sys.executable, "-m", f"{pkg}.cli", "replica",
            "--primary", primary, "--port", "0"]
    if parent:
        argv += ["--parent", str(parent)]
    argv += list(base_args or [])
    return argv, None


class ReplicaPool:
    """Dynamic pool of replica subprocesses: the EXECUTE half of replica
    autoscaling (docs/SHARDING.md "Serve tier"). Where
    :class:`WorkerSupervisor` keeps a FIXED slot count alive, this pool's
    size is the controlled variable — :class:`~..telemetry.autoscale.
    ReplicaAutoscaler` calls :meth:`grow`/:meth:`shrink` and reads
    :meth:`count`. No respawn discipline: a replica that dies simply
    lowers the live count, and the autoscaler's next tick re-grows if the
    load still warrants it — the pool stays a pure actuator."""

    def __init__(self, argv_for, spawn=None, log=print,
                 graceful_timeout: float = 10.0):
        #: ``argv_for(index) -> (argv, env|None)`` builds one spawn;
        #: ``spawn(argv, env)`` is injectable so tests run the pool with
        #: fake processes.
        self.argv_for = argv_for
        self._spawn_fn = spawn or WorkerSupervisor._default_spawn
        self.log = log
        self.graceful_timeout = float(graceful_timeout)
        self._lock = threading.Lock()
        self._procs: dict[int, subprocess.Popen] = {}  # guarded by: self._lock
        self._next_index = 0  # guarded by: self._lock
        from ..telemetry import get_registry
        self._tm_live = get_registry().gauge("dps_replicas_live")

    def _reap_locked(self) -> None:
        for idx in [i for i, p in self._procs.items()
                    if p.poll() is not None]:
            self.log(f"REPLICA_POOL_EXIT index={idx} "
                     f"rc={self._procs[idx].poll()}", flush=True)
            del self._procs[idx]

    def count(self) -> int:
        with self._lock:
            self._reap_locked()
            n = len(self._procs)
        self._tm_live.set(n)
        return n

    def grow(self, parent: str | None = None) -> int:
        """Spawn one replica; returns its pool index. ``parent`` routes
        tree-aware placement through to the argv builder (a two-arg
        ``argv_for``); the plain call keeps 1-arg builders (and every
        pre-tree caller) working unchanged."""
        with self._lock:
            idx = self._next_index
            self._next_index += 1
            built = self.argv_for(idx) if parent is None \
                else self.argv_for(idx, parent)
            argv, env = WorkerSupervisor._normalize(built)
            self._procs[idx] = self._spawn_fn(argv, env)
            n = len(self._procs)
        self.log(f"REPLICA_POOL_GROW index={idx} live={n}"
                 + (f" parent={parent}" if parent else ""), flush=True)
        self._tm_live.set(n)
        return idx

    def shrink(self) -> int | None:
        """Terminate the YOUNGEST replica (the one clients have depended
        on for the shortest time); returns its index, or None when the
        pool is empty."""
        with self._lock:
            self._reap_locked()
            if not self._procs:
                return None
            idx = max(self._procs)
            proc = self._procs.pop(idx)
            n = len(self._procs)
        try:
            proc.terminate()
        except OSError:
            pass
        self.log(f"REPLICA_POOL_SHRINK index={idx} live={n}", flush=True)
        self._tm_live.set(n)
        return idx

    def stop(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.time() + self.graceful_timeout
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._tm_live.set(0)

    def status(self) -> dict:
        with self._lock:
            self._reap_locked()
            return {"live": len(self._procs),
                    "indices": sorted(self._procs),
                    "spawned_total": self._next_index}
