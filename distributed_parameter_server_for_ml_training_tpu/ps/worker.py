"""Async-mode worker runtime: threads driving device-compiled local steps.

Re-hosts the reference worker loop (src/workers/worker.py:350-403) against
the in-process :class:`~.store.ParameterStore` (or a gRPC client with the
same interface): register -> shard data by worker id -> per batch
[fetch params if step%K==0] -> local fwd/bwd on the accelerator ->
[push gradients if step%K==0] -> per-epoch full-test-set eval -> finished.

K-step ("--sync-steps") semantics: the reference computes gradients on every
batch but only pushes on ``batch_idx % K == 0`` batches — gradients from the
other K-1 batches are DISCARDED (worker.py:339+376; SURVEY.md quirk 7), so
K>1 trains on 1/K of the data. ``k_step_mode='faithful'`` reproduces that;
``'accumulate'`` is the corrected local-SGD behavior (mean of the window's
gradients pushed at the window end).
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..data.cifar import Dataset, make_batches, shard_range
from ..ops.compression import (  # hot-path imports hoisted, like ps/store
    QUANTIZED_PUSH_CODECS,
    ErrorFeedback,
    compress_push,
    fp16_compress,
    fp16_decompress,
)
from ..ops.device_codec import DeviceCodec, DevicePayload, is_device_tree
from ..telemetry import (
    GoodputAccount,
    current_wire_trace,
    now as _tnow,
    trace_span,
    use_wire_context,
)
from ..train.device_loop import prefetch_to_device
from ..train.steps import make_eval_step, make_fused_local_step, \
    make_grad_step
from ..utils.pytree import flatten_params, unflatten_params
from .store import ParameterStore

# Shared no-op bracket for goodput spans before telemetry init (and on
# the comms-pipeline thread, whose seconds overlap training compute).
_NULL_GP = nullcontext()


@dataclass
class WorkerConfig:
    batch_size: int = 128      # worker.py:474-482 distributed defaults
    num_epochs: int = 3
    sync_steps: int = 1        # K; CLI default 1 (worker.py:468)
    # 'faithful' | 'accumulate' | 'local_sgd'. local_sgd runs the DONATED
    # fused step (train/steps.py make_fused_local_step): grads + plain-SGD
    # apply + window accumulation as one compiled program, params updated
    # in place on device — no param round-trip inside the K-step window.
    # The window's gradient MEAN is pushed at the boundary (same payload
    # shape as 'accumulate'); with K=1 it matches 'faithful' bit-for-bit
    # up to +0/-0 on exactly-zero gradient entries.
    k_step_mode: str = "faithful"
    augment: bool = True
    eval_batch_size: int = 1000
    eval_each_epoch: bool = True   # worker.py:393-394
    seed: int = 0
    # Liveness ping via periodic fetch. The reference WROTE this (30 s
    # FetchParameters ping, worker.py:112-119) but never ran it — the loop
    # was dead code (SURVEY.md quirk 8). 0 disables; set e.g. 30.0 to enable
    # the capability the reference intended.
    heartbeat_interval: float = 0.0
    # Overlapped comms pipeline: pushes (and the following prefetch) run on
    # a bounded single-slot background thread while the training thread
    # computes the window's remaining batches. The per-worker RPC ORDER is
    # identical to the serial loop (push then fetch, exactly-once tokens
    # preserved); with a single worker every fetched_step is identical too
    # and curves match bit-for-bit (pinned by test). With MULTIPLE workers
    # the prefetch runs up to K-1 batches earlier than the serial loop's
    # boundary fetch, so it can observe a step another worker's push would
    # have advanced by then — at most one round per window, the same
    # no-barrier staleness class the store already tolerates (quirk 2 in
    # sync, the staleness bound in async). Pays off when sync_steps > 1
    # (there is compute to hide the comms behind).
    overlap: bool = False
    # Version-gated delta fetches: refetches send have_step so a store
    # whose step hasn't advanced answers NOT_MODIFIED (header-only) and
    # the worker keeps the params it already holds — byte-identical to a
    # full refetch at the same step, minus the wire bytes.
    delta_fetch: bool = True
    # Session resume (docs/ROBUSTNESS.md): when a remote store loses its
    # session (transient RPC failures outlive the retry budget —
    # SessionLostError), the worker re-registers, re-fetches at the
    # restored server step, and reconciles the in-flight gradient instead
    # of dying. This bounds the whole reconnect window in seconds;
    # 0 (default) disables resume and keeps the terminal-failure behavior.
    reconnect_timeout: float = 0.0
    # First reconnect retry delay; doubles per attempt (capped at 10 s).
    reconnect_backoff: float = 0.5
    # Deterministic compute-fault injection (the health demo / tests,
    # docs/OBSERVABILITY.md): at this 0-based local step, this batch's loss
    # and gradients are poisoned with NaN — the worker's own health report
    # must flag them non-finite and the cluster monitor must alert. Env
    # DPS_NAN_STEP provides the same hook to subprocess workers. None
    # disables (production default).
    nan_inject_step: int | None = None
    # Error feedback for the quantized push codecs (int8/int4/topk/
    # adaptive; docs/WIRE_PROTOCOL.md): the quantization residual of each
    # push is carried into the next step's gradient, so compressed updates
    # sum to the true gradient over time — what makes int4 and top-k
    # accuracy-safe. No effect on the none/fp16 codecs.
    error_feedback: bool = True
    # Fraction of entries a 'topk' push keeps per tensor (largest
    # magnitude; int8-quantized values + int32 indices on the wire).
    topk_frac: float = 0.01
    # Device-resident push codec (ops/device_codec.py): quantize/pack on
    # the accelerator and pull only the packed wire bytes, instead of
    # pulling fp32 gradients and encoding them with NumPy. Wire bytes and
    # error-feedback residuals are bit-identical to the NumPy reference
    # (property-tested, tests/test_quantize.py); engages only when a
    # quantized codec was negotiated and the gradients are device arrays.
    # False forces the NumPy reference path.
    device_codec: bool = True
    # Host->device input double buffering: keep this many batches'
    # transfers in flight ahead of compute (train/device_loop.py
    # prefetch_to_device), so batch N+1's upload overlaps batch N's
    # compute. 0 feeds host batches directly (the prior behavior).
    prefetch_batches: int = 2
    # 'local_sgd' mode: the worker-local SGD learning rate; None adopts
    # the store's configured learning_rate.
    local_lr: float | None = None

    def __post_init__(self):
        if self.k_step_mode not in ("faithful", "accumulate", "local_sgd"):
            raise ValueError(self.k_step_mode)
        if self.sync_steps < 1:
            raise ValueError("sync_steps must be >= 1")
        if self.prefetch_batches < 0:
            raise ValueError("prefetch_batches must be >= 0")


@dataclass
class WorkerResult:
    worker_id: int = -1
    worker_name: str = ""
    epoch_times: list = field(default_factory=list)
    test_accuracies: list = field(default_factory=list)
    local_steps_completed: int = 0
    pushes_accepted: int = 0
    pushes_rejected: int = 0
    heartbeats: int = 0
    # Session resumes survived (server restarts / network partitions the
    # reconnect state machine rode through; docs/ROBUSTNESS.md).
    reconnects: int = 0
    # Server->worker control directives acted on, by action name
    # (docs/ROBUSTNESS.md "Self-healing"); empty when none arrived.
    directives_applied: dict = field(default_factory=dict)
    # Push windows skipped under a quarantine directive.
    pushes_quarantined: int = 0
    # Client-side wire accounting (RemoteStore.wire_stats); empty for
    # in-process stores, which cross no wire.
    wire: dict = field(default_factory=dict)
    error: Exception | None = None

    def metrics(self, total_workers: int, learning_rate: float,
                config: WorkerConfig) -> dict:
        """METRICS_JSON field parity with worker.py:421-434 (+ wire
        accounting when the store is remote)."""
        out = self._base_metrics(total_workers, learning_rate, config)
        if self.directives_applied:
            out["directives_applied"] = dict(self.directives_applied)
        if self.pushes_quarantined:
            out["pushes_quarantined"] = self.pushes_quarantined
        if self.wire:
            out.update(self.wire)
        return out

    def _base_metrics(self, total_workers: int, learning_rate: float,
                      config: WorkerConfig) -> dict:
        return {
            "worker_id": self.worker_id,
            "worker_name": self.worker_name,
            "total_workers": total_workers,
            "total_training_time_seconds": round(sum(self.epoch_times), 2),
            "average_epoch_time_seconds": (
                round(float(np.mean(self.epoch_times)), 2)
                if self.epoch_times else 0.0),
            "epoch_times_seconds": [round(t, 2) for t in self.epoch_times],
            "final_test_accuracy": (self.test_accuracies[-1]
                                    if self.test_accuracies else 0.0),
            "all_test_accuracies": self.test_accuracies,
            "local_steps_completed": self.local_steps_completed,
            "batch_size": config.batch_size,
            "learning_rate": learning_rate,
            "num_epochs": config.num_epochs,
            "reconnects": self.reconnects,
        }


def _window_mean(accum_tree, n: int):
    """Mean of an accumulated K-step gradient window — ONE definition
    shared by the serial and overlapped push paths, so their numerics
    cannot drift apart."""
    scale = np.float32(n)
    return jax.tree_util.tree_map(lambda a: a / scale, accum_tree)


class _BitwidthController:
    """Per-layer push-codec chooser for the quantized codec family.

    Fixed codecs (``int8``/``int4``/``topk``) pin the aggressiveness
    level; ``adaptive`` moves the level with measured LINK PRESSURE — the
    fraction of wall time the push spends on the wire (push RPC seconds
    over the window since the previous push, the same signal the
    ``worker.push_wait`` span and pipeline telemetry already expose).
    Sustained pressure above ``hi`` escalates int8 -> int4 -> +topk;
    sustained pressure below ``lo`` de-escalates. ``patience``
    consecutive windows are required either way, so one slow RPC doesn't
    whipsaw the codec.

    The plan is per-layer: tiny tensors (biases, norms) stay int8 at any
    level — their bytes are noise and sparse/packed overhead would exceed
    the savings; topk only applies above ``min_topk_size``.
    """

    LEVEL_NAMES = ("int8", "int4", "topk")

    def __init__(self, codec: str, hi: float = 0.25, lo: float = 0.05,
                 patience: int = 2, min_int4_size: int = 256,
                 min_topk_size: int = 4096):
        self.adaptive = codec == "adaptive"
        self.level = 0 if self.adaptive \
            else {"int8": 0, "int4": 1, "topk": 2}.get(codec, 0)
        self.hi, self.lo, self.patience = hi, lo, patience
        self.min_int4_size = min_int4_size
        self.min_topk_size = min_topk_size
        self._hot = self._cold = 0

    def note_push(self, push_seconds: float, window_seconds: float) -> None:
        """Feed one push's timing (adaptive only): RPC seconds vs the
        wall-clock window since the previous push completed."""
        if not self.adaptive or window_seconds <= 0:
            return
        pressure = push_seconds / window_seconds
        if pressure > self.hi:
            self._hot += 1
            self._cold = 0
            if self._hot >= self.patience and self.level < 2:
                self.level += 1
                self._hot = 0
        elif pressure < self.lo:
            self._cold += 1
            self._hot = 0
            if self._cold >= self.patience and self.level > 0:
                self.level -= 1
                self._cold = 0
        else:
            self._hot = self._cold = 0

    def plan(self, flat: dict) -> dict:
        """{tensor name: 'int8'|'int4'|'topk'} for this push."""
        out = {}
        for name, a in flat.items():
            # .size, not np.asarray(a).size: the flat dict may hold DEVICE
            # arrays (device codec path) and the plan must not pull them.
            size = int(a.size)
            if self.level >= 2 and size >= self.min_topk_size:
                out[name] = "topk"
            elif self.level >= 1 and size >= self.min_int4_size:
                out[name] = "int4"
            else:
                out[name] = "int8"
        return out

    def describe(self) -> str:
        name = self.LEVEL_NAMES[self.level]
        return f"adaptive({name})" if self.adaptive else name


class _CommsPipeline:
    """Bounded single-slot comms thread for one worker.

    Executes (push, then optional prefetch) work items in submission order
    on ONE background thread, so a worker's pushes stay strictly sequential
    — the RemoteStore push-token dedupe contract ("a retry always precedes
    that worker's next distinct push") holds exactly as in the serial loop
    — and a prefetch can never overtake the push it follows. At most ONE
    item is in flight: ``submit`` blocks until the previous item completed
    (natural backpressure; the depth gauge is therefore 0 or 1).

    Timing caveat: the prefetch is issued right after its push, up to K-1
    batches EARLIER than the serial loop's next-boundary fetch, so with
    multiple workers it can see a step that a peer's push would have
    advanced by boundary time — bounded at one round per window and
    within the store's existing no-barrier staleness model (see the
    ``WorkerConfig.overlap`` comment and docs/WIRE_PROTOCOL.md). With one
    worker the fetch results are identical and parity is exact.

    The training thread's contract:

    - ``submit(grads, fetched_step, prefetch_current)`` — push ``grads``
      with ``fetched_step``; if ``prefetch_current`` is not None, follow
      with a params fetch (``have_step=fetched_step``, delta-gated) whose
      result ``await_params`` later returns.
    - ``await_params()`` — block until the pending prefetch result is
      available and take it.
    - ``flush()`` — block until the pipeline is idle (epoch boundaries:
      every push must be on the server before the epoch closes).

    Comms-thread exceptions surface on the NEXT training-thread call, so a
    dead server still fails the worker (with the original traceback as
    ``__cause__``) instead of hanging it.
    """

    def __init__(self, worker: "PSWorker", worker_id: int):
        self._worker = worker
        self._worker_id = worker_id
        self._item = None
        self._error: Exception | None = None
        # The (grads, fetched_step) of a PUSH that died on the comms
        # thread — what the session-resume reconciliation must decide
        # about. A failed PREFETCH leaves this None: its push already
        # landed and must not be re-sent.
        self._failed_push = None
        self._go = threading.Event()
        self._done = threading.Event()
        self._done.set()
        self._stop = False
        self._result = None            # (params, step) of the last prefetch
        self._result_ready = threading.Event()
        self._pending_prefetch = False  # training thread only
        self._last_comms_s = 0.0
        from ..telemetry import get_registry
        reg = get_registry()
        w = str(worker_id)
        self._tm_depth = reg.gauge("dps_worker_pipeline_depth", worker=w)
        # Comms seconds the training thread did NOT spend blocked: the
        # item's comms-thread duration minus the time await/flush actually
        # waited for it — the per-window overlap win, live.
        self._tm_saved = reg.histogram("dps_worker_overlap_saved_seconds",
                                       worker=w)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"comms-pipeline-{worker_id}")
        self._thread.start()

    # -- comms thread --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            self._go.wait()
            self._go.clear()
            if self._stop:
                return
            grads, fetched_step, prefetch_current, wctx = self._item
            self._item = None
            t0 = _tnow()
            try:
                # Adopt the submitting step's trace context so this item's
                # comms span (and the RPC/store spans under it) attach to
                # the step whose window hides the latency.
                with use_wire_context(wctx), \
                        trace_span("pipeline.comms",
                                   worker=self._worker_id,
                                   prefetch=prefetch_current is not None):
                    if grads is not None:
                        try:
                            self._worker._push(self._worker_id, grads,
                                               fetched_step)
                        except Exception:  # noqa: BLE001 — stash, then re-raise
                            self._failed_push = (grads, fetched_step)
                            raise
                    if prefetch_current is not None:
                        result = self._worker._fetch_params(
                            self._worker_id, have_step=fetched_step,
                            current=prefetch_current)
                        # Duration published BEFORE the ready flag: a
                        # waiter that wakes immediately must see THIS
                        # item's comms time in its overlap-savings
                        # record, not the previous one's.
                        self._last_comms_s = _tnow() - t0
                        self._result = result
                        self._result_ready.set()
            except Exception as e:  # noqa: BLE001 — surfaced via await_params
                self._error = e
                self._result_ready.set()  # wake a blocked await_params
            finally:
                self._last_comms_s = _tnow() - t0
                self._tm_depth.set(0)
                self._done.set()

    # -- training thread -----------------------------------------------------

    def _raise_if_failed(self) -> None:
        if self._error is not None:
            raise RuntimeError("comms pipeline failed") from self._error

    def submit(self, grads, fetched_step: int, prefetch_current) -> None:
        self._done.wait()  # single-slot bound: previous item must be done
        self._raise_if_failed()
        # Double-buffered gradient pull: start the device->host copies NOW,
        # on the training thread, so they run behind the next window's
        # compute and the comms thread's device_get finds the bytes already
        # on the host. A DevicePayload started its own copies at encode
        # time; device-resident stores never pull, so nothing to stage.
        if grads is not None and not isinstance(grads, DevicePayload) \
                and not getattr(self._worker.store, "keeps_device_arrays",
                                False):
            for leaf in jax.tree_util.tree_leaves(grads):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        # Trace context captured on the TRAINING thread (the submitting
        # step's push_wait span) — the comms thread re-enters it.
        self._item = (grads, fetched_step, prefetch_current,
                      current_wire_trace())
        self._pending_prefetch = prefetch_current is not None
        self._done.clear()
        self._tm_depth.set(1)
        self._go.set()

    def params_pending(self) -> bool:
        return self._pending_prefetch

    def await_params(self):
        """Take the pending prefetch result; records the overlap saving
        (comms time hidden behind compute) for this window."""
        t0 = _tnow()
        self._result_ready.wait()
        waited = _tnow() - t0
        self._raise_if_failed()
        params, step = self._result
        self._result = None
        self._result_ready.clear()
        self._pending_prefetch = False
        self._tm_saved.observe(max(0.0, self._last_comms_s - waited))
        return params, step

    def flush(self) -> None:
        """Epoch barrier: wait until the in-flight item (if any) finished.
        A pending prefetch RESULT survives a flush — the next epoch's
        opening fetch consumes it."""
        self._done.wait()
        self._raise_if_failed()

    def take_failed_item(self):
        """The (grads, fetched_step) of the push that killed this
        pipeline, if any — consumed once by the session-resume
        reconciliation (ps/worker.py:_recover_session)."""
        item, self._failed_push = self._failed_push, None
        return item

    def close(self) -> None:
        # Bounded wait: a comms thread stuck deep in RPC retries must not
        # wedge worker teardown — it is a daemon thread and will observe
        # _stop when (if) its RPC returns.
        self._done.wait(timeout=120.0)
        self._stop = True
        self._go.set()
        self._thread.join(timeout=10.0)


class PSWorker(threading.Thread):
    """One logical worker. Runs as a thread; compute runs on the accelerator
    via a shared jit-compiled grad step (one compile for all workers)."""

    def __init__(self, store: ParameterStore, model, dataset: Dataset,
                 config: WorkerConfig | None = None,
                 grad_step=None, eval_step=None, fused_step=None,
                 worker_name: str = ""):
        super().__init__(daemon=True)
        self.store = store
        self.model = model
        self.dataset = dataset
        self.config = config or WorkerConfig()
        self.worker_name = worker_name
        self.result = WorkerResult()
        # Step of the last successful fetch; the heartbeat thread reads it
        # to delta-gate its pings (int read/write is atomic enough).
        self._last_fetched_step: int | None = None
        # Overlapped comms pipeline (set in _run when overlap=True); an
        # attribute so the session-resume path can drain and rebuild it.
        self._pipe: _CommsPipeline | None = None
        self._tm_reconnect = None  # created at _init_telemetry
        self._tm_hb_err = None
        # Worker health report (docs/OBSERVABILITY.md): built at push
        # boundaries by _note_health, shipped by the RemoteStore on every
        # fetch/push/heartbeat via the provider installed in _run. The lock
        # covers training-thread writes vs heartbeat/comms-thread reads.
        self._health_lock = threading.Lock()
        self._health: dict = {}  # guarded by: self._health_lock
        self._health_enabled = False
        self._health_rate: tuple[float, int] | None = None
        # Report revision, bumped under the lock on every mutation: lets
        # the RemoteStore cache the report's JSON encode across the many
        # heartbeat pings between boundary updates (comms/client.py
        # health_revision).
        self._health_rev = 0  # guarded by: self._health_lock
        # Quantized-codec state (set up after registration, once the
        # store's negotiated codec is known): error-feedback residuals and
        # the per-layer bitwidth controller (docs/WIRE_PROTOCOL.md).
        self._ef: ErrorFeedback | None = None
        self._bitwidth: _BitwidthController | None = None
        # Device-resident codec (ops/device_codec.py): set in _run when a
        # quantized codec is negotiated and config.device_codec is on.
        # Carries its own error-feedback residuals ON DEVICE.
        self._device_codec: DeviceCodec | None = None
        self._prev_push_done: float | None = None
        # Directive-channel state (docs/ROBUSTNESS.md "Self-healing"):
        # server->worker directives arrive on fetch/push reply meta and
        # are acted on at step boundaries by the training thread.
        self._force_full_fetch = False     # refetch_params
        self._quarantine_windows = 0       # quarantine: windows to skip
        self._epoch_break = False          # rebalance_shard
        self._draining = False             # drain
        # Injected per-step compute slowdown (comms/faults.py COMPUTE_OP):
        # set in _run from the store's fault injector, if any.
        self._compute_faults = None
        # Goodput ledger (telemetry/goodput.py): created at
        # _init_telemetry; every second of the training thread's wall is
        # classified into GOODPUT_CATEGORIES.
        self._goodput: GoodputAccount | None = None
        ns = self.config.nan_inject_step
        if ns is None:
            import os as _os
            env = _os.environ.get("DPS_NAN_STEP")
            ns = int(env) if env else None
        self._nan_step = ns
        # Shared compiled functions may be passed in to avoid re-tracing per
        # worker; otherwise built here.
        self._grad_step = grad_step or make_grad_step(
            model, augment=self.config.augment)
        self._eval_step = eval_step or jax.jit(make_eval_step())
        # local_sgd's donated fused step: built lazily in _run (only that
        # mode pays the trace) unless a shared compile was passed in.
        self._fused_step = fused_step

    # -- the training loop (worker.py:350-403) ------------------------------

    def run(self) -> None:
        self._done = threading.Event()
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — surfaced via .result
            self.result.error = e
        finally:
            self._done.set()
            if self.result.worker_id >= 0:
                try:
                    self.store.job_finished(self.result.worker_id)
                except Exception as e:  # noqa: BLE001
                    # A dead server at goodbye time must not erase an
                    # otherwise-complete run (the result already holds
                    # the training outcome); the server's liveness reaper
                    # expires the slot instead.
                    print(f"JobFinished failed for worker "
                          f"{self.result.worker_id}: {e!r}", flush=True)
            # After JobFinished so the final RPC is counted too.
            ws = getattr(self.store, "wire_stats", None)
            if callable(ws):
                self.result.wire = ws()

    def _heartbeat_loop(self, interval: float) -> None:
        """Liveness ping: periodic fetch (the reference's intended
        health_check_loop, worker.py:112-119, implemented for real).
        Delta-gated when possible: the ping's payload is discarded anyway,
        so against a store that supports it a ping costs a header whenever
        the step hasn't advanced past the training thread's last fetch.
        The worker id is re-read every tick, so after a session resume the
        same thread keeps the NEW registration alive — heartbeats
        re-establish themselves with no thread churn.

        Tick failures are COUNTED (dps_worker_heartbeat_errors_total) and
        logged once per transition into the failing state — previously they
        were swallowed silently, so a half-dead worker (pings failing,
        training limping along) was invisible until the server expired it.
        Transient blips still don't kill the thread; the next tick retries."""
        failing = False
        while not self._done.wait(interval):
            try:
                worker_id = self.result.worker_id
                have = self._last_fetched_step
                if (have is not None and self.config.delta_fetch
                        and getattr(self.store, "supports_delta_fetch",
                                    False)):
                    self.store.fetch(worker_id, have_step=have)
                else:
                    self.store.fetch(worker_id)
                self.result.heartbeats += 1
                if failing:
                    failing = False
                    print(f"HEARTBEAT_RECOVERED worker={self.worker_name} "
                          f"id={self.result.worker_id}", flush=True)
            except Exception as e:  # noqa: BLE001 — next tick retries
                if self._tm_hb_err is not None:
                    self._tm_hb_err.inc()
                with self._health_lock:
                    self._health["heartbeat_errors"] = \
                        self._health.get("heartbeat_errors", 0) + 1
                    self._health_rev += 1
                if not failing:
                    failing = True
                    print(f"HEARTBEAT_FAILING worker={self.worker_name} "
                          f"id={self.result.worker_id} err={e!r}",
                          flush=True)

    def _compute_shard(self, worker_id: int, total_workers: int):
        """This worker's contiguous data shard.

        Faithful mode: fixed split by registration id over the configured
        total (worker.py:166-179), ids wrapping into range. Elastic mode:
        split over the LIVE membership by rank among active workers — at
        epoch boundaries this rebalances coverage as workers join/leave.
        """
        n = len(self.dataset.x_train)
        # Works for remote (gRPC) stores too: elastic servers piggyback live
        # membership on Register/Fetch replies and RemoteStore caches it, so
        # its membership_snapshot() serves the same role as the in-process
        # store's lock-guarded one.
        cfg = getattr(self.store, "config", None)
        if getattr(cfg, "elastic", False) \
                and hasattr(self.store, "membership_snapshot"):
            active = self.store.membership_snapshot()
            if worker_id in active:
                rank, total = active.index(worker_id), len(active)
            else:  # raced with own expiry: keep the fallback split
                rank, total = worker_id % total_workers, total_workers
        else:
            rank, total = worker_id % total_workers, total_workers
        lo, hi = shard_range(n, rank, total)
        return self.dataset.x_train[lo:hi], self.dataset.y_train[lo:hi]

    def _init_telemetry(self, worker_id: int) -> None:
        """Per-worker live instruments (telemetry/), labeled by worker id
        so a multi-worker process's snapshot stream separates into
        per-worker time-series. Created once, after registration (the id
        IS the label)."""
        from ..telemetry import get_registry
        reg = get_registry()
        w = str(worker_id)
        self._tm_step_s = reg.histogram("dps_worker_step_seconds", worker=w)
        self._tm_steps = reg.counter("dps_worker_steps_total", worker=w)
        self._tm_epochs = reg.counter("dps_worker_epochs_total", worker=w)
        self._tm_acc = reg.gauge("dps_worker_test_accuracy", worker=w)
        # Payload bytes around the push codec: 'precodec' counts the fp32
        # gradient payload, 'wire' what actually leaves after compression
        # — the live per-worker form of the reference's one-off size log
        # (worker.py:292), and the per-update byte accounting compression
        # studies need (PAPERS.md).
        self._tm_push_pre = reg.counter("dps_worker_push_bytes_total",
                                        stage="precodec", worker=w)
        self._tm_push_wire = reg.counter("dps_worker_push_bytes_total",
                                         stage="wire", worker=w)
        self._tm_fetch_post = reg.counter("dps_worker_fetch_bytes_total",
                                          stage="postcodec", worker=w)
        # Refetches answered NOT_MODIFIED (delta fetch): the worker kept
        # its params and moved ~zero payload bytes.
        self._tm_fetch_nm = reg.counter(
            "dps_worker_fetch_not_modified_total", worker=w)
        # Session resumes survived (reconnect state machine,
        # docs/ROBUSTNESS.md). Labeled by the INITIAL registration id —
        # the logical worker's identity for the whole run, even though a
        # resume may register under a fresh id (the id is in the resume
        # log line and the worker.reconnect span attrs).
        self._tm_reconnect = reg.counter("dps_worker_reconnect_total",
                                         worker=w)
        # Heartbeat ticks that failed (satellite: a half-dead worker's
        # failing pings were previously invisible — no counter, no log).
        self._tm_hb_err = reg.counter("dps_worker_heartbeat_errors_total",
                                      worker=w)
        # Wire bytes the push codec saved vs the fp32 payload (precodec −
        # wire, cumulative), and the effective bits/value of the LAST push
        # — the live bitwidth the adaptive controller settled on
        # (32 = fp32, 8 = int8, ~4 = int4, <1 = topk).
        self._tm_push_saved = reg.counter(
            "dps_worker_push_bytes_saved_total", worker=w)
        self._tm_push_bits = reg.gauge("dps_worker_push_bitwidth", worker=w)
        # Push-codec seconds per push (device encode + packed-bytes pull,
        # or the NumPy compress when the device codec is off), and the
        # device->host gradient-pull seconds that ran on the comms
        # pipeline thread instead of blocking the training thread — the
        # double-buffered-transfer win, live (docs/OBSERVABILITY.md).
        self._tm_codec_s = reg.histogram("dps_worker_codec_seconds",
                                         worker=w)
        self._tm_d2h_saved = reg.histogram(
            "dps_worker_d2h_overlap_saved_seconds", worker=w)
        # Server->worker directives acted on, one series per catalog
        # action (docs/ROBUSTNESS.md "Self-healing").
        from ..comms.service import DIRECTIVE_CATALOG
        self._tm_directives = {
            a: reg.counter("dps_worker_directives_total", worker=w,
                           action=a)
            for a in DIRECTIVE_CATALOG
        }
        # Wall-clock goodput ledger: the shared cumulative counters sum
        # worker-seconds across every account in the process; the
        # instance keeps its own totals so _note_health reports an
        # honest per-worker goodput fraction.
        self._goodput = GoodputAccount(reg)

    def _gp(self, category: str):
        """Goodput bracket for the TRAINING thread's wall. The
        comms-pipeline thread's overlapped work is deliberately NOT
        charged — those seconds run under the window's compute, and
        charging them would make the categories sum past the wall."""
        gp = self._goodput
        if gp is None:
            return _NULL_GP
        pipe = self._pipe
        if pipe is not None and threading.current_thread() is pipe._thread:
            return _NULL_GP
        return gp.span(category)

    def _compute_category(self) -> str:
        """Quarantined windows still burn device seconds, but their
        pushes are dropped at the boundary — that wall is idle-by-
        directive, not goodput."""
        return "quarantine_idle" if self._quarantine_windows > 0 \
            else "compute"

    # -- worker health report (docs/OBSERVABILITY.md) ------------------------

    def _health_snapshot(self) -> dict | None:
        """Provider installed on the RemoteStore: the current report, or
        None before the first boundary note (a report-less heartbeat is a
        valid legacy ping, not an error)."""
        with self._health_lock:
            return dict(self._health) if self._health else None

    def _health_revision(self) -> int:
        """Companion provider: the report's revision, so the store can
        reuse its cached JSON encode while the report is unchanged
        (heartbeat pings far outnumber boundary updates)."""
        with self._health_lock:
            return self._health_rev

    def _note_health(self, loss, grads_tree, epoch: int,
                     grad_scale: float = 1.0) -> None:
        """Refresh the health report at a push boundary — the one place the
        loop already synchronizes with the device, so the float() / norm
        materializations add no extra sync points. Skipped entirely unless
        the store advertised the health_report capability (zero cost for
        unmonitored runs).

        ``grads_tree`` must be (proportional to) what is PUSHED — in
        accumulate mode that is the window's gradient sum with
        ``grad_scale=1/n`` (norm of the pushed mean; a NaN from ANY batch
        in the window is in the sum, so the finite check flags exactly the
        payload that poisons the server, not just the boundary batch)."""
        if not self._health_enabled:
            return
        import math
        try:
            lval = float(loss)
        except (TypeError, ValueError):
            lval = float("nan")
        try:
            import jax.numpy as jnp
            sq = sum(jnp.sum(jnp.square(jnp.asarray(g, jnp.float32)))
                     for g in jax.tree_util.tree_leaves(grads_tree))
            gval = float(jnp.sqrt(sq)) * float(grad_scale)
        except (TypeError, ValueError):
            gval = float("nan")
        loss_finite = math.isfinite(lval)
        grad_finite = math.isfinite(gval)
        now = time.time()
        steps = self.result.local_steps_completed
        eps = None
        prev = self._health_rate
        if prev is not None and now > prev[0] and steps > prev[1]:
            eps = (steps - prev[1]) * self.config.batch_size \
                / (now - prev[0])
        self._health_rate = (now, steps)
        pipe = self._pipe
        depth = 0 if pipe is None or pipe._done.is_set() else 1
        gpf = self._goodput.fraction() if self._goodput is not None \
            else None
        with self._health_lock:
            h = self._health
            h["step"] = steps
            h["epoch"] = epoch
            # Non-finite values travel as null + a false finite flag so
            # NaN never rides a JSON hop (telemetry/cluster.py schema).
            h["loss"] = round(lval, 6) if loss_finite else None
            h["loss_finite"] = loss_finite
            h["grad_norm"] = round(gval, 6) if grad_finite else None
            h["grad_finite"] = grad_finite
            if eps is not None:
                h["examples_per_s"] = round(eps, 3)
            h["pipeline_depth"] = depth
            h["reconnects"] = self.result.reconnects
            # Negotiated push codec, live (the adaptive controller's
            # CURRENT level, '+ef' when error feedback is on) — surfaces
            # in /cluster and the `cli status` worker table.
            codec = self._bitwidth.describe() if self._bitwidth \
                else getattr(self.store, "push_codec", "none")
            h["push_codec"] = codec + ("+ef" if self._ef is not None
                                       else "")
            if gpf is not None:
                # Productive fraction of this worker's wall so far
                # (telemetry/goodput.py) — the status/top goodput column.
                h["goodput_fraction"] = round(gpf, 4)
            h.setdefault("heartbeat_errors", 0)
            self._health_rev += 1

    # -- directive channel (docs/ROBUSTNESS.md "Self-healing") ---------------

    def _poll_directives(self) -> None:
        """Drain and act on server->worker directives (step boundaries —
        the places the loop already talks to the server). No-op against
        stores without the channel (in-process, legacy servers)."""
        take = getattr(self.store, "take_directives", None)
        if not callable(take):
            return
        try:
            directives = take()
        except Exception:  # noqa: BLE001 — directives must not kill a run
            return
        for d in directives:
            self._apply_directive(d)

    def _apply_directive(self, d: dict) -> None:
        action = d.get("action")
        if action == "refetch_params":
            # Drop the delta basis: the next boundary fetch is a full
            # fresh fetch even if the step did not advance.
            self._force_full_fetch = True
        elif action == "quarantine":
            try:
                steps = max(1, int(d.get("steps", 3)))
            except (TypeError, ValueError):
                steps = 3
            self._quarantine_windows = max(self._quarantine_windows, steps)
            if self._ef is not None:
                # The residual carry may hold the same poison the server
                # quarantined us for — restart it clean.
                self._ef = ErrorFeedback()
            if self._device_codec is not None:
                self._device_codec.reset()  # same carry, device-resident
            self._force_full_fetch = True
        elif action == "rebalance_shard":
            # Finish the current epoch early; the next epoch recomputes
            # the shard from live membership (the per-epoch reshard the
            # loop already does).
            self._epoch_break = True
        elif action == "drain":
            self._draining = True
        else:
            return  # unknown directive from a newer server: ignore
        self.result.directives_applied[action] = \
            self.result.directives_applied.get(action, 0) + 1
        tm = getattr(self, "_tm_directives", None)
        if tm and action in tm:
            tm[action].inc()
        print(f"DIRECTIVE worker={self.worker_name} "
              f"id={self.result.worker_id} action={action} "
              f"seq={d.get('seq')}", flush=True)

    def _run(self) -> None:
        t_run0 = _tnow()
        cfg = self.config
        worker_id, total_workers = self.store.register_worker(self.worker_name)
        self.result.worker_id = worker_id
        self.result.worker_name = self.worker_name
        self._init_telemetry(worker_id)
        # Quantized push codec (negotiated: the store advertised it at
        # registration): error-feedback residuals + the per-layer bitwidth
        # controller. Legacy servers advertise fp16/none and neither
        # engages — same degradation discipline as delta-fetch.
        codec = getattr(self.store, "push_codec", "none")
        if codec in QUANTIZED_PUSH_CODECS:
            self._ef = ErrorFeedback() if cfg.error_feedback else None
            self._bitwidth = _BitwidthController(codec)
            if cfg.device_codec:
                # Device-resident encode (ops/device_codec.py): when the
                # gradients are device arrays the quantize/pack runs on
                # the accelerator and only the packed wire bytes cross
                # the link — bit-identical to the NumPy path, which
                # remains the fallback (host-resident trees) and the
                # server-side decode. Its EF carry supersedes self._ef
                # whenever it engages (one push never pays both).
                self._device_codec = DeviceCodec(
                    error_feedback=cfg.error_feedback,
                    topk_frac=cfg.topk_frac)
        # Health reports ride fetch/push/heartbeat envelopes when the
        # server advertised the capability at registration; otherwise the
        # note path stays disabled and costs nothing (the same degradation
        # discipline as delta-fetch / trace-context).
        if getattr(self.store, "supports_health_report", False) \
                and hasattr(self.store, "health_provider"):
            self.store.health_provider = self._health_snapshot
            if hasattr(self.store, "health_revision"):
                self.store.health_revision = self._health_revision
            self._health_enabled = True
        # Injected compute slowdown (comms/faults.py 'compute' pseudo-op):
        # the same --faults spec that drives RPC chaos can make THIS
        # worker a deterministic straggler.
        injector = getattr(self.store, "faults", None)
        if injector is not None and hasattr(injector,
                                            "maybe_delay_compute"):
            self._compute_faults = injector
        if cfg.heartbeat_interval > 0:
            threading.Thread(
                target=self._heartbeat_loop,
                args=(cfg.heartbeat_interval,),
                daemon=True).start()

        # Template structure for flat<->pytree conversion.
        h, w = self.dataset.x_train.shape[1:3]
        variables = self.model.init(
            jax.random.PRNGKey(cfg.seed),
            np.zeros((1, h, w, 3), np.float32), train=False)
        batch_stats = variables.get("batch_stats", {})  # ViT has no BN
        params = variables["params"]

        rng = jax.random.PRNGKey(cfg.seed + worker_id)
        fetched_step = 0
        params = None
        k = cfg.sync_steps
        accum = None
        accum_n = 0
        # local_sgd mode: the donated fused step walks a LOCAL parameter
        # trajectory between push boundaries (train/steps.py). local_params
        # is an explicit COPY of the fetched params — the fused step
        # donates its inputs, and the fetched tree must stay intact as the
        # delta-fetch basis.
        local_sgd = cfg.k_step_mode == "local_sgd"
        local_params = None
        local_lr = None
        if local_sgd:
            if self._fused_step is None:
                self._fused_step = make_fused_local_step(
                    self.model, augment=cfg.augment)
            local_lr = cfg.local_lr
            if local_lr is None:
                local_lr = float(getattr(
                    getattr(self.store, "config", None),
                    "learning_rate", 0.1) or 0.1)
            local_lr = np.float32(local_lr)
        # Overlapped comms: pushes + prefetches ride a bounded single-slot
        # background thread; the RPC sequence is IDENTICAL to the serial
        # loop (see _CommsPipeline), only the training thread stops
        # blocking on it. Held as an attribute so the session-resume path
        # can drain and rebuild it (docs/ROBUSTNESS.md).
        self._pipe = _CommsPipeline(self, worker_id) if cfg.overlap else None

        gp = self._goodput
        if gp is not None:
            # Everything from _run entry to here — registration, codec
            # negotiation, model/template init, pipeline spin-up — is the
            # startup bucket; backdating the wall anchor puts it INSIDE
            # the wall so the ledger reconciles end to end.
            gp.add("startup", _tnow() - t_run0)
            gp.start_wall(t_run0)
        try:
            for epoch in range(cfg.num_epochs):
                t_epoch = time.time()
                self._epoch_break = False
                # The epoch's first fetch happens BEFORE the shard
                # computation: batch 0 is always a fetch boundary anyway
                # (batch_idx % K == 0), and hoisting it means a REMOTE
                # store's membership cache is fresh when the shard is
                # computed — at registration time the first worker only
                # sees itself, and an epoch-1 shard computed from that
                # would cover the whole dataset. An overlapped pipeline's
                # pending prefetch serves the same role (it IS a fetch,
                # moments old, and refreshed the membership cache).
                # The opening fetch gets its own root trace entry (attr
                # epoch_open): a worker stuck here — a stale server, a
                # slow wire — shows up in the straggler report as a
                # fetch-wait-dominant step rather than vanishing into
                # epoch bookkeeping.
                with trace_span("worker.step", root=True, worker=worker_id,
                                step=self.result.local_steps_completed,
                                epoch=epoch, epoch_open=True):
                    with trace_span("worker.fetch_wait"):
                        params, fetched_step = self._boundary_fetch(
                            worker_id, fetched_step, params)
                # A session resume inside the fetch may have re-registered
                # under a fresh id; everything downstream (shard, spans,
                # pushes) must use the CURRENT registration.
                worker_id = self.result.worker_id
                # Contiguous shard by worker id (worker.py:166-179); ids
                # beyond total_workers wrap (vs the reference's skewed
                # coverage, SURVEY.md quirk 10). Recomputed each epoch: in
                # elastic mode the split covers the LIVE membership, so a
                # net-new joiner takes a fair slice instead of doubling up
                # on a shard.
                x_shard, y_shard = self._compute_shard(worker_id,
                                                       total_workers)
                batches = make_batches(x_shard, y_shard, cfg.batch_size,
                                       seed=cfg.seed * 1000 + epoch)
                if cfg.prefetch_batches > 0:
                    # Input double buffering: batch N+1's host->device
                    # upload overlaps batch N's compute (device_put is
                    # async dispatch; train/device_loop.py). Bitwise the
                    # same batches, off the critical path.
                    batches = prefetch_to_device(
                        batches, depth=cfg.prefetch_batches)
                for batch_idx, (xb, yb) in enumerate(batches):
                    boundary = batch_idx % k == 0
                    # One ROOT trace per loop iteration: fetch wait,
                    # compute, and push wait nest under it, the push's
                    # context crosses the wire, and the server's
                    # handler/store/apply spans join the same trace —
                    # the per-step causal tree the critical-path
                    # attribution consumes (analysis/traces.py).
                    step_span = trace_span(
                        "worker.step", root=True, worker=worker_id,
                        step=self.result.local_steps_completed,
                        epoch=epoch)
                    with step_span:
                        if boundary and batch_idx > 0:
                            with trace_span("worker.fetch_wait"):
                                params, fetched_step = \
                                    self._boundary_fetch(
                                        worker_id, fetched_step, params)
                            worker_id = self.result.worker_id

                        t_step = _tnow()
                        if local_sgd:
                            if boundary:
                                # Window open: adopt the fetched params as
                                # the local trajectory (fresh copy — the
                                # fused step donates) and zero the window
                                # accumulator.
                                local_params = jax.tree_util.tree_map(
                                    lambda a: jnp.array(a), params)
                                accum = jax.tree_util.tree_map(
                                    jnp.zeros_like, local_params)
                                accum_n = 0
                            with trace_span("worker.compute") as _csp, \
                                    self._gp(self._compute_category()):
                                (local_params, accum, batch_stats, loss,
                                 acc) = self._fused_step(
                                    local_params, accum, batch_stats,
                                    xb, yb, rng,
                                    self.result.local_steps_completed,
                                    local_lr)
                                if _csp.ctx is not None:
                                    jax.block_until_ready(accum)
                            grads = None
                        else:
                            with trace_span("worker.compute") as _csp, \
                                    self._gp(self._compute_category()):
                                grads, batch_stats, loss, acc = \
                                    self._grad_step(
                                        params, batch_stats, xb, yb, rng,
                                        self.result.local_steps_completed)
                                if _csp.ctx is not None:
                                    # Tracing: pin jax's async dispatch so
                                    # device time lands on THIS span
                                    # instead of on whichever later span
                                    # first materializes the grads (the
                                    # codec's device_get would otherwise
                                    # absorb the whole step and poison the
                                    # attribution).
                                    jax.block_until_ready(grads)
                        if self._nan_step is not None \
                                and self.result.local_steps_completed \
                                == self._nan_step:
                            # Deterministic compute-fault injection
                            # (WorkerConfig.nan_inject_step / DPS_NAN_STEP):
                            # poison THIS batch — the health report must
                            # flag it and the cluster monitor must alert.
                            nan = np.float32("nan")
                            if local_sgd:
                                # Poison the window accumulator — that is
                                # what gets pushed at the boundary.
                                accum = jax.tree_util.tree_map(
                                    lambda a: a * nan, accum)
                            else:
                                grads = jax.tree_util.tree_map(
                                    lambda a: a * nan, grads)
                            loss = loss * nan
                            print(f"fault injection: NaN gradients/loss at "
                                  f"worker={self.worker_name} local_step="
                                  f"{self.result.local_steps_completed}",
                                  flush=True)
                        if self._compute_faults is not None:
                            # Deterministic straggler injection: the sleep
                            # lands inside the step timing, so the health
                            # report's throughput and the straggler_lag
                            # rule see it like real slow compute.
                            self._compute_faults.maybe_delay_compute()
                        # Span = dispatch-to-return of the compiled step.
                        # Under jax async dispatch that can undercount
                        # device time on non-boundary batches; boundary
                        # steps (push/fetch) force completion, so the
                        # per-window totals stay honest.
                        self._tm_step_s.observe(_tnow() - t_step)
                        self._tm_steps.inc()
                        self.result.local_steps_completed += 1

                        if local_sgd:
                            accum_n += 1
                            if accum_n == k:
                                self._note_health(loss, accum, epoch,
                                                  grad_scale=1.0 / accum_n)
                                params, fetched_step = \
                                    self._dispatch_push_mean(
                                        worker_id, accum, accum_n,
                                        fetched_step, params)
                                worker_id = self.result.worker_id
                                accum, accum_n = None, 0
                        elif cfg.k_step_mode == "accumulate" and k > 1:
                            accum = grads if accum is None else \
                                jax.tree_util.tree_map(
                                    lambda a, b: a + b, accum, grads)
                            accum_n += 1
                            if accum_n == k:
                                self._note_health(loss, accum, epoch,
                                                  grad_scale=1.0 / accum_n)
                                params, fetched_step = \
                                    self._dispatch_push_mean(
                                        worker_id, accum, accum_n,
                                        fetched_step, params)
                                worker_id = self.result.worker_id
                                accum, accum_n = None, 0
                        elif boundary:
                            # Faithful: push THIS batch's gradients; the
                            # other K-1 batches' gradients are computed
                            # and dropped (quirk 7).
                            self._note_health(loss, grads, epoch)
                            params, fetched_step = self._dispatch_push(
                                worker_id, grads, fetched_step, params)
                            worker_id = self.result.worker_id

                    if gp is not None:
                        # Wall accrues step by step whether or not a
                        # category claimed it (residual -> 'other').
                        gp.tick_wall()
                    if self._draining or self._epoch_break:
                        # Directive: stop this epoch's batch loop at the
                        # step boundary (rebalance_shard resumes at the
                        # next epoch with a fresh shard; drain exits the
                        # run after the epoch bookkeeping below).
                        break

                # An epoch ending mid-window flushes the partial
                # accumulator, divided by the ACTUAL number of accumulated
                # batches — it must not leak into the next epoch's first
                # window (which would push a >K-batch sum divided by K,
                # against stale params).
                if accum is not None:
                    self._note_health(loss, accum, epoch,
                                      grad_scale=1.0 / accum_n)
                    params, fetched_step = self._dispatch_push_mean(
                        worker_id, accum, accum_n, fetched_step, params)
                    worker_id = self.result.worker_id
                    accum, accum_n = None, 0
                if self._pipe is not None:
                    # Epoch barrier: the epoch's last push must be ON the
                    # server before the epoch closes, so epoch timings and
                    # sync-round accounting match the serial loop; the
                    # prefetch RESULT survives into the next epoch's
                    # opening fetch.
                    try:
                        self._pipe.flush()
                    except Exception as e:  # noqa: BLE001 — session recovery
                        params, fetched_step = self._recover_session(e)
                        worker_id = self.result.worker_id

                self.result.epoch_times.append(time.time() - t_epoch)
                self._tm_epochs.inc()
                if cfg.eval_each_epoch:
                    with trace_span("worker.eval", root=True,
                                    worker=worker_id, epoch=epoch), \
                            self._gp("compute"):
                        self.result.test_accuracies.append(
                            self.evaluate(params, batch_stats))
                    self._tm_acc.set(self.result.test_accuracies[-1])
                # Per-epoch progress line (the reference workers logged
                # epochs to CloudWatch, worker.py:329-335);
                # run_wire_matrix's elastic cell also keys its mid-run kill
                # off this marker.
                acc = (f", test_acc={self.result.test_accuracies[-1]:.4f}"
                       if self.result.test_accuracies else "")
                print(f"EPOCH_DONE worker={self.worker_name} id={worker_id} "
                      f"epoch={epoch + 1}/{cfg.num_epochs} "
                      f"time={self.result.epoch_times[-1]:.1f}s{acc}",
                      flush=True)
                if gp is not None:
                    gp.tick_wall()  # eval + epoch bookkeeping wall
                if self._draining:
                    print(f"DRAINED worker={self.worker_name} "
                          f"id={worker_id} epoch={epoch + 1}", flush=True)
                    break
        finally:
            if self._goodput is not None:
                self._goodput.tick_wall()
            if self._pipe is not None:
                self._pipe.close()

    # -- session resume (docs/ROBUSTNESS.md) ---------------------------------

    @staticmethod
    def _session_lost(exc):
        """The SessionLostError behind ``exc`` (direct, or carried as the
        ``__cause__`` of a comms-pipeline RuntimeError), else None."""
        from ..comms.client import SessionLostError
        if isinstance(exc, SessionLostError):
            return exc
        cause = getattr(exc, "__cause__", None)
        if isinstance(cause, SessionLostError):
            return cause
        return None

    def _repush_viable(self, old_fetched: int, server_step: int) -> bool:
        """Worker-side half of the staleness semantics for a gradient
        stranded by a session loss: never push a gradient whose basis is
        AHEAD of the restored server (the down-weighting math assumes
        non-negative staleness), and don't bother re-sending one the async
        staleness gate would reject anyway. Sync mode accepts any
        contribution (the no-barrier round model, quirk 2)."""
        if server_step < old_fetched:
            return False
        cfg = getattr(self.store, "config", None)
        if getattr(cfg, "mode", "sync") == "async":
            from .semantics import DEFAULT_STALENESS_BOUND
            bound = getattr(cfg, "staleness_bound",
                            DEFAULT_STALENESS_BOUND)
            return server_step - old_fetched <= bound
        return True

    def _reconcile_inflight(self, worker_id: int, inflight,
                            server_step: int) -> str:
        """Decide the fate of the gradient that was mid-push when the
        session died: discard (stale or rewound basis) or re-push. The
        re-push prefers the client's recorded request — SAME exactly-once
        token, so a push the crashed server already applied and journaled
        replays as a duplicate instead of double-applying."""
        grads_tree, old_fetched = inflight
        if not self._repush_viable(old_fetched, server_step):
            return "discarded"
        repush = getattr(self.store, "repush_last", None)
        if callable(repush):
            accepted = repush(worker_id)
            if accepted is not None:
                if accepted:
                    self.result.pushes_accepted += 1
                else:
                    self.result.pushes_rejected += 1
                return "repushed"
        # No recorded request to replay (in-process store duck-typing):
        # fall back to a fresh push with the original basis step.
        self._push(worker_id, grads_tree, old_fetched)
        return "repushed"

    def _recover_session(self, exc, inflight=None):
        """The reconnect state machine: on SessionLostError (server died
        or restarted), drain the comms pipeline, re-register — under
        elastic membership the fresh registration takes the lowest free
        slot, so sync rounds re-size to the post-restart membership
        instead of wedging — re-fetch params at the restored server step,
        reconcile the in-flight gradient, and rebuild the pipeline.
        Bounded by ``reconnect_timeout`` with exponential backoff;
        disabled (0, the default) re-raises ``exc`` unchanged. Returns the
        fresh ``(params, fetched_step)`` the training loop adopts."""
        lost = self._session_lost(exc)
        cfg = self.config
        if lost is None or cfg.reconnect_timeout <= 0:
            raise exc
        if self._pipe is not None:
            # Drain/reset: capture the failed push (if that is what died)
            # for reconciliation, then retire the comms thread. A fresh
            # pipeline starts once the new session is up.
            failed = self._pipe.take_failed_item()
            if inflight is None:
                inflight = failed
            try:
                self._pipe.close()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
            self._pipe = None
        old_id = self.result.worker_id
        deadline = time.time() + cfg.reconnect_timeout
        delay = cfg.reconnect_backoff
        attempts = 0
        with trace_span("worker.reconnect", root=True,
                        worker=old_id) as sp, \
                self._gp("reconnect_recovery"):
            while True:
                attempts += 1
                try:
                    # The WHOLE resume attempt — register, refetch,
                    # reconcile — retries inside the window: a server
                    # that flaps again mid-refetch costs one backoff
                    # turn, not the worker (the reconcile re-push is
                    # idempotent: same token, journal-deduped).
                    # A channel that watched its server die can wedge in
                    # connect backoff even once the replacement listens
                    # on the same port — start every attempt on a fresh
                    # channel (RemoteStore.reset_channel; no-op for
                    # in-process stores).
                    reset = getattr(self.store, "reset_channel", None)
                    if callable(reset):
                        reset()
                    # Single registration attempt per turn of OUR backoff
                    # loop (the client's internal x5 backoff would blow
                    # through the reconnect window in one call).
                    if hasattr(self.store, "register_retries"):
                        worker_id, _ = self.store.register_worker(
                            self.worker_name, retries=1)
                    else:
                        worker_id, _ = self.store.register_worker(
                            self.worker_name)
                    # Fresh FULL fetch at the restored server step (the
                    # old session's delta basis is gone with the old
                    # server).
                    params, fetched_step = self._fetch_params(worker_id)
                    outcome = "none"
                    if inflight is not None:
                        outcome = self._reconcile_inflight(
                            worker_id, inflight, fetched_step)
                    break
                except ConnectionError as e:
                    if time.time() + delay > deadline:
                        sp.attrs["outcome"] = "gave_up"
                        from ..comms.client import SessionLostError
                        raise SessionLostError(
                            f"reconnect window "
                            f"({cfg.reconnect_timeout:.0f}s) exhausted "
                            f"after {attempts} attempts: {e}") from lost
                    time.sleep(delay)
                    delay = min(delay * 2.0, 10.0)
            self.result.worker_id = worker_id
            self.result.reconnects += 1
            self._tm_reconnect.inc()
            sp.attrs.update(attempts=attempts, new_worker_id=worker_id,
                            inflight=outcome)
            if cfg.overlap:
                self._pipe = _CommsPipeline(self, worker_id)
        print(f"RECONNECTED worker={self.worker_name} old_id={old_id} "
              f"new_id={worker_id} server_step={fetched_step} "
              f"attempts={attempts} inflight={outcome}", flush=True)
        return params, fetched_step

    def _boundary_fetch(self, worker_id: int, fetched_step: int, params):
        """The (pipeline-aware) boundary params fetch, resuming the
        session on failure. Returns (params pytree, fetched step).
        A pending ``refetch_params`` directive bypasses the delta basis
        (and any prefetched result) with a full fresh fetch."""
        try:
            with self._gp("fetch_wait"):
                pipe = self._pipe
                if pipe is not None and pipe.params_pending():
                    # The prefetch issued right after the window's push —
                    # its latency ran under the window's compute instead
                    # of on the critical path.
                    result = pipe.await_params()
                    if not self._force_full_fetch:
                        self._poll_directives()
                        if not self._force_full_fetch:
                            return result
                elif pipe is not None:
                    pipe.flush()  # a fetch must never overtake a push
                if self._force_full_fetch:
                    self._force_full_fetch = False
                    result = self._fetch_params(worker_id)
                else:
                    result = self._fetch_params(
                        worker_id,
                        have_step=fetched_step if params is not None
                        else None,
                        current=params)
                self._poll_directives()
                return result
        except Exception as e:  # noqa: BLE001 — session recovery
            return self._recover_session(e)

    def _dispatch_push(self, worker_id: int, grads_tree,
                       fetched_step: int, params):
        """Push now (serial) or hand to the comms pipeline with a prefetch
        of the next params riding behind it (overlapped). Returns the
        (params, fetched_step) the loop should continue with — unchanged
        on the happy path, the restored server state after a session
        resume.

        The push_wait span is the training thread's blocked time either
        way: the full push RPC when serial, the single-slot backpressure
        when overlapped (near zero while the pipeline keeps up — the
        overlap win, visible per step in the trace)."""
        if self._skip_quarantined_push():
            return params, fetched_step
        with trace_span("worker.push_wait"), self._gp("push_wait"):
            item = grads_tree
            try:
                if self._pipe is None:
                    self._push(worker_id, grads_tree, fetched_step)
                else:
                    # Overlapped path: ENCODE at dispatch, on the training
                    # thread — the device quantize/pack is dispatched (and
                    # its EF residual carried) in program order before the
                    # next window's gradients touch it; the comms thread
                    # later pulls only the finished packed bytes.
                    payload = self._maybe_encode_device(grads_tree)
                    if payload is not None:
                        item = payload
                    self._pipe.submit(item, fetched_step,
                                      prefetch_current=params)
                self._poll_directives()
                return params, fetched_step
            except Exception as e:  # noqa: BLE001 — push recovery
                return self._recover_push(e, item, fetched_step)

    def _dispatch_push_mean(self, worker_id: int, accum_tree, n: int,
                            fetched_step: int, params):
        if self._skip_quarantined_push():
            return params, fetched_step
        with trace_span("worker.push_wait"), self._gp("push_wait"):
            item = None
            try:
                if self._pipe is None:
                    self._push_mean(worker_id, accum_tree, n, fetched_step)
                else:
                    item = _window_mean(accum_tree, n)
                    payload = self._maybe_encode_device(item)
                    if payload is not None:
                        item = payload
                    self._pipe.submit(item, fetched_step,
                                      prefetch_current=params)
                self._poll_directives()
                return params, fetched_step
            except Exception as e:  # noqa: BLE001 — push recovery
                grads = item if item is not None \
                    else _window_mean(accum_tree, n)
                return self._recover_push(e, grads, fetched_step)

    def _skip_quarantined_push(self) -> bool:
        """Quarantine directive: this window's push stays local (the
        server refuses it anyway); the window counts down so training
        resumes pushing automatically."""
        if self._quarantine_windows <= 0:
            return False
        self._quarantine_windows -= 1
        self.result.pushes_quarantined += 1
        return True

    def _recover_push(self, exc, grads_tree, fetched_step: int):
        """Session recovery from a push dispatch. Serial case: THIS push
        died mid-RPC — it is the in-flight gradient to reconcile.
        Pipelined case: ``submit`` surfaced a PREVIOUS item's failure
        (that item is reconciled from the pipeline's failed slot) and
        this window's gradients never left — send them after the resume
        if still viable against the restored step."""
        pipelined = self._pipe is not None
        inflight = None if pipelined else (grads_tree, fetched_step)
        params, new_step = self._recover_session(exc, inflight=inflight)
        if pipelined and self._repush_viable(fetched_step, new_step):
            try:
                self._push(self.result.worker_id, grads_tree, fetched_step)
            except Exception as e2:  # noqa: BLE001 — double-flap handoff
                # The server flapped AGAIN between the resume and this
                # send: this push is now the in-flight gradient of a new
                # session loss — recover once more (bounded by its own
                # reconnect window).
                params, new_step = self._recover_session(
                    e2, inflight=(grads_tree, fetched_step))
        return params, new_step

    def _fetch_params(self, worker_id: int, have_step: int | None = None,
                      current=None):
        """One FetchParameters round trip -> (params pytree, fetched step).

        With ``have_step`` + ``current`` (the pytree fetched at that step)
        and a delta-capable store, a NOT_MODIFIED reply hands back
        ``current`` unchanged — the params a full refetch would have
        returned byte-for-byte, since the canonical step didn't move."""
        use_delta = (have_step is not None and current is not None
                     and self.config.delta_fetch
                     and getattr(self.store, "supports_delta_fetch", False))
        if use_delta:
            flat, fetched_step = self.store.fetch(worker_id,
                                                  have_step=have_step)
            if not flat and fetched_step == have_step:
                self._tm_fetch_nm.inc()
                return current, fetched_step
        else:
            flat, fetched_step = self.store.fetch(worker_id)
        with trace_span("worker.codec", stage="decode"), self._gp("codec"):
            if (getattr(self.store, "fetch_codec", "none")
                    in ("fp16", "bf16")
                    and not getattr(self.store, "decompresses_fetches",
                                    False)):
                # In-process compressed fetch (RemoteStore already
                # decompressed client-side — casting again would copy the
                # full parameter set a second time per fetch for nothing).
                flat = fp16_decompress(flat)
            if not getattr(self.store, "keeps_device_arrays", False):
                # Decoded (fp32) payload bytes; the on-the-wire size
                # lives in the RPC-layer counters (device stores move
                # zero bytes — skip).
                self._tm_fetch_post.inc(
                    sum(int(v.nbytes) for v in flat.values()))
            self._last_fetched_step = fetched_step
            return unflatten_params(flat), fetched_step

    def _push_mean(self, worker_id, accum_tree, n: int,
                   fetched_step) -> None:
        """Push the mean of an accumulated gradient window of n batches."""
        self._push(worker_id, _window_mean(accum_tree, n), fetched_step)

    def _gradient_scales(self) -> dict:
        """The server-published per-layer absmax table (shared-scale
        quantization, docs/WIRE_PROTOCOL.md): read directly off in-process
        stores, from the registration/fetch-refreshed cache on a
        RemoteStore. Empty ({}) degrades to per-push scales."""
        fn = getattr(self.store, "gradient_scales", None)
        if not callable(fn):
            return {}
        try:
            scales, _ = fn()
            return scales
        except Exception:  # noqa: BLE001 — scales are an optimization hint
            return {}

    def _note_d2h_overlap(self, seconds: float) -> None:
        """Record device->host gradient-pull seconds that ran on the comms
        pipeline thread — pull time the training thread did NOT block on
        (the double-buffered-transfer win). Serial pulls block the trainer
        and are not 'saved'."""
        pipe = self._pipe
        if pipe is not None and threading.current_thread() is pipe._thread:
            self._tm_d2h_saved.observe(seconds)

    def _maybe_encode_device(self, grads_tree):
        """Device-resident encode of a push, if it applies: returns a
        DevicePayload (quantize/pack dispatched on the accelerator, packed
        bytes copying to the host in the background) or None when the
        NumPy reference path in ``_push`` should handle it (codec off,
        non-quantized codec, or a host-resident tree)."""
        if self._device_codec is None \
                or isinstance(grads_tree, DevicePayload):
            return None
        flat = flatten_params(grads_tree, as_numpy=False)
        if not is_device_tree(flat):
            return None
        plan = self._bitwidth.plan(flat) if self._bitwidth else None
        return self._device_codec.encode(
            flat, plan=plan, scales=self._gradient_scales())

    def _push(self, worker_id, grads_tree, fetched_step) -> None:
        with trace_span("worker.codec", stage="encode"), self._gp("codec"):
            if getattr(self.store, "keeps_device_arrays", False):
                # Device-resident store: hand over the device arrays
                # untouched — no host round-trip, no wire, no codec.
                flat = flatten_params(grads_tree, as_numpy=False)
                pre_bytes = 0
            else:
                payload = grads_tree \
                    if isinstance(grads_tree, DevicePayload) \
                    else self._maybe_encode_device(grads_tree)
                if payload is not None:
                    # Device codec: the quantize/pack already ran on the
                    # accelerator (at dispatch time when pipelined);
                    # finalize pulls ONLY the packed wire bytes.
                    t0 = _tnow()
                    flat = self._device_codec.finalize(payload)
                    pull_s = _tnow() - t0
                    self._note_d2h_overlap(pull_s)
                    self._tm_codec_s.observe(
                        payload.encode_seconds + pull_s)
                    pre_bytes = payload.pre_bytes
                else:
                    t0 = _tnow()
                    flat = flatten_params(jax.device_get(grads_tree))
                    self._note_d2h_overlap(_tnow() - t0)
                    pre_bytes = sum(int(v.nbytes) for v in flat.values())
                    # Worker-side compression (worker.py:264-268): the
                    # store/service advertises its codec; the encode
                    # happens here, once, before the wire (fp16 = the
                    # reference's cast; the quantized family — int8/int4/
                    # topk/adaptive — quantizes per the bitwidth
                    # controller's per-layer plan, against the server's
                    # shared scales when published, with error feedback
                    # carrying the residual).
                    codec = getattr(self.store, "push_codec", "none")
                    t1 = _tnow()
                    if codec == "fp16":
                        flat = fp16_compress(flat)
                        self._tm_codec_s.observe(_tnow() - t1)
                    elif codec in QUANTIZED_PUSH_CODECS:
                        plan = self._bitwidth.plan(flat) if self._bitwidth \
                            else None
                        flat = compress_push(
                            flat, plan, scales=self._gradient_scales(),
                            ef=self._ef, topk_frac=self.config.topk_frac)
                        self._tm_codec_s.observe(_tnow() - t1)
                wire_bytes = sum(int(v.nbytes) for v in flat.values())
                self._tm_push_pre.inc(pre_bytes)
                self._tm_push_wire.inc(wire_bytes)
                self._tm_push_saved.inc(max(0, pre_bytes - wire_bytes))
                if pre_bytes:
                    # Effective bits per gradient VALUE this push (fp32
                    # payload carries pre_bytes/4 values).
                    self._tm_push_bits.set(
                        round(wire_bytes * 32.0 / pre_bytes, 3))
        t0 = _tnow()
        if self.store.push(worker_id, flat, fetched_step):
            self.result.pushes_accepted += 1
        else:
            self.result.pushes_rejected += 1
        done = _tnow()
        if self._bitwidth is not None and self._prev_push_done is not None:
            # Link pressure = push RPC seconds over the window since the
            # previous push completed (adaptive codec only).
            self._bitwidth.note_push(done - t0, done - self._prev_push_done)
        self._prev_push_done = done

    def evaluate(self, params, batch_stats) -> float:
        """Full test-set top-1 (worker.py:313-331)."""
        from ..train.train_state import TrainState  # light TrainState shim
        import optax
        state = TrainState.create(
            apply_fn=self.model.apply, params=params,
            batch_stats=batch_stats, tx=optax.identity())
        # Device-resident test set, shared by every worker in the process:
        # uploaded once instead of ~30 MB per eval (the remote-attach link
        # is slow; see ps/device_store.py). Benign create race: last wins.
        cache = getattr(self.dataset, "_device_test_cache", None)
        if cache is None:
            import jax.numpy as jnp
            cache = (jnp.asarray(self.dataset.x_test),
                     jnp.asarray(self.dataset.y_test.astype(np.int32)))
            self.dataset._device_test_cache = cache
        x_te, y_te = cache
        correct = total = 0
        for xb, yb in make_batches(x_te, y_te,
                                   self.config.eval_batch_size,
                                   shuffle=False, drop_remainder=False):
            c, t = self._eval_step(state, xb, yb)
            correct += int(c)
            total += int(t)
        return correct / max(total, 1)


def run_workers(store: ParameterStore, model, dataset: Dataset,
                n_workers: int, config: WorkerConfig | None = None,
                timeout: float | None = None) -> list[WorkerResult]:
    """Spawn N worker threads sharing one compiled step; join them all.

    The in-process equivalent of launching N Fargate worker tasks
    (terraform/main.tf:387-435).
    """
    config = config or WorkerConfig()
    grad_step = make_grad_step(model, augment=config.augment)
    eval_step = jax.jit(make_eval_step())
    # local_sgd workers share ONE donated fused compile too (same shapes
    # => one executable; each call donates its own buffers).
    fused_step = make_fused_local_step(model, augment=config.augment) \
        if config.k_step_mode == "local_sgd" else None
    workers = [
        PSWorker(store, model, dataset, config, grad_step=grad_step,
                 eval_step=eval_step, fused_step=fused_step,
                 worker_name=f"worker-{i}")
        for i in range(n_workers)
    ]
    for w in workers:
        w.start()
    # Failure-detection reaper: with a worker_timeout configured, expire
    # silent workers periodically so elastic rounds shrink instead of
    # wedging on a dead worker (the capability behind --worker-timeout).
    reaper_stop = threading.Event()
    wt = getattr(store.config, "worker_timeout", None)
    if wt:
        def _reap():
            while not reaper_stop.wait(wt / 2):
                expired = store.expire_stale_workers()
                if expired:
                    print(f"expired silent workers: {expired}")
        threading.Thread(target=_reap, daemon=True).start()
    try:
        for w in workers:
            w.join(timeout)
    finally:
        reaper_stop.set()
    for w in workers:
        if w.result.error is not None:
            raise w.result.error
    return [w.result for w in workers]
