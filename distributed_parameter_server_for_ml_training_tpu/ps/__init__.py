"""Parameter store: the async (bounded-staleness) half of the framework.

SPMD cannot express per-worker asynchrony, so — per the north-star design
(BASELINE.json) — async mode is hosted as a parameter store on the TPU host
CPU. Worker threads drive jit-compiled local gradient steps on device and
push/pull through the store's in-process API, which preserves the reference's
4-RPC lifecycle (src/communication/ps.proto:4-19): register / fetch / push /
finished. A gRPC service wraps the same store for multi-host deployments.
"""

from .semantics import (
    staleness_weight,
    mean_gradients,
    sgd_apply,
    DEFAULT_STALENESS_BOUND,
)
from .device_store import DeviceParameterStore
from .sharding import (SHARD_SLOTS, ShardInfo, partition_keys,
                       shard_for_key, validate_shard_map)
from .store import ParameterStore, StoreConfig
from .supervisor import SupervisorConfig, WorkerSupervisor
from .worker import PSWorker, WorkerConfig, WorkerResult, run_workers


def make_store(backend: str, flat_params, config: StoreConfig):
    """Build a parameter store by backend name: 'python' (host numpy),
    'native' (C++ arena), or 'device' (HBM-resident)."""
    if backend == "native":
        from ..native import NativeParameterStore
        return NativeParameterStore(flat_params, config)
    if backend == "device":
        return DeviceParameterStore(flat_params, config)
    if backend != "python":
        raise ValueError(f"unknown store backend {backend!r}")
    return ParameterStore(flat_params, config)


__all__ = [
    "ParameterStore",
    "DeviceParameterStore",
    "make_store",
    "SHARD_SLOTS",
    "ShardInfo",
    "partition_keys",
    "shard_for_key",
    "validate_shard_map",
    "StoreConfig",
    "SupervisorConfig",
    "WorkerSupervisor",
    "PSWorker",
    "WorkerConfig",
    "WorkerResult",
    "run_workers",
    "staleness_weight",
    "mean_gradients",
    "sgd_apply",
    "DEFAULT_STALENESS_BOUND",
]
