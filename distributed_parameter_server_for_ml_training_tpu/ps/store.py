"""In-process parameter store with the reference server's exact semantics.

This is the TPU re-hosting of ``src/parameter_server/server.py``: canonical
parameters live on the host CPU as a flat ``{name: np.ndarray}`` dict
(server.py:96), guarded by the same three-lock structure — ``param_lock``
(apply + fetch-serialize, server.py:97), ``sync_lock`` (pending-gradient
barrier, server.py:114), ``registration_lock`` (id assignment, server.py:103).

Faithful behaviors reproduced deliberately (SURVEY.md appendix):

- quirk 2: sync push returns immediately — no worker-side barrier; the round
  completes whenever the count reaches ``total_workers`` (server.py:264-288),
- quirk 3: a double push before the round completes OVERWRITES that worker's
  pending entry while still incrementing ``gradients_received`` — a round can
  complete with fewer than N distinct contributions (server.py:267-268).
  ``strict_rounds=True`` opts into the corrected behavior (count distinct
  workers instead),
- quirk 4: ``fetched_step`` is the global step the worker last fetched, so
  staleness = versions-behind (server.py:293-294, worker.py:299),
- worker-count validation 1..32 (server.py:424-426),
- ``last_seen`` tracked on fetch/push but never expired (server.py:219, 251),
- final stats printed when the active-worker set empties (server.py:315-316).

Wire codec: pushes are fp16-compressed by default — and fetches are NOT —
matching the reference's asymmetry (push: worker.py:264-268 casts fp16;
fetch: server.py:222 pickles fp32).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from ..ops.compression import (  # hot-path imports hoisted: no import-lock
    PUSH_CODECS,                 # checks inside push/fetch
    QUANTIZED_PUSH_CODECS,
    bf16_compress,
    fp16_compress,
    fp16_decompress,
    homomorphic_mean,
    is_quantized_payload,
    payload_logical_shapes,
    wire_decompress,
)
from ..telemetry import now as _tnow, trace_span
from .semantics import (
    DEFAULT_STALENESS_BOUND,
    mean_gradients,
    sgd_apply,
    staleness_weight,
)

MAX_WORKERS = 32  # server.py:424-426


@dataclass
class StoreConfig:
    mode: str = "sync"  # 'sync' | 'async' (server.py --mode)
    total_workers: int = 4
    learning_rate: float = 0.1  # server.py:84, 413
    staleness_bound: int = DEFAULT_STALENESS_BOUND
    # 'none' | 'fp16' | 'int8' | 'int4' | 'topk' | 'adaptive' | None =
    # backend default ('fp16' for the wire-crossing python/native stores,
    # matching the reference's worker-side cast (worker.py:264-268);
    # 'none' for the device store, which crosses no wire). 'int8'
    # (per-tensor symmetric quantization, ~half fp16's bytes) decodes on
    # the python store (host numpy) and the native store (fused C++
    # dequant+apply). 'int4' (packed nibbles, ~1/8 fp32), 'topk' (sparse
    # triples), and 'adaptive' (worker picks int8/int4/topk per layer from
    # link pressure) are python-store codecs; workers pair them with
    # error feedback (docs/WIRE_PROTOCOL.md). Stores resolve the sentinel
    # at construction.
    push_codec: str | None = None
    # Compressed-domain sync aggregation (THC-style, PAPERS.md): quantized
    # pushes are held as-is and summed in per-layer int32 accumulators,
    # dequantized ONCE per round at apply time — the per-push fp32 decode
    # disappears. False restores decode-per-push (the A/B control in
    # experiments/run_compression_matrix.py); numerics agree to float
    # rounding either way.
    compressed_domain: bool = True
    # Fetch-side wire codec. 'none' (default) = reference parity: fetches
    # are fp32, reproducing its dominant server cost (the ~45 MB re-pickle
    # per fetch, server.py:222). 'bf16'/'fp16' opt in to halving the
    # params-in wire term; workers/clients decompress after fetch.
    fetch_codec: str = "none"
    strict_rounds: bool = False  # True = corrected double-push semantics
    # Membership expiry. The reference tracks last_seen but NEVER expires
    # workers (server.py:219, 251) — restarted workers pollute membership
    # (SURVEY.md quirk 10). None reproduces that; a number of seconds turns
    # on the corrected behavior via expire_stale_workers().
    worker_timeout: float | None = None
    # Elastic membership (net-new; the reference's only "elasticity" was ECS
    # restarting tasks, which inflated worker ids and skewed shards,
    # README.md:368-371). When True:
    #   - a registering worker takes the LOWEST free id slot, so a
    #     replacement adopts the dead worker's data shard,
    #   - sync rounds complete at the CURRENT active-worker count instead of
    #     the fixed total, so training continues while a slot is empty,
    #   - expiry purges the dead worker's pending gradients and completes
    #     the round if the survivors already cover it.
    elastic: bool = False
    # Quorum rounds (self-healing, docs/ROBUSTNESS.md): a sync round
    # completes once this many DISTINCT workers of the live round target
    # have pushed — an int >= 1 is an absolute count, 0 < f < 1 a fraction
    # of the target (ceil) — instead of waiting for every worker. One
    # slow-but-alive straggler then costs the round nothing; its late
    # push reconciles through the async staleness semantics (weighted
    # apply, bounded) rather than blocking the barrier or polluting the
    # next round. None keeps the full barrier (reference behavior).
    sync_quorum: float | None = None
    # Per-round deadline in seconds, armed when the round's FIRST gradient
    # lands: when it fires, the round completes with whatever has arrived
    # (>= 1 contribution). Composable with sync_quorum (whichever trips
    # first); None disables.
    round_deadline: float | None = None
    # Shard identity (docs/SHARDING.md): when shard_count > 1 this store
    # holds only the key subset consistent-hashing assigns to shard_index
    # (cli serve filters the init params via ps/sharding.partition_keys).
    # Carried in checkpoints so a restore into the WRONG shard slot — or
    # into a differently-partitioned topology — is refused instead of
    # silently serving another shard's tensors.
    shard_index: int = 0
    shard_count: int = 1
    # Tenancy (docs/TENANCY.md): which job's namespace this store IS.
    # "default" is the pre-tenancy server (bare key names, legacy wire);
    # non-default stores are built by ps/tenancy.JobManager from a job
    # spec and carry the id into checkpoint meta (v4) so restore refuses
    # cross-job, mirroring shard_index/shard_count above.
    job_id: str = "default"

    def __post_init__(self):
        from .tenancy import is_valid_job_id  # cold path; avoids cycle
        if not is_valid_job_id(self.job_id):
            raise ValueError(
                f"job_id must match [A-Za-z0-9][A-Za-z0-9_-]* "
                f"(<= 64 chars), got {self.job_id!r}")
        if self.mode not in ("sync", "async"):
            raise ValueError(f"mode must be sync|async, got {self.mode!r}")
        if not 1 <= self.total_workers <= MAX_WORKERS:
            raise ValueError(
                f"total_workers must be 1..{MAX_WORKERS} (server.py:424-426),"
                f" got {self.total_workers}")
        if self.fetch_codec not in ("none", "fp16", "bf16"):
            raise ValueError(f"fetch_codec must be none|fp16|bf16, got "
                             f"{self.fetch_codec!r}")
        if self.sync_quorum is not None:
            q = float(self.sync_quorum)
            if q <= 0:
                raise ValueError(f"sync_quorum must be > 0, got {q}")
            if q >= 1.0 and q != int(q):
                raise ValueError(
                    f"sync_quorum >= 1 is a worker COUNT and must be "
                    f"whole, got {q} (use a value < 1 for a fraction)")
        if self.round_deadline is not None and self.round_deadline <= 0:
            raise ValueError(
                f"round_deadline must be > 0 seconds, got "
                f"{self.round_deadline}")
        if self.sync_quorum is not None or self.round_deadline is not None:
            # Quorum counting must count DISTINCT workers — under the
            # faithful quirk-3 semantics (overwrite the entry, increment
            # the counter anyway) ONE worker double-pushing could satisfy
            # a 2-worker quorum alone and the round would aggregate a
            # single contribution. Quorum therefore implies the corrected
            # strict_rounds accounting (regression-pinned in
            # tests/test_selfheal.py).
            self.strict_rounds = True
        if self.shard_count < 1 or not \
                0 <= self.shard_index < self.shard_count:
            raise ValueError(
                f"shard_index must be in [0, shard_count) with "
                f"shard_count >= 1; got index={self.shard_index} "
                f"count={self.shard_count}")


@dataclass
class _Stats:
    gradients_processed: int = 0
    gradients_rejected: int = 0
    total_parameter_updates: int = 0
    staleness_values: list = field(default_factory=list)
    update_times: deque = field(default_factory=lambda: deque(maxlen=100))
    start_time: float = field(default_factory=time.time)


class MembershipMixin:
    """Worker-lifecycle surface shared by the Python and native stores:
    sequential id assignment under a registration lock (server.py:190-211),
    JobFinished accounting (server.py:306-318), and the corrected-semantics
    expiry (no-op when ``worker_timeout`` is None, the faithful default —
    the reference tracks ``last_seen`` but never expires, server.py:219,251).

    Expects the host class to provide ``config``, ``_registration_lock``,
    ``_next_worker_id``, ``active_workers``, ``last_seen`` and
    ``_finished_event``.
    """

    # Membership state is the mixin's contract even though the concrete
    # store's __init__ constructs it; declared here so tools/dpslint
    # checks every method that touches it (lock-guard rule).
    _next_worker_id: int  # guarded by: self._registration_lock
    active_workers: set  # guarded by: self._registration_lock
    last_seen: dict  # guarded by: self._registration_lock

    def register_worker(self, worker_name: str = "") -> tuple[int, int]:
        """Returns (worker_id, total_workers).

        Faithful mode assigns strictly sequential ids (server.py:193-194);
        elastic mode reuses the lowest free slot so a replacement worker
        adopts the departed worker's shard.
        """
        with self._registration_lock:
            if getattr(self.config, "elastic", False):
                worker_id = next(i for i in range(len(self.active_workers) + 1)
                                 if i not in self.active_workers)
                self._next_worker_id = max(self._next_worker_id, worker_id + 1)
            else:
                worker_id = self._next_worker_id
                self._next_worker_id += 1
            self.active_workers.add(worker_id)
            self.last_seen[worker_id] = time.time()
        return worker_id, self.config.total_workers

    def job_finished(self, worker_id: int) -> None:
        """Remove from the active set; final stats fire when it empties."""
        with self._registration_lock:
            self.active_workers.discard(worker_id)
            empty = not self.active_workers
        # Elastic: a departure shrinks the round target, so the pending
        # round may already be satisfied — re-evaluate WITHOUT purging (a
        # clean departure's final push is a valid contribution; only dead
        # workers' pending grads are purged, in _on_workers_expired).
        self._on_worker_departed(worker_id)
        if empty:
            self._finished_event.set()

    def wait_all_finished(self, timeout: float | None = None) -> bool:
        return self._finished_event.wait(timeout)

    def membership_snapshot(self) -> list[int]:
        """Sorted copy of the live worker ids, taken under the registration
        lock (safe against concurrent register/finish/expire)."""
        with self._registration_lock:
            return sorted(self.active_workers)

    def _round_target(self) -> int:
        """Sync-round completion size: fixed total (server.py:271-274) or,
        in elastic mode, the live membership count (snapshotted under the
        registration lock — callers hold only the sync lock, and a racing
        register/expire must not yield a torn count; lock order sync ->
        registration is safe because no path takes them the other way
        round). Workers quorum-EXCLUDED by the remediation layer
        (``exclude_worker``) leave the target either way — rounds stop
        waiting for them, their own pushes still land."""
        excluded = getattr(self, "_excluded", None)
        if getattr(self.config, "elastic", False):
            with self._registration_lock:
                if excluded:
                    return max(1, len(self.active_workers - excluded))
                return max(1, len(self.active_workers))
        if excluded:
            return max(1, self.config.total_workers - len(excluded))
        return self.config.total_workers

    def _on_workers_expired(self, stale: list[int]) -> None:
        """Hook for stores to clean round state after expiry (no-op here)."""

    def _on_worker_departed(self, worker_id: int) -> None:
        """Hook after a clean JobFinished departure (no-op here)."""

    def expire_stale_workers(self) -> list[int]:
        """Failure detection: drop workers not seen within the timeout —
        liveness comes from pushes, fetches, and the heartbeat ping."""
        if self.config.worker_timeout is None:
            return []
        cutoff = time.time() - self.config.worker_timeout
        with self._registration_lock:
            stale = [w for w in self.active_workers
                     if self.last_seen.get(w, 0.0) < cutoff]
            for w in stale:
                self.active_workers.discard(w)
            empty = not self.active_workers
        if stale:
            self._on_workers_expired(stale)
        if stale and empty:
            self._finished_event.set()
        return stale


class TelemetryMixin:
    """Store-side live instruments (telemetry/), shared by all three
    backends (python, device, native). Instruments are created ONCE at
    store construction and held as attributes — the registry dict is never
    touched on the hot path (telemetry/registry.py constraint 1). A
    process's stores of the same backend share instruments (identical
    name+labels), so counters aggregate across them; the step gauge then
    reports the most recent writer, which is what a live dashboard wants.
    """

    def _init_telemetry(self) -> None:
        from ..telemetry import STALENESS_BUCKETS, get_registry
        reg = get_registry()
        b = self.store_backend
        self._tm_push_s = reg.histogram("dps_store_push_seconds", backend=b)
        self._tm_fetch_s = reg.histogram("dps_store_fetch_seconds",
                                         backend=b)
        self._tm_apply_s = reg.histogram("dps_store_apply_seconds",
                                         backend=b)
        self._tm_push_ok = reg.counter("dps_store_pushes_total", backend=b,
                                       outcome="accepted")
        self._tm_push_rej = reg.counter("dps_store_pushes_total", backend=b,
                                        outcome="rejected")
        self._tm_fetches = reg.counter("dps_store_fetches_total", backend=b)
        # Version-gated delta fetches answered with an empty NOT_MODIFIED
        # payload (fetch(have_step=...) when the step hasn't advanced) —
        # the not-modified ratio is this over dps_store_fetches_total.
        self._tm_fetch_nm = reg.counter("dps_store_fetch_not_modified_total",
                                        backend=b)
        # Observed for EVERY arriving async push (accepted or not): the
        # arrival distribution is the signal adaptive-staleness policies
        # need (PAPERS.md: ACE-Sync); stats.staleness_values keeps the
        # reference's accepted-only semantics for the exit line.
        self._tm_staleness = reg.histogram("dps_store_staleness_versions",
                                           buckets=STALENESS_BUCKETS,
                                           backend=b)
        self._tm_step = reg.gauge("dps_store_global_step", backend=b)
        self._tm_rounds = reg.counter("dps_store_sync_rounds_total",
                                      backend=b)
        # Pushes held in the quantized domain (no per-push fp32 decode;
        # summed in int32 accumulators at round completion) — the
        # compressed-domain aggregation fast path, live.
        self._tm_compressed = reg.counter(
            "dps_store_compressed_accum_total", backend=b)
        # Self-healing round surface (docs/ROBUSTNESS.md): what closed
        # each sync round (full barrier / quorum / deadline expiry),
        # stragglers' late pushes reconciled via the staleness path, and
        # the live quorum-exclusion set size.
        self._tm_round_trigger = {
            trig: reg.counter("dps_store_round_completions_total",
                              backend=b, trigger=trig)
            for trig in ("full", "quorum", "deadline")
        }
        self._tm_late = reg.counter("dps_store_late_pushes_total",
                                    backend=b)
        self._tm_excluded = reg.gauge("dps_store_excluded_workers",
                                      backend=b)


class AggregationBase(TelemetryMixin, MembershipMixin):
    """Sync-round / async-apply orchestration shared by every in-process
    store backend (host numpy, device HBM). Subclasses supply the three
    kernels — ``_mean(grad_dicts)``, ``_apply(grads, lr, weight)`` (must
    bump ``global_step`` under ``_param_lock`` semantics chosen by the
    subclass) is split here as apply-only; and ``_after_apply()`` (e.g.
    device sync) — plus the ``store_backend`` label for metrics.
    """

    store_backend = "python"

    #: Whether fetch() accepts ``have_step`` and answers NOT_MODIFIED
    #: (empty payload) when the canonical step hasn't advanced. Backends
    #: that can't check the step without materializing the payload (the
    #: native C++ arena's seqlock fetch) leave this False.
    supports_delta_fetch = False

    # Cross-thread contracts (tools/dpslint lock-guard): pusher threads,
    # the round-deadline Timer, and the reaper all meet on this state.
    parameters: dict  # guarded by: self._param_lock
    global_step: int  # guarded by: self._param_lock
    _pending: dict  # guarded by: self._sync_lock
    _gradients_received: int  # guarded by: self._sync_lock
    _round_serial: int  # guarded by: self._sync_lock
    _deadline_timer: object  # guarded by: self._sync_lock
    _last_round_trigger: object  # guarded by: self._sync_lock
    _excluded: set  # guarded by: self._registration_lock

    def _mean(self, grad_dicts: list) -> dict:
        raise NotImplementedError

    def _apply(self, grads: dict, lr: float, weight: float = 1.0) -> None:
        """Apply p -= lr*weight*g to self.parameters (no locking here)."""
        raise NotImplementedError

    def _init_round_state(self) -> None:
        """Quorum-round bookkeeping (called from each concrete __init__
        alongside ``_init_telemetry``): the exclusion set the remediation
        layer edits, the round serial that fences stale deadline timers,
        and the armed timer itself."""
        self._excluded: set[int] = set()
        self._round_serial = 0
        self._deadline_timer: threading.Timer | None = None
        self._last_round_trigger: str | None = None

    def _after_apply(self):
        """Hook after an update is issued. Return contract: anything but
        ``False`` means the hook synchronized with (or is) the real
        completion of the update, and the caller records an update_times
        entry; return ``False`` to decline (the device store samples its
        waits — only every Nth update blocks on the device — so timings
        stay honest without a round trip per update)."""

    def _round_update(self, grad_dicts: list, lr: float) -> None:
        """One sync-round update: aggregate then apply + bump the step.
        The mean runs OUTSIDE the param lock (it touches only the stashed
        gradients); subclasses may override with a fused kernel."""
        mean = self._mean(grad_dicts)
        with self._param_lock:
            self._apply(mean, lr)
            self.global_step += 1

    def _quorum_mode(self) -> bool:
        return (getattr(self.config, "sync_quorum", None) is not None
                or getattr(self.config, "round_deadline", None) is not None)

    def _quorum_target(self, full: int) -> int:
        """Contributions that complete a round: the full target, or the
        configured quorum (count, or ceil of a fraction of the target),
        clamped to [1, full]."""
        q = getattr(self.config, "sync_quorum", None)
        if q is None:
            return full
        q = float(q)
        n = math.ceil(q * full - 1e-9) if q < 1.0 else int(q)
        return max(1, min(full, n))

    def _push_sync(self, worker_id: int, grads: dict,
                   fetched_step: int | None = None) -> bool:
        """server.py:264-288: stash under sync_lock; when the round hits
        its (quorum) target, mean + apply + reset. No barrier — returns
        immediately. In quorum mode a LATE push — one whose basis round
        already closed under quorum/deadline — reconciles through the
        async staleness semantics instead of being stashed against a
        stale basis (docs/ROBUSTNESS.md)."""
        # Routing pre-check only: an unlocked step read is fine here —
        # the late path re-checks staleness under _param_lock, and a push
        # mis-routed into the round path was on time by definition.
        if self._quorum_mode() and fetched_step is not None \
                and fetched_step < self.global_step:  # dpslint: ignore[lock-guard]
            return self._push_late(worker_id, grads, fetched_step)
        with self._sync_lock:
            if self.config.strict_rounds:
                # Corrected semantics: count distinct workers.
                self._pending[worker_id] = grads
                self._gradients_received = len(self._pending)
            else:
                # Faithful quirk 3 (server.py:267-268): overwrite the entry,
                # increment the count anyway.
                self._pending[worker_id] = grads
                self._gradients_received += 1
            self._arm_deadline_locked()
            finish = self._maybe_complete_round_locked()
            self.stats.gradients_processed += 1
        self._tm_push_ok.inc()
        if finish is not None:
            finish()
        return True

    def _push_late(self, worker_id: int, grads: dict,
                   fetched_step: int) -> bool:
        """A straggler's push that missed its round (quorum/deadline
        completed it): apply it through the existing async staleness
        semantics — down-weighted immediate apply, rejected past the
        staleness bound — so the contribution is neither double-counted
        into the next round nor silently dropped."""
        self._tm_late.inc()
        if is_quantized_payload(grads):
            # The compressed-domain hold-as-is path is a round
            # optimization; a late single-payload apply needs fp32.
            grads = wire_decompress(grads)
        return self._push_async(worker_id, grads, fetched_step)

    def _arm_deadline_locked(self) -> None:
        """Arm the per-round deadline timer on the round's first gradient
        (caller holds ``_sync_lock``). The timer captures the round
        serial, so a stale timer firing after its round completed is a
        no-op."""
        deadline = getattr(self.config, "round_deadline", None)
        if not deadline or self._deadline_timer is not None \
                or not self._gradients_received:
            return
        t = threading.Timer(deadline, self._round_deadline_fired,
                            args=(self._round_serial,))
        t.daemon = True
        self._deadline_timer = t
        t.start()

    def _round_deadline_fired(self, serial: int) -> None:
        """Deadline expiry: complete the round with whatever arrived.
        Fenced by the round serial — if the round already completed (and
        reset the serial forward), this timer is stale and does nothing."""
        with self._sync_lock:
            if serial != self._round_serial:
                return
            self._deadline_timer = None
            finish = (self._complete_round_locked("deadline")
                      if self._gradients_received else None)
        if finish is not None:
            finish()

    def _cancel_deadline_locked(self) -> None:
        t, self._deadline_timer = self._deadline_timer, None
        if t is not None:
            t.cancel()

    def _maybe_complete_round_locked(self):
        """Complete the round if it reached its (quorum) target (caller
        holds ``_sync_lock``); see :meth:`_complete_round_locked` for the
        returned completion callable."""
        full = self._round_target()
        if self._gradients_received >= self._quorum_target(full):
            trigger = ("full" if self._gradients_received >= full
                       else "quorum")
            return self._complete_round_locked(trigger)
        return None

    def _complete_round_locked(self, trigger: str):
        """Aggregate + apply + reset (caller holds ``_sync_lock``).
        Returns a completion callable the CALLER must invoke AFTER
        releasing the sync lock — it waits for the device
        (``_after_apply``) and records the update time. Waiting under the
        lock convoyed every other worker's push behind the ~100 ms device
        round trip each round (round-2 VERDICT weak item 3); the update
        itself (dispatch + step bump) stays inside, so ordering and
        staleness accounting are unchanged."""
        t0 = time.time()
        try:
            # The apply span parents on the handler/worker span of the
            # push that COMPLETED the round — the causally responsible
            # step (trace context is thread-local; the last pusher's
            # thread runs the aggregation).
            with trace_span("store.apply", backend=self.store_backend,
                            mode="sync",
                            n_grads=self._gradients_received):
                self._round_update(list(self._pending.values()),
                                   self.config.learning_rate)
            self.stats.total_parameter_updates += 1
        finally:
            # The round MUST reset even if aggregation raises —
            # otherwise every later push re-triggers the failure and
            # the server is wedged permanently.
            self._pending.clear()
            self._gradients_received = 0
            self._round_serial += 1
            self._cancel_deadline_locked()
            self._last_round_trigger = trigger
        self._tm_rounds.inc()
        tm = self._tm_round_trigger.get(trigger)
        if tm is not None:
            tm.inc()
        self._tm_step.set(self.global_step)

        def finish() -> None:
            # _after_apply may decline to sync (sampled waits on the
            # device store) — only record a timing that measured real
            # completion, not async dispatch. The telemetry histogram
            # mirrors the same honesty rule.
            if self._after_apply() is not False:
                dt = time.time() - t0
                self.stats.update_times.append(dt)
                self._tm_apply_s.observe(dt)

        return finish

    # -- remediation hooks (telemetry/remediation.py) ------------------------

    def exclude_worker(self, worker_id: int) -> None:
        """Quorum-exclude a worker (straggler remediation): rounds stop
        waiting for it — it leaves the round target and the quorum
        denominator — while its own pushes still land (on-time ones count
        toward the round, late ones reconcile via staleness). Re-evaluates
        the pending round, since shrinking the target may complete it."""
        with self._registration_lock:
            self._excluded.add(int(worker_id))
            n = len(self._excluded)
        self._tm_excluded.set(n)
        with self._sync_lock:
            finish = (self._maybe_complete_round_locked()
                      if self._gradients_received else None)
        if finish is not None:
            finish()

    def include_worker(self, worker_id: int) -> None:
        """Lift a quorum exclusion (the straggler caught up / its alert
        resolved): the worker counts toward round targets again."""
        with self._registration_lock:
            self._excluded.discard(int(worker_id))
            n = len(self._excluded)
        self._tm_excluded.set(n)

    def excluded_workers(self) -> list[int]:
        with self._registration_lock:
            return sorted(self._excluded)

    def round_status(self) -> dict:
        """Live sync-round/quorum state for ``GET /cluster`` and
        ``cli status`` (docs/ROBUSTNESS.md): target vs received, who has
        pushed, who is excluded, and what closed the last round."""
        with self._sync_lock:
            received = self._gradients_received
            pending = sorted(self._pending)
            serial = self._round_serial
            armed = self._deadline_timer is not None
            trigger = self._last_round_trigger
        full = self._round_target()
        return {
            "mode": self.config.mode,
            "target": full,
            "quorum": self._quorum_target(full),
            "received": received,
            "pushed_workers": pending,
            "excluded": self.excluded_workers(),
            "round_serial": serial,
            "deadline_s": getattr(self.config, "round_deadline", None),
            "deadline_armed": armed,
            "last_trigger": trigger,
        }

    def _on_workers_expired(self, stale: list[int]) -> None:
        """Elastic: purge DEAD workers' pending gradients and complete the
        round if the survivors already cover the reduced target. An
        expired worker also leaves the exclusion set — if it returns
        (respawn reuses its slot), the replacement starts unexcluded."""
        # Emptiness pre-check dodging the lock in the common (no
        # exclusions) case; the mutation below re-checks nothing — it is
        # a blind difference_update, safe against any interleaving.
        if self._excluded:  # dpslint: ignore[lock-guard]
            with self._registration_lock:
                self._excluded.difference_update(stale)
                n = len(self._excluded)
            self._tm_excluded.set(n)
        if not getattr(self.config, "elastic", False):
            return
        with self._sync_lock:
            finish = None
            for w in stale:
                self._pending.pop(w, None)
            if self._pending or self._gradients_received:
                self._gradients_received = len(self._pending)
                finish = self._maybe_complete_round_locked()
        if finish is not None:
            finish()

    def _on_worker_departed(self, worker_id: int) -> None:
        """Elastic: a clean departure only shrinks the round target — its
        own final push (if any) stays in the round."""
        # Emptiness pre-check, same rationale as _on_workers_expired.
        if self._excluded:  # dpslint: ignore[lock-guard]
            self.include_worker(worker_id)
        if not getattr(self.config, "elastic", False):
            return
        with self._sync_lock:
            finish = (self._maybe_complete_round_locked()
                      if self._gradients_received else None)
        if finish is not None:
            finish()

    def _push_async(self, worker_id: int, grads: dict,
                    fetched_step: int) -> bool:
        """server.py:290-304 + 171-186: bounded staleness with down-weighted
        immediate apply.

        The staleness check and the apply run under ONE ``_param_lock``
        hold: with an unlocked pre-check, a concurrent apply could bump
        ``global_step`` between check and apply, admitting a push that
        was already past the bound — and weighting it as fresher than it
        is (tests/test_dpslint_fixes.py pins this down).
        """
        t0 = time.time()
        step = 0
        with self._param_lock:
            staleness = self.global_step - fetched_step
            accepted = staleness <= self.config.staleness_bound
            if accepted:
                weight = staleness_weight(staleness)
                with trace_span("store.apply", backend=self.store_backend,
                                mode="async", staleness=staleness,
                                weight=round(weight, 4)):
                    self._apply(grads, self.config.learning_rate, weight)
                    self.global_step += 1
                step = self.global_step
        self._tm_staleness.observe(staleness)
        if not accepted:
            self.stats.gradients_rejected += 1
            self._tm_push_rej.inc()
            return False
        self._tm_step.set(step)
        measured = self._after_apply() is not False
        self.stats.gradients_processed += 1
        self.stats.total_parameter_updates += 1
        self.stats.staleness_values.append(staleness)
        self._tm_push_ok.inc()
        if measured:
            dt = time.time() - t0
            self.stats.update_times.append(dt)
            self._tm_apply_s.observe(dt)
        return True

    # -- checkpoint surface --------------------------------------------------

    def snapshot(self) -> tuple[dict[str, np.ndarray], int]:
        """Consistent (host-numpy params copy, global_step) pair for
        checkpointing — the capability the reference listed as future work
        (DEPLOYMENT.md:309). Device-array stores convert to host OUTSIDE the
        lock (jax arrays are immutable, so the references stay consistent
        while the transfer runs)."""
        device_arrays = getattr(self, "keeps_device_arrays", False)
        with self._param_lock:
            params = {k: (v if device_arrays else v.copy())
                      for k, v in self.parameters.items()}
            step = self.global_step
        if device_arrays:
            params = {k: np.asarray(v) for k, v in params.items()}
        return params, step

    def load_snapshot(self, params: Mapping[str, np.ndarray],
                      step: int) -> None:
        """Restore a (params, step) snapshot; conversion happens outside the
        lock, the swap inside it."""
        if getattr(self, "keeps_device_arrays", False):
            import jax.numpy as jnp
            new = {k: jnp.asarray(v, jnp.float32) for k, v in params.items()}
        else:
            new = {k: np.array(v, np.float32) for k, v in params.items()}
        with self._param_lock:
            self.parameters = new
            self.global_step = int(step)

    # -- live-migration surface (docs/SHARDING.md "Migration protocol") ------

    def param_names(self) -> list[str]:
        """Current parameter names (a migration derives the slot-range
        subset from these; cheap — no tensor copies)."""
        with self._param_lock:
            return list(self.parameters.keys())

    def export_params(self, names) -> tuple[dict[str, np.ndarray], int]:
        """Consistent (subset copy, global_step) for a slot-range handoff
        — the donor half of a live reshard. Unknown names are skipped
        (the admin derives the subset from slots, not from this store's
        key list). Same host-conversion discipline as :meth:`snapshot`.
        """
        wanted = set(names)
        device_arrays = getattr(self, "keeps_device_arrays", False)
        with self._param_lock:
            params = {k: (v if device_arrays else v.copy())
                      for k, v in self.parameters.items() if k in wanted}
            step = self.global_step
        if device_arrays:
            params = {k: np.asarray(v) for k, v in params.items()}
        return params, step

    def adopt_params(self, params: Mapping[str, np.ndarray]) -> int:
        """Graft migrated tensors into this store (the recipient half of
        a handoff). Existing names are overwritten — the donor's copy is
        newer by protocol (it stopped applying to the range at export).
        Returns how many tensors were adopted."""
        if getattr(self, "keeps_device_arrays", False):
            import jax.numpy as jnp
            new = {k: jnp.asarray(v, jnp.float32)
                   for k, v in params.items()}
        else:
            new = {k: np.array(v, np.float32) for k, v in params.items()}
        with self._param_lock:
            self.parameters.update(new)
        return len(new)

    def drop_params(self, names) -> int:
        """Release tensors this shard no longer owns (the donor's commit
        step, after the recipient confirmed adoption). Returns how many
        were dropped."""
        wanted = set(names)
        with self._param_lock:
            mine = [k for k in self.parameters if k in wanted]
            for k in mine:
                del self.parameters[k]
        return len(mine)

    # -- observability -------------------------------------------------------

    def metrics(self) -> dict:
        """Final-statistics fields, matching the server's METRICS_JSON
        (server.py:349-366; SURVEY.md §5.5)."""
        elapsed = time.time() - self.stats.start_time
        out = {
            "mode": self.config.mode,
            "total_workers": self.config.total_workers,
            "total_training_time_seconds": round(elapsed, 2),
            # Unlocked read: a final-stats row tolerates being one
            # concurrent apply behind.
            "global_steps_completed": self.global_step,  # dpslint: ignore[lock-guard]
            "total_parameter_updates": self.stats.total_parameter_updates,
            "gradients_processed": self.stats.gradients_processed,
            "average_update_time_seconds": (
                round(float(np.mean(self.stats.update_times)), 6)
                if self.stats.update_times else 0.0),
            "updates_per_second": (
                round(self.stats.total_parameter_updates / elapsed, 3)
                if elapsed > 0 else 0.0),
            "learning_rate": self.config.learning_rate,
            "store_backend": self.store_backend,
        }
        # Sampled device syncs (ps/device_store.py wait_every): each
        # recorded update_time measured completion of up to wait_every
        # queued rounds, so it is NOT comparable 1:1 with the per-update
        # host-backend timings — emit the sampling interval so readers
        # (and PERF.md tables) can normalize (ADVICE r3).
        we = getattr(self, "wait_every", 1)
        if we and we > 1:
            out["update_time_wait_every"] = int(we)
        if self.config.mode == "async":
            sv = self.stats.staleness_values
            out.update({
                "staleness_bound": self.config.staleness_bound,
                "gradients_rejected": self.stats.gradients_rejected,
                "average_staleness": (round(float(np.mean(sv)), 3)
                                      if sv else 0.0),
                "max_staleness": int(max(sv)) if sv else 0,
            })
        return out


class ParameterStore(AggregationBase):
    """Thread-safe canonical parameter holder + sync/async aggregator."""

    def __init__(self, initial_params: Mapping[str, np.ndarray],
                 config: StoreConfig | None = None):
        self.config = config or StoreConfig()
        # Resolve the backend-default sentinel LOCALLY — a StoreConfig may
        # be shared across stores, so the resolution must not leak into it.
        self._push_codec = (self.config.push_codec
                            if self.config.push_codec is not None
                            else "fp16")  # reference default
        if self._push_codec not in PUSH_CODECS:
            raise ValueError(
                f"push_codec must be one of {'|'.join(PUSH_CODECS)}, "
                f"got {self._push_codec!r}")
        self.parameters: dict[str, np.ndarray] = {
            k: np.array(v, np.float32) for k, v in initial_params.items()
        }
        self.global_step = 0
        # Per-layer gradient ABSMAX estimates — the shared quantization
        # basis workers fetch (negotiated at registration, refreshed via
        # the fetch path) so a round's int8/int4 pushes land in ONE
        # accumulator group. _qscale_step bumps on every refresh so
        # clients can cheap-check for changes.
        self._qscales: dict[str, float] = {}  # guarded by: self._param_lock
        self._qscale_step = 0  # guarded by: self._param_lock

        self._param_lock = threading.Lock()
        self._sync_lock = threading.Lock()
        self._registration_lock = threading.Lock()

        self._next_worker_id = 0
        self.active_workers: set[int] = set()
        self.last_seen: dict[int, float] = {}

        self._pending: dict[int, dict[str, np.ndarray]] = {}
        self._gradients_received = 0

        self.stats = _Stats()
        self._finished_event = threading.Event()
        self._init_telemetry()
        self._init_round_state()

    @property
    def push_codec(self) -> str:
        """Codec workers must apply before pushing (worker.py:264-268 did the
        fp16 cast on the worker side)."""
        return self._push_codec

    @property
    def fetch_codec(self) -> str:
        """Codec applied to fetched payloads; workers must decompress
        (non-default — the reference always fetched fp32, server.py:222)."""
        return self.config.fetch_codec

    # -- lifecycle (register/finish/expire inherited) ----------------- ps.proto:8

    supports_delta_fetch = True

    #: This store can aggregate quantized pushes without decoding them
    #: (docs/WIRE_PROTOCOL.md) and publishes per-layer gradient scales.
    #: The gRPC service advertises it at registration, same gating
    #: discipline as delta-fetch; the native/device backends leave it off.
    supports_compressed_domain = True

    def gradient_scales(self) -> tuple[dict[str, float], int]:
        """The server's per-layer gradient ABSMAX table + its version.
        Workers quantize against these (int8 scale = absmax/127, int4 =
        absmax/7) so a sync round's pushes share one scale group. Empty
        until the first round refreshes it — workers fall back to
        per-push scales, which the aggregation handles as extra groups."""
        with self._param_lock:
            return dict(self._qscales), self._qscale_step

    def _refresh_qscales_locked(self, grads: Mapping[str, np.ndarray]
                                ) -> None:
        """Update the shared-scale table from an applied aggregate
        (caller holds ``_param_lock``). EMA toward 2x the aggregate's
        absmax — individual workers' gradients run hotter than the round
        mean, and error feedback absorbs what still clips."""
        if self._push_codec not in QUANTIZED_PUSH_CODECS:
            return
        changed = False
        for name, g in grads.items():
            g = np.asarray(g)
            m = float(np.max(np.abs(g))) if g.size else 0.0
            if not np.isfinite(m) or m <= 0.0:
                continue
            target = 2.0 * m
            old = self._qscales.get(name)
            new = target if old is None else 0.5 * old + 0.5 * target
            if old is None or abs(new - old) > 1e-12:
                self._qscales[name] = new
                changed = True
        if changed:
            self._qscale_step += 1

    # dpslint: hot-path — every worker, every step; ONE sanctioned copy
    def fetch(self, worker_id: int | None = None,
              have_step: int | None = None
              ) -> tuple[dict[str, np.ndarray], int]:
        """Copy of the canonical params + current global step
        (server.py:213-237). Codec per config (reference: fp32, uncompressed).

        ``have_step`` opts into the version-gated delta protocol: when it
        equals the canonical step, the reply is NOT_MODIFIED — ``({}, step)``
        with ``step == have_step`` — and the caller keeps the params it
        already holds. The comparison happens under the param lock, so a
        concurrent apply can never slip between the check and the reply:
        either the reply step equals ``have_step`` (and the params are
        byte-identical to what the caller fetched at that step) or the full
        fresh payload is returned. Steps only ever advance, so equality is
        exactly "nothing changed".
        """
        t0 = _tnow()
        with trace_span("store.fetch", backend=self.store_backend) as sp:
            with self._param_lock:
                if have_step is not None and have_step == self.global_step:
                    payload, step, modified = {}, self.global_step, False
                else:
                    payload = {k: v.copy()
                               for k, v in self.parameters.items()}
                    step = self.global_step
                    modified = True
            if worker_id is not None:
                # Under the registration lock: a bare dict store raced
                # the reaper's iteration in expire_stale_workers.
                with self._registration_lock:
                    self.last_seen[worker_id] = time.time()
            if not modified:
                sp.attrs["not_modified"] = True
                self._tm_fetch_nm.inc()
                self._tm_fetch_s.observe(_tnow() - t0)
                self._tm_fetches.inc()
                return payload, step
            if self.config.fetch_codec == "fp16":
                payload = fp16_compress(payload)
            elif self.config.fetch_codec == "bf16":
                payload = bf16_compress(payload)
            self._tm_fetch_s.observe(_tnow() - t0)
            self._tm_fetches.inc()
            return payload, step

    def push(self, worker_id: int, gradients: Mapping[str, np.ndarray],
             fetched_step: int) -> bool:
        """Push gradients (PushGradrients, ps.proto:12 — typo preserved in
        the reference wire protocol; here the API is just named push).

        ``fetched_step`` is the global step the worker last fetched — the
        reference's ``local_step`` field actually carries this
        (worker.py:299), making staleness = versions-behind.
        Returns True iff the gradients were accepted (sync mode always
        accepts, matching PushReply(received=True), server.py:286-288).
        """
        t0 = _tnow()
        with trace_span("store.push", backend=self.store_backend) as sp:
            try:
                accepted = self._push_timed(worker_id, gradients,
                                            fetched_step)
                sp.attrs["accepted"] = accepted
                return accepted
            finally:
                self._tm_push_s.observe(_tnow() - t0)

    # dpslint: hot-path — per-push; quantized payloads stay encoded
    def _push_timed(self, worker_id: int,
                    gradients: Mapping[str, np.ndarray],
                    fetched_step: int) -> bool:
        gradients = dict(gradients)
        quantized = is_quantized_payload(gradients)
        # Compressed-domain fast path (sync only): hold the quantized
        # payload AS-IS — no per-push fp32 decode; the round completion
        # sums int8/int4 entries in int32 accumulators and dequantizes
        # once (homomorphic_mean). Async, legacy codecs, and
        # compressed_domain=False decode here as before; async applies
        # dequantize the single incoming payload with its carried scale.
        keep_quantized = (quantized and self.config.mode == "sync"
                          and self.config.compressed_domain)
        with self._registration_lock:
            self.last_seen[worker_id] = time.time()

        # Reject malformed/mismatched pushes up front (e.g. a worker
        # built with a different head size than the server, a missing
        # scale companion, an out-of-range sparse index): the reference
        # would crash mid-apply on the broadcast; here the bad push is
        # refused and the round state stays clean. Quantized payloads are
        # checked on their LOGICAL shapes — carried in the wire headers,
        # no decode needed — and the sparse/scale validation runs at THIS
        # push, never deferred into the round completion where it would
        # fail a different worker's RPC.
        try:
            if keep_quantized:
                shapes = payload_logical_shapes(gradients)
            else:
                if quantized:
                    gradients = wire_decompress(gradients)
                elif self._push_codec == "fp16":
                    gradients = fp16_decompress(gradients)
                else:
                    gradients = {k: np.asarray(v, np.float32)
                                 for k, v in gradients.items()}
                shapes = {k: g.shape for k, g in gradients.items()}
        except ValueError as e:
            self.stats.gradients_rejected += 1
            self._tm_push_rej.inc()
            print(f"rejecting push from worker {worker_id}: {e}")
            return False
        # Snapshot the expected shapes under the lock (shapes never
        # change after __init__, but the dict itself may be swapped by a
        # concurrent load_snapshot restore).
        with self._param_lock:
            param_shapes = {k: v.shape for k, v in self.parameters.items()}
        for name, shape in shapes.items():
            p_shape = param_shapes.get(name)
            if p_shape is not None and p_shape != tuple(shape):
                self.stats.gradients_rejected += 1
                self._tm_push_rej.inc()
                print(f"rejecting push from worker {worker_id}: {name} "
                      f"shape {tuple(shape)} != server {p_shape} "
                      f"(model/dataset mismatch?)")
                return False
        if keep_quantized:
            # Counted only once the push is actually ACCEPTED into the
            # quantized-domain round (the metric claims int32-accumulated
            # pushes; a rejected payload never was).
            self._tm_compressed.inc()

        if self.config.mode == "sync":
            return self._push_sync(worker_id, gradients, fetched_step)
        return self._push_async(worker_id, gradients, fetched_step)

    # -- aggregation kernels (orchestration in AggregationBase) --------------

    def _mean(self, grad_dicts: list) -> dict:
        return mean_gradients(grad_dicts)

    def _round_update(self, grad_dicts: list, lr: float) -> None:
        """Sync-round update, compressed-domain aware: quantized payloads
        aggregate via :func:`homomorphic_mean` (int32 accumulate, one
        dequantize per layer per round); all-dense rounds keep the
        reference's :func:`mean_gradients` path. Either way the applied
        aggregate refreshes the shared scale table under the param lock,
        so the next fetches publish fresh scales."""
        if any(is_quantized_payload(g) for g in grad_dicts):
            mean = homomorphic_mean(grad_dicts)
        else:
            mean = self._mean(grad_dicts)
        with self._param_lock:
            self._apply(mean, lr)
            self.global_step += 1
            self._refresh_qscales_locked(mean)

    def _apply(self, grads: dict, lr: float, weight: float = 1.0) -> None:
        # Kernel contract (AggregationBase): callers hold _param_lock.
        sgd_apply(self.parameters, grads, lr, weight=weight)  # dpslint: ignore[lock-guard]
