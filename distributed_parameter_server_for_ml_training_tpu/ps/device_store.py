"""Device-resident parameter store: the async/sync PS with params in HBM.

The reference keeps canonical params in server RAM as numpy and moves the
full ~45 MB parameter/gradient payload across the network on every fetch and
push (server.py:96, 222, 245). :class:`~.store.ParameterStore` re-hosts that
faithfully on the host CPU — which is the right shape for a *multi-host*
deployment, but on a TPU host it forces two full host<->device transfers per
worker step. This store is the TPU-native alternative for workers that share
the accelerator:

- canonical parameters live ON DEVICE as a flat ``{name: jax.Array}`` dict
  (fp32, like server.py:96's state_dict copy),
- ``fetch`` returns *references* to the current device arrays (jax arrays
  are immutable, so a fetched snapshot stays consistent while later pushes
  rebind the store to new arrays) — zero bytes moved,
- ``push`` takes device gradient arrays straight from ``jax.grad`` and
  applies the update with a jitted on-device SGD kernel — zero bytes moved.

Aggregation/membership orchestration (sync rounds, bounded staleness,
elastic expiry, metrics) is shared with the host store via
:class:`~.store.AggregationBase` — only the three kernels differ (jitted
device mean/apply + a block_until_ready so update timings measure compute,
not dispatch). Staleness math is therefore identical to the reference
(server.py:145-169, 126-143, 171-186).

No wire codec applies (``push_codec='none'``): nothing crosses a wire. The
fp16-compression analogue for this path is the bf16/int8 *collective*
compression in parallel/sync_dp.py.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .store import AggregationBase, StoreConfig, _Stats
from ..telemetry import now as _tnow, trace_span


@jax.jit
def _sgd_apply_device(params: dict, grads: dict, scale):
    """p <- p - scale * g for the params present in ``grads``
    (server.py:126-143 apply_gradients; scale = lr * staleness_weight)."""
    return {
        k: (params[k] - scale * grads[k] if k in grads else params[k])
        for k in params
    }


@jax.jit
def _mean_grads_device(stacked: dict):
    """Per-parameter mean over the leading (worker) axis
    (server.py:145-169 aggregate_gradients_sync)."""
    return {k: jnp.mean(v, axis=0) for k, v in stacked.items()}


@jax.jit
def _mean_apply_device(params: dict, stacked: dict, scale):
    """Fused sync-round update: worker-mean + SGD apply in ONE compiled
    program — one dispatch per round instead of two (the remote-attached
    chip pays ~100 ms per dispatch, and the round completes while other
    workers wait on the sync lock)."""
    return {
        k: (params[k] - scale * jnp.mean(stacked[k], axis=0)
            if k in stacked else params[k])
        for k in params
    }


class DeviceParameterStore(AggregationBase):
    """Thread-safe parameter store whose tensors never leave the device.

    API-compatible with :class:`~.store.ParameterStore` for in-process
    workers (register/fetch/push/job_finished/metrics), with
    ``keeps_device_arrays = True`` advertising that fetch returns jax arrays
    and push expects them (PSWorker skips its host round-trip accordingly).
    """

    keeps_device_arrays = True
    store_backend = "device"
    push_codec = "none"
    fetch_codec = "none"

    # AggregationBase's contracts re-declared (tools/dpslint checks are
    # module-local), plus this backend's own sampling counter.
    parameters: dict  # guarded by: self._param_lock
    global_step: int  # guarded by: self._param_lock
    last_seen: dict  # guarded by: self._registration_lock
    _updates_since_wait: int  # guarded by: self._wait_lock

    def __init__(self, initial_params: Mapping[str, np.ndarray],
                 config: StoreConfig | None = None):
        self.config = config or StoreConfig()
        if self.config.push_codec not in (None, "none"):
            # An EXPLICITLY requested codec cannot apply: nothing crosses a
            # wire here, so the reference's fp16 gradient quantization
            # (worker.py:264-268) is skipped — gradient numerics differ
            # from the python/native backends. Make that explicit instead of
            # silently ignoring the config.
            import warnings
            warnings.warn(
                f"DeviceParameterStore ignores push_codec="
                f"{self.config.push_codec!r}: device-resident pushes are "
                f"uncompressed fp32 (no wire); gradients skip the fp16 "
                f"quantization the python/native backends apply",
                stacklevel=2)
        if self.config.fetch_codec != "none":
            import warnings
            warnings.warn(
                f"DeviceParameterStore ignores fetch_codec="
                f"{self.config.fetch_codec!r}: fetches hand back device "
                f"arrays directly (no wire to compress)", stacklevel=2)
        self.parameters: dict[str, jax.Array] = {
            k: jnp.asarray(v, jnp.float32) for k, v in initial_params.items()
        }
        self.global_step = 0

        self._param_lock = threading.Lock()
        self._sync_lock = threading.Lock()
        self._registration_lock = threading.Lock()
        self._wait_lock = threading.Lock()
        self._updates_since_wait = 0

        self._next_worker_id = 0
        self.active_workers: set[int] = set()
        self.last_seen: dict[int, float] = {}

        self._pending: dict[int, dict[str, jax.Array]] = {}
        self._gradients_received = 0

        self.stats = _Stats()
        self._finished_event = threading.Event()
        self._init_telemetry()
        self._init_round_state()

    # -- hot path ------------------------------------------------------------

    # dpslint: hot-path — zero-byte fetch: references, never copies
    def fetch(self, worker_id: int | None = None
              ) -> tuple[dict[str, jax.Array], int]:
        """Consistent (params, step) snapshot — references, not copies
        (immutability makes the reference's copy-under-lock, server.py:222,
        free here)."""
        t0 = _tnow()
        with trace_span("store.fetch", backend=self.store_backend):
            with self._param_lock:
                payload = dict(self.parameters)
                step = self.global_step
        if worker_id is not None:
            # Registration lock: the bare dict store raced the reaper's
            # iteration in expire_stale_workers.
            with self._registration_lock:
                self.last_seen[worker_id] = time.time()
        # NOTE: the span measures the dict-copy handoff (~us) — fetch here
        # moves zero bytes by design, so this histogram is the proof, not
        # the cost (compare against the python/native backends' ms-scale
        # fetch distributions in the same snapshot stream).
        self._tm_fetch_s.observe(_tnow() - t0)
        self._tm_fetches.inc()
        return payload, step

    # dpslint: hot-path — device arrays in, device arrays applied
    def push(self, worker_id: int, gradients: Mapping[str, jax.Array],
             fetched_step: int) -> bool:
        """Accept device-array gradients; apply per the configured mode.

        Same accept/reject contract as ParameterStore.push (PushGradrients,
        ps.proto:12): sync always accepts, async rejects past the staleness
        bound.
        """
        t0 = _tnow()
        with self._registration_lock:
            self.last_seen[worker_id] = time.time()
        with self._param_lock:
            param_shapes = {k: v.shape for k, v in self.parameters.items()}
        for name, g in gradients.items():
            p_shape = param_shapes.get(name)
            if p_shape is not None and p_shape != g.shape:
                self.stats.gradients_rejected += 1
                self._tm_push_rej.inc()
                print(f"rejecting push from worker {worker_id}: {name} "
                      f"shape {g.shape} != server {p_shape}")
                return False
        try:
            with trace_span("store.push",
                            backend=self.store_backend) as sp:
                if self.config.mode == "sync":
                    accepted = self._push_sync(worker_id, dict(gradients),
                                               fetched_step)
                    sp.attrs["accepted"] = accepted
                    return accepted
                accepted = self._push_async(worker_id, dict(gradients),
                                            fetched_step)
                sp.attrs["accepted"] = accepted
                return accepted
        finally:
            self._tm_push_s.observe(_tnow() - t0)

    # -- aggregation kernels (orchestration in AggregationBase) --------------

    def _mean(self, grad_dicts: list) -> dict:
        """Mean each parameter over the workers that supplied it
        (server.py:145-169 iterates params independently, so partial pushes
        average over their own supplier count)."""
        names = {n for g in grad_dicts for n in g}
        full = [n for n in names if all(n in g for g in grad_dicts)]
        # Common case — every worker supplied every param — is one jitted
        # stacked mean; stragglers (ragged pushes) are averaged per name.
        mean = _mean_grads_device(
            {n: jnp.stack([g[n] for g in grad_dicts]) for n in full})
        for n in names:
            if n not in mean:
                have = [g[n] for g in grad_dicts if n in g]
                mean[n] = jnp.mean(jnp.stack(have), axis=0)
        return mean

    def _apply(self, grads: dict, lr: float, weight: float = 1.0) -> None:
        # Kernel contract (AggregationBase): callers hold _param_lock.
        self.parameters = _sgd_apply_device(  # dpslint: ignore[lock-guard]
            self.parameters, grads,  # dpslint: ignore[lock-guard]
            jnp.float32(lr * weight))

    def _round_update(self, grad_dicts: list, lr: float) -> None:
        """Fused path for the common full round (every worker supplied
        every param): ONE dispatch for mean + apply. Ragged rounds
        (stragglers / partial pushes) fall back to the two-kernel base."""
        names = {n for g in grad_dicts for n in g}
        if any(n not in g for n in names for g in grad_dicts):
            return super()._round_update(grad_dicts, lr)
        stacked = {n: jnp.stack([g[n] for g in grad_dicts]) for n in names}
        with self._param_lock:
            self.parameters = _mean_apply_device(
                self.parameters, stacked, jnp.float32(lr))
            self.global_step += 1

    #: Sync with the device every Nth update. Waiting on EVERY update cost
    #: one ~100 ms tunnel round trip per round while pushes queued behind
    #: it (round-2 VERDICT weak item 3); correctness never needed the wait
    #: (jax dataflow orders the param chain), only update-time METRICS did.
    #: Sampling keeps update_times honest — entries measure real completion
    #: of everything queued since the last sync — while letting the update
    #: stream run at device speed between samples.
    wait_every = 8

    def _after_apply(self):
        # Counter guarded by its own lock: finish() callables (and async
        # pushes) run concurrently outside the sync lock, and a lost
        # increment would stretch the sampling interval — the only
        # backpressure on dispatched device work.
        with self._wait_lock:
            self._updates_since_wait += 1
            if self._updates_since_wait < self.wait_every:
                return False  # declined: caller must not record a timing
            self._updates_since_wait = 0
        # Deliberately outside _param_lock: one consistent reference is
        # enough (jax arrays are immutable), and blocking the device under
        # the lock would convoy every concurrent push behind the wait.
        jax.block_until_ready(self.parameters)  # dpslint: ignore[lock-guard]
        return True
