"""Consistent-hash parameter sharding + the shard map (docs/SHARDING.md).

The single parameter server holds every canonical tensor — the hard
ceiling on both training fan-in and any serve-path read workload. This
module is the partitioning layer under the sharded topology (ACE-Sync's
two-tier shape, PAPERS.md): parameter NAMES are consistent-hashed into a
fixed slot space, slot ranges are owned by N primary shards, and each
shard may publish read-only replicas that subscribe to it over the
delta-fetch protocol.

Everything that routes — the worker's push/fetch fan-out
(``comms/sharded.py``), each shard's key-subset filter (``cli serve
--shard-index``), the replica announce path, the checkpoint identity
check — derives from the same two pure functions here
(:func:`shard_for_key` / :func:`partition_keys`), so no two layers can
ever disagree about who owns a tensor.

The **shard map** is the wire artifact (schema pinned both directions by
``tests/test_docs_drift.py``): published in the registration reply when a
server runs sharded, refreshed via fetch-reply meta exactly like the
qscale table (the client sends ``have_shard_map``, the server attaches
the map only when its version is newer), and capability-gated with the
same legacy-degradation discipline as ``delta_fetch`` /
``compressed_domain`` / ``directives`` — an unsharded server never
advertises it, an old client never asks, and either pairing degrades to
the single-server wire.
"""

from __future__ import annotations

import threading
import time
import zlib

__all__ = [
    "SHARD_MAP_FIELDS",
    "SHARD_SLOTS",
    "ShardInfo",
    "key_slot",
    "partition_keys",
    "shard_for_key",
    "shard_for_slot",
    "slot_range",
    "validate_ranges",
    "validate_shard_map",
]

#: Fixed consistent-hash slot space. Key -> slot assignment NEVER moves
#: when the shard count changes; only the slot-range -> shard ownership
#: does — so a rebalance remaps whole contiguous ranges instead of
#: rehashing every tensor (docs/SHARDING.md "Rebalance semantics").
SHARD_SLOTS = 64

#: The shard-map wire schema: field name -> one-line meaning. This table
#: IS the doc contract — ``tests/test_docs_drift.py`` pins it to
#: docs/SHARDING.md's field table in both directions, the same discipline
#: as metric/span/rule/codec/directive names.
SHARD_MAP_FIELDS = {
    "version": "monotonic map revision; refresh is delta-gated on it "
               "(have_shard_map handshake)",
    "slots": "size of the consistent-hash slot space (SHARD_SLOTS)",
    "shard_count": "number of primary shards owning slot ranges",
    "shards": "one entry per shard: shard_id, slot_range, primary, "
              "replicas",
    "shard_id": "this entry's shard index in [0, shard_count)",
    "slot_range": "[lo, hi) slot interval this shard owns",
    "primary": "the shard primary's host:port (push + authoritative "
               "fetch)",
    "replicas": "host:port list of live delta-fed read replicas behind "
                "this shard",
}


def key_slot(name: str, slots: int = SHARD_SLOTS) -> int:
    """The consistent-hash slot a parameter name lives in — forever.
    Every routing decision (canonical or live-resharded) starts here."""
    return zlib.crc32(str(name).encode("utf-8")) % slots


def shard_for_key(name: str, shard_count: int,
                  slots: int = SHARD_SLOTS) -> int:
    """Owning shard index for a parameter name under the CANONICAL
    launch-time partition (equal contiguous ranges).

    crc32 over the name, folded into the fixed slot space, then mapped to
    the shard owning that slot's range. Pure and stable: every layer
    (worker fan-out, shard key filter, checkpoint identity) computes the
    same answer forever, and adding shards moves only whole slot ranges.
    After a live reshard the authoritative answer is the published map's
    ranges (:func:`shard_for_slot`); this stays the boot-time seed.
    """
    if shard_count < 1:
        raise ValueError(f"shard_count must be >= 1, got {shard_count}")
    slot = key_slot(name, slots)
    # Contiguous ranges: shard i owns [i*slots//N, (i+1)*slots//N).
    return min(shard_count - 1, slot * shard_count // slots)


def shard_for_slot(slot: int, ranges) -> int:
    """Owning shard index for a slot under LIVE (possibly resharded)
    ranges — one ``[lo, hi)`` pair per shard, contiguous and ordered
    (what :func:`validate_ranges` guarantees). Raises ``ValueError`` if
    no range covers the slot (a malformed map that validation rejects
    anyway)."""
    for i, (lo, hi) in enumerate(ranges):
        if lo <= slot < hi:
            return i
    raise ValueError(f"slot {slot} not covered by ranges {list(ranges)}")


def validate_ranges(ranges, shard_count: int,
                    slots: int = SHARD_SLOTS) -> list[tuple[int, int]]:
    """Validate a live slot-range partition: one ``[lo, hi)`` per shard,
    ordered, contiguous (entry i starts where i-1 ended), first at 0,
    last at ``slots`` — together: disjoint and covering. Empty ranges
    (``lo == hi``) are legal: a merge can leave a shard owning nothing.
    Returns normalized tuples; raises ``ValueError`` on anything else."""
    if len(ranges) != shard_count:
        raise ValueError(f"need one slot range per shard: got "
                         f"{len(ranges)} for shard_count={shard_count}")
    norm: list[tuple[int, int]] = []
    prev_hi = 0
    for i, pair in enumerate(ranges):
        try:
            lo, hi = (int(x) for x in pair)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad slot range {i}: {pair!r}") from e
        if lo != prev_hi or hi < lo:
            raise ValueError(f"slot ranges must be an ordered contiguous "
                             f"partition: entry {i} is [{lo}, {hi}) after "
                             f"[.., {prev_hi})")
        norm.append((lo, hi))
        prev_hi = hi
    if prev_hi != slots:
        raise ValueError(f"slot ranges cover [0, {prev_hi}), "
                         f"want [0, {slots})")
    return norm


def slot_range(shard_id: int, shard_count: int,
               slots: int = SHARD_SLOTS) -> tuple[int, int]:
    """The [lo, hi) slot interval shard ``shard_id`` owns."""
    if not 0 <= shard_id < shard_count:
        raise ValueError(f"shard_id {shard_id} outside "
                         f"[0, {shard_count})")
    return (shard_id * slots // shard_count,
            (shard_id + 1) * slots // shard_count)


def partition_keys(keys, shard_count: int) -> list[list[str]]:
    """Split parameter names into per-shard key lists (deterministic:
    input order preserved within each shard). Every shard's serve process
    and every worker derive the same partition from the same two
    arguments — there is no partition state to distribute."""
    out: list[list[str]] = [[] for _ in range(shard_count)]
    for k in keys:
        out[shard_for_key(k, shard_count)].append(k)
    return out


def validate_shard_map(m) -> dict:
    """Validate a wire shard map; returns it normalized. Raises
    ``ValueError`` on anything malformed — the CLIENT calls this before
    adopting a refresh, so a garbled map degrades to the cached one
    (the caller swallows the error), never to misrouted pushes."""
    if not isinstance(m, dict):
        raise ValueError("shard map must be an object")
    try:
        version = int(m["version"])
        slots = int(m.get("slots", SHARD_SLOTS))
        shard_count = int(m["shard_count"])
        shards = m["shards"]
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad shard map: {e}") from e
    if shard_count < 1 or slots < shard_count:
        raise ValueError(f"bad shard map: shard_count={shard_count} "
                         f"slots={slots}")
    if not isinstance(shards, list) or len(shards) != shard_count:
        raise ValueError("bad shard map: shards list does not match "
                         "shard_count")
    norm = []
    for i, s in enumerate(shards):
        if not isinstance(s, dict):
            raise ValueError(f"bad shard entry {i}")
        try:
            sid = int(s["shard_id"])
            primary = str(s["primary"])
            lo, hi = (int(x) for x in s["slot_range"])
            replicas = [str(r) for r in s.get("replicas", [])]
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad shard entry {i}: {e}") from e
        if sid != i:
            raise ValueError(f"bad shard entry {i}: id mismatch")
        norm.append({"shard_id": sid, "slot_range": [lo, hi],
                     "primary": primary, "replicas": replicas})
    # Ranges need not be the canonical equal split — live resharding
    # moves boundaries — but they MUST still tile the slot space: any
    # gap/overlap would orphan or double-own keys.
    validate_ranges([s["slot_range"] for s in norm], shard_count, slots)
    return {"version": version, "slots": slots,
            "shard_count": shard_count, "shards": norm}


class ShardInfo:
    """One shard primary's live sharding state (held by the
    ``ParameterService`` when ``cli serve`` runs sharded).

    Owns the authoritative copy of this server's shard map — the static
    topology (``--shard-peers``) plus the LIVE replica membership learned
    from replica announces riding fetch meta — and the replica lag
    bookkeeping behind the ``dps_replica_lag_*`` gauges and the
    ``GET /cluster`` / ``cli status`` shard rows.

    Thread-safety: announces arrive on gRPC handler threads; the map and
    the lag table are read by every registration/fetch reply and by the
    monitor's view. One small lock covers both.
    """

    #: A replica silent for this long drops out of the published map (and
    #: its lag gauges stop updating) — liveness is announce-driven, there
    #: is no replica heartbeat channel.
    REPLICA_EXPIRE_S = 30.0

    def __init__(self, shard_id: int, shard_count: int,
                 primaries: list[str], clock=time.time):
        if len(primaries) != shard_count:
            raise ValueError(
                f"need one primary address per shard: got "
                f"{len(primaries)} for shard_count={shard_count}")
        if not 0 <= shard_id < shard_count:
            raise ValueError(f"shard_id {shard_id} outside "
                             f"[0, {shard_count})")
        self.shard_id = int(shard_id)
        self.shard_count = int(shard_count)
        self.primaries = [str(p) for p in primaries]
        self.clock = clock
        self._lock = threading.Lock()
        self._version = 1
        # Live slot ownership, seeded canonical; a reshard moves these
        # boundaries (adopt_ranges) and bumps the version so every
        # cached client map refreshes. guarded by: self._lock
        self._ranges: list[tuple[int, int]] = [
            slot_range(i, self.shard_count) for i in range(self.shard_count)]
        #: replica address -> {"step": int, "ts": float, "lag_steps": int}
        self._replicas: dict[str, dict] = {}
        from ..telemetry import get_registry
        reg = get_registry()
        self._tm_id = reg.gauge("dps_shard_id")
        self._tm_count = reg.gauge("dps_shard_count")
        self._tm_map_version = reg.gauge("dps_shard_map_version")
        self._tm_replicas = reg.gauge("dps_shard_replicas")
        self._tm_id.set(self.shard_id)
        self._tm_count.set(self.shard_count)
        self._tm_map_version.set(self._version)
        self._reg = reg
        self._tm_lag: dict[str, tuple] = {}
        #: parent address -> child-count gauge (guarded by: self._lock;
        #: removed via registry.remove when a node loses its last child).
        self._tm_children: dict[str, object] = {}
        #: Optional zero-arg callable returning the in-flight migration
        #: block for ``view()`` (or None when idle). The owning service
        #: installs its ``migration_view`` here so ``GET /cluster``
        #: surfaces live reshard state without sharding importing comms.
        self.migration_provider = None

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def my_range(self) -> tuple[int, int]:
        """The ``[lo, hi)`` slot interval THIS shard currently owns."""
        with self._lock:
            return self._ranges[self.shard_id]

    def owns_slot(self, slot: int) -> bool:
        with self._lock:
            lo, hi = self._ranges[self.shard_id]
        return lo <= slot < hi

    def ranges(self) -> list[tuple[int, int]]:
        with self._lock:
            return list(self._ranges)

    def adopt_ranges(self, ranges, version: int | None = None) -> int:
        """Install a new live slot partition (a reshard commit or the
        admin's post-migration broadcast). ``version``, when given, is
        the coordinator-chosen map revision — floored at one past the
        current version so the map NEVER goes backwards and every
        ``have_shard_map`` client refreshes. Returns the new version.
        Raises ``ValueError`` on a malformed partition (nothing adopted).
        """
        norm = validate_ranges(ranges, self.shard_count)
        with self._lock:
            self._ranges = norm
            bump = self._version + 1
            self._version = max(bump, int(version or 0))
            self._tm_map_version.set(self._version)
            return self._version

    def note_replica(self, address: str, step, global_step: int,
                     metrics: str | None = None,
                     parent: str | None = None,
                     tier=None, fetches=None) -> None:
        """Ingest one replica announce (rides the replica's refresh fetch
        meta). A NEW address bumps the map version so subscribed clients
        refresh; a known one just updates lag — EXCEPT when its
        ``parent`` changed (a re-parent), which is a topology edit and
        bumps the version too, REPLACING the row in place (announce
        dedup: rows are keyed by address, so a re-parented replica never
        duplicates itself). ``metrics`` is the replica's /metrics
        endpoint when it announces one — published in :meth:`view` so
        the fleet collector (telemetry/fleet.py) can adopt the replica
        as a scrape target. ``tier``/``fetches`` feed the fan-out-tree
        rollups: consecutive announces of the cumulative serve count
        become the per-node ``fetch_qps`` the tree-aware autoscaler
        ranks parents by. Never raises — a garbled announce must not
        fail the fetch that carried it."""
        try:
            addr = str(address)
            have = int(step)
        except (TypeError, ValueError):
            return
        now = self.clock()
        lag = max(0, int(global_step) - have)
        with self._lock:
            prev = self._replicas.get(addr)
            fresh = prev is None
            row = {"step": have, "ts": now, "lag_steps": lag,
                   "tier": max(1, int(tier or 1))}
            if metrics:
                row["metrics"] = str(metrics)
            if parent:
                row["parent"] = str(parent)
            if fetches is not None:
                try:
                    row["fetches"] = int(fetches)
                    if prev is not None and "fetches" in prev \
                            and now > prev["ts"]:
                        row["fetch_qps"] = round(
                            max(0, row["fetches"] - prev["fetches"])
                            / (now - prev["ts"]), 2)
                except (TypeError, ValueError):
                    pass
            moved = prev is not None \
                and prev.get("parent") != row.get("parent")
            self._replicas[addr] = row
            if fresh or moved:
                self._version += 1
                self._tm_map_version.set(self._version)
            self._expire_locked(now)
            self._tm_replicas.set(len(self._replicas))
            self._sync_children_locked()
        if addr not in self._tm_lag:
            self._tm_lag[addr] = (
                self._reg.gauge("dps_replica_lag_steps", replica=addr),
                self._reg.gauge("dps_replica_lag_seconds", replica=addr))
        self._tm_lag[addr][0].set(lag)
        self._tm_lag[addr][1].set(0.0)  # fresh announce = just synced

    def _sync_children_locked(self) -> None:
        """Recompute the per-node child-count gauges from the live rows.
        A node that LOST all its children (re-parent, expiry) gets its
        ``dps_replica_children`` series removed outright — a frozen
        child count on a dead interior node reads as a live subtree."""
        my_primary = self.primaries[self.shard_id]
        counts: dict[str, int] = {}
        for r in self._replicas.values():
            p = r.get("parent") or my_primary
            counts[p] = counts.get(p, 0) + 1
        for node in set(self._tm_children) - set(counts):
            self._tm_children.pop(node, None)
            self._reg.remove("dps_replica_children", node=node)
        for node, n in counts.items():
            if node not in self._tm_children:
                self._tm_children[node] = self._reg.gauge(
                    "dps_replica_children", node=node)
            self._tm_children[node].set(n)

    def _expire_locked(self, now: float) -> None:
        dead = [a for a, r in self._replicas.items()
                if now - r["ts"] > self.REPLICA_EXPIRE_S]
        for a in dead:
            del self._replicas[a]
            # The departed replica's lag series must go with it — a
            # frozen dps_replica_lag_* gauge reads as a live replica
            # that stopped syncing, the opposite of what happened.
            self._tm_lag.pop(a, None)
            self._reg.remove("dps_replica_lag_steps", replica=a)
            self._reg.remove("dps_replica_lag_seconds", replica=a)
        if dead:
            self._version += 1
            self._tm_map_version.set(self._version)
            self._sync_children_locked()

    def shard_map(self) -> dict:
        """The current wire shard map (docs/SHARDING.md schema). Only
        THIS shard's replica list is live-tracked here; peer shards'
        replica lists are published by their own primaries — a client
        merges maps per shard_id by version."""
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            shards = []
            for i, primary in enumerate(self.primaries):
                lo, hi = self._ranges[i]
                shards.append({
                    "shard_id": i, "slot_range": [lo, hi],
                    "primary": primary,
                    "replicas": (sorted(self._replicas)
                                 if i == self.shard_id else []),
                })
            return {"version": self._version, "slots": SHARD_SLOTS,
                    "shard_count": self.shard_count, "shards": shards}

    def topology(self) -> dict:
        """The fan-out-tree view shipped DOWN the tree as the delta-gated
        ``topology`` fetch attachment (docs/SHARDING.md "Fan-out trees"):
        version + primary + one row per live replica with its parent
        edge. This is what a child re-parents from when its own parent
        dies — deliberately small and flat."""
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            nodes = [{"address": a, "tier": r.get("tier", 1),
                      "parent": r.get("parent"),
                      "step": r["step"], "lag_steps": r["lag_steps"]}
                     for a, r in sorted(self._replicas.items())]
            return {"version": self._version,
                    "primary": self.primaries[self.shard_id],
                    "nodes": nodes}

    def view(self) -> dict:
        """The ``GET /cluster`` sharding block (rendered by
        ``cli status``): identity, map version, and per-replica lag."""
        now = self.clock()
        with self._lock:
            self._expire_locked(now)
            replicas = []
            tiers: dict[int, dict] = {}
            for a, r in sorted(self._replicas.items()):
                row = {"address": a, "step": r["step"],
                       "lag_steps": r["lag_steps"],
                       "announce_age_s": round(max(0.0, now - r["ts"]),
                                               3)}
                for k in ("metrics", "parent", "tier", "fetch_qps"):
                    if k in r:
                        row[k] = r[k]
                replicas.append(row)
                t = tiers.setdefault(int(r.get("tier", 1)),
                                     {"replicas": 0, "max_lag_steps": 0,
                                      "fetch_qps": 0.0})
                t["replicas"] += 1
                t["max_lag_steps"] = max(t["max_lag_steps"],
                                         r["lag_steps"])
                t["fetch_qps"] = round(t["fetch_qps"]
                                       + r.get("fetch_qps", 0.0), 2)
            out = {"shard_id": self.shard_id,
                   "shard_count": self.shard_count,
                   "map_version": self._version,
                   "slot_range": list(self._ranges[self.shard_id]),
                   "primaries": list(self.primaries),
                   "replicas": replicas,
                   "tiers": {str(t): v
                             for t, v in sorted(tiers.items())}}
        if self.migration_provider is not None:
            try:
                mig = self.migration_provider()
            except Exception:  # noqa: BLE001 — view is observability only
                mig = None
            if mig is not None:
                out["migration"] = mig
        return out
