"""The aggregation math, as pure unit-testable functions.

Every function here reproduces a specific piece of the reference server's
numerics bit-for-bit (SURVEY.md §4 names these the natural test seams):

- :func:`staleness_weight`  == server.py:171-186 ``apply_gradients_async``
- :func:`mean_gradients`    == server.py:145-169 ``aggregate_gradients_sync``
- :func:`sgd_apply`         == server.py:126-143 ``apply_gradients``
"""

from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

#: server.py:418 ``--staleness-bound`` default.
DEFAULT_STALENESS_BOUND = 5

#: server.py:178 decay constant and floor.
STALENESS_DECAY = 0.1
STALENESS_FLOOR = 0.1


def staleness_weight(staleness: int, decay: float = STALENESS_DECAY,
                     floor: float = STALENESS_FLOOR) -> float:
    """Down-weighting for stale gradients: ``max(0.1, 1/(1+0.1*s))``
    (server.py:178)."""
    return max(floor, 1.0 / (1.0 + decay * float(staleness)))


def mean_gradients(
    grads_per_worker: Iterable[Mapping[str, np.ndarray]],
) -> dict[str, np.ndarray]:
    """Element-wise mean over workers, per parameter (server.py:145-169).

    Every worker must supply the same parameter names; float32 accumulation.
    """
    grads_list = list(grads_per_worker)
    if not grads_list:
        raise ValueError("no gradients to aggregate")
    names = set(grads_list[0])
    for g in grads_list[1:]:
        if set(g) != names:
            raise ValueError("workers pushed mismatched parameter sets")
    n = len(grads_list)
    return {
        k: np.sum([np.asarray(g[k], np.float32) for g in grads_list], axis=0)
        / np.float32(n)
        for k in grads_list[0]
    }


def sgd_apply(params: dict[str, np.ndarray],
              grads: Mapping[str, np.ndarray],
              lr: float, weight: float = 1.0) -> None:
    """In-place plain SGD ``p -= lr * weight * g`` (server.py:133; the
    async path additionally scales by the staleness weight, server.py:183).

    Unknown gradient names are ignored, matching the reference's
    ``if name in self.parameters`` guard (server.py:131).
    """
    scale = np.float32(lr * weight)
    for name, g in grads.items():
        if name in params:
            params[name] -= scale * np.asarray(g, np.float32)
