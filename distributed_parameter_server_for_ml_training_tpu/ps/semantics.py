"""The aggregation math, as pure unit-testable functions.

Every function here reproduces a specific piece of the reference server's
numerics bit-for-bit (SURVEY.md §4 names these the natural test seams):

- :func:`staleness_weight`  == server.py:171-186 ``apply_gradients_async``
- :func:`mean_gradients`    == server.py:145-169 ``aggregate_gradients_sync``
- :func:`sgd_apply`         == server.py:126-143 ``apply_gradients``
"""

from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

#: server.py:418 ``--staleness-bound`` default.
DEFAULT_STALENESS_BOUND = 5

#: server.py:178 decay constant and floor.
STALENESS_DECAY = 0.1
STALENESS_FLOOR = 0.1


def staleness_weight(staleness: int, decay: float = STALENESS_DECAY,
                     floor: float = STALENESS_FLOOR) -> float:
    """Down-weighting for stale gradients: ``max(0.1, 1/(1+0.1*s))``
    (server.py:178)."""
    return max(floor, 1.0 / (1.0 + decay * float(staleness)))


def mean_gradients(
    grads_per_worker: Iterable[Mapping[str, np.ndarray]],
) -> dict[str, np.ndarray]:
    """Element-wise mean over workers, per parameter (server.py:145-169).

    Parameter names come from the FIRST worker's push, and each parameter is
    averaged over only the workers that supplied it (``valid_workers`` in
    ``aggregate_gradients_sync``) — a partial push therefore skews the mean
    for the parameters it carries rather than aborting the round. Names that
    appear only in later workers' pushes are dropped, exactly as the
    reference's ``param_names = list(worker_gradients[0].keys())`` does.
    Float32 accumulation. Returns ``{}`` for an empty round (server.py:147).
    """
    grads_list = list(grads_per_worker)
    if not grads_list:
        return {}
    out: dict[str, np.ndarray] = {}
    for name in grads_list[0]:
        total = None
        valid = 0
        for g in grads_list:
            if name in g:
                arr = np.asarray(g[name], np.float32)
                # no copy needed: accumulation and the final divide both
                # allocate fresh arrays, so `total` never aliases the output
                total = arr if total is None else total + arr
                valid += 1
        if valid > 0:
            out[name] = total / np.float32(valid)
    return out


def sgd_apply(params: dict[str, np.ndarray],
              grads: Mapping[str, np.ndarray],
              lr: float, weight: float = 1.0) -> None:
    """In-place plain SGD ``p -= lr * weight * g`` (server.py:133; the
    async path additionally scales by the staleness weight, server.py:183).

    Unknown gradient names are ignored, matching the reference's
    ``if name in self.parameters`` guard (server.py:131).
    """
    scale = np.float32(lr * weight)
    for name, g in grads.items():
        if name in params:
            params[name] -= scale * np.asarray(g, np.float32)
