"""Multi-job tenancy: job-scoped namespaces over one parameter server.

The server ran exactly ONE training job; production scale (ROADMAP north
star) means many concurrent jobs sharing one PS fleet without
interfering. This module is the namespace layer (docs/TENANCY.md):

- a **job id** rides the wire at registration and on every push/fetch
  envelope, capability-gated with the same degradation discipline as
  delta-fetch / trace-context — a legacy peer that never negotiated the
  ``jobs`` capability lands in the ``default`` job and sees the exact
  pre-tenancy wire;
- each job owns its OWN :class:`~.store.ParameterStore` — its own
  parameters, aggregation config (sync quorum for job A, async staleness
  for job B, on the same server), membership, and checkpoint lineage
  (snapshot meta v4 carries ``job``; restore refuses cross-job exactly
  like ``check_shard_identity`` refuses cross-shard);
- worker ids are made globally unique by striding the per-job local id
  (``global = job_index * WID_STRIDE + local``), so the cluster monitor,
  directives, and quarantine keep one flat id space;
- sharding composes: a job's canonical key names are prefixed
  (:func:`job_key`) before the consistent hash, so *a job is a set of
  slots* in the same 64-slot space (:func:`job_slots` reuses
  ``ps/sharding.py`` slot math).

``JOB_SPEC_FIELDS`` is the ``--jobs`` spec grammar's field table — a doc
contract pinned both directions to docs/TENANCY.md by the dpslint
catalog-drift pass, like the action/directive/metric catalogs.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, replace

__all__ = [
    "DEFAULT_JOB",
    "JOB_SPEC_FIELDS",
    "is_valid_job_id",
    "JobManager",
    "JobSpec",
    "WID_STRIDE",
    "job_key",
    "job_slots",
    "normalize_job_id",
    "parse_jobs_spec",
    "split_job_key",
    "split_wid",
]

#: The job every legacy peer (and every unlabeled envelope) lands in.
#: The default job IS the pre-tenancy server: bare key names, worker ids
#: starting at 0, the primary store — byte-identical behavior.
DEFAULT_JOB = "default"

#: Worker-id stride between jobs: ``global = index * WID_STRIDE +
#: local``. Far above any per-store membership cap (MAX_WORKERS = 32),
#: so global ids never collide and ``split_wid`` is pure arithmetic.
WID_STRIDE = 4096

#: Job ids are path/label-safe: they name metric label values, checkpoint
#: directories, and key prefixes.
_JOB_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_\-]{0,63}$")

#: ``--jobs`` / ``SubmitJob`` spec field -> meaning. A doc contract:
#: docs/TENANCY.md's "Job spec fields" table is pinned to the KEYS of
#: this dict in both directions (tools/dpslint catalog-drift).
JOB_SPEC_FIELDS = {
    "weight": "relative share of serve capacity under contention "
              "(float > 0, default 1.0)",
    "max_inflight": "hard cap on the job's concurrently admitted RPCs "
                    "(int >= 1, default 8)",
    "mode": "aggregation mode override for the job's store "
            "(sync | async; default: inherit the server's)",
    "learning_rate": "server-side SGD learning rate override (float > 0)",
    "staleness_bound": "async staleness bound override (int >= 0)",
    "sync_quorum": "sync quorum override (int >= 1; implies strict "
                   "rounds, ps/store.py)",
    "total_workers": "expected worker count for the job's store "
                     "(int >= 1; default: inherit the server's)",
    "min_workers": "worker-autoscaler floor for the job (int >= 0, "
                   "default 1)",
    "max_workers": "worker-autoscaler ceiling for the job "
                   "(int >= min_workers, default 4)",
}


def is_valid_job_id(value) -> bool:
    """True when ``value`` is a well-formed job id (the grammar in
    :data:`_JOB_ID_RE`; label/path/prefix-safe)."""
    return isinstance(value, str) and bool(_JOB_ID_RE.match(value))


def normalize_job_id(value) -> str:
    """Coerce a wire job id to a valid one; garbled/absent degrades to
    :data:`DEFAULT_JOB`. Never raises — the tenancy layer follows the
    health-report discipline: a bad value from a buggy peer lands in the
    default namespace, it does not fail the RPC that carried it."""
    return value if is_valid_job_id(value) else DEFAULT_JOB


def job_key(job: str, name: str) -> str:
    """Canonical namespaced key for a parameter of ``job``. The default
    job keeps BARE names (pre-tenancy compatibility: its checkpoints,
    journals, and shard routing are byte-identical); other jobs prefix
    with ``job::`` — ``::`` never appears in flax param paths, so the
    mapping is unambiguous both ways."""
    return name if job == DEFAULT_JOB else f"{job}::{name}"


def split_job_key(key: str) -> tuple[str, str]:
    """Inverse of :func:`job_key`: ``(job, bare_name)``."""
    job, sep, name = key.partition("::")
    if sep and _JOB_ID_RE.match(job):
        return job, name
    return DEFAULT_JOB, key


def job_slots(job: str, names) -> list[int]:
    """The consistent-hash slots a job's parameters occupy — *a job is a
    set of slots* in the same space shards partition, so tenancy composes
    with sharding instead of inventing a second routing scheme
    (ps/sharding.py:key_slot over the namespaced keys)."""
    from .sharding import key_slot
    return sorted({key_slot(job_key(job, n)) for n in names})


def split_wid(global_wid: int) -> tuple[int, int]:
    """``global worker id -> (job_index, local_wid)``."""
    gw = int(global_wid)
    return gw // WID_STRIDE, gw % WID_STRIDE


@dataclass
class JobSpec:
    """One job's declaration (``--jobs`` spec / ``SubmitJob``).

    Fields documented in :data:`JOB_SPEC_FIELDS` (docs/TENANCY.md).
    ``None`` overrides inherit the server's primary store config.
    """

    name: str
    weight: float = 1.0
    max_inflight: int = 8
    mode: str | None = None
    learning_rate: float | None = None
    staleness_bound: int | None = None
    sync_quorum: int | None = None
    total_workers: int | None = None
    min_workers: int = 1
    max_workers: int = 4

    def __post_init__(self):
        if not _JOB_ID_RE.match(self.name or ""):
            raise ValueError(f"invalid job name {self.name!r} (want "
                             f"[A-Za-z0-9][A-Za-z0-9_-]*, <= 64 chars)")
        if not self.weight > 0:
            raise ValueError(f"job {self.name}: weight must be > 0, "
                             f"got {self.weight}")
        if self.max_inflight < 1:
            raise ValueError(f"job {self.name}: max_inflight must be "
                             f">= 1, got {self.max_inflight}")
        if self.mode not in (None, "sync", "async"):
            raise ValueError(f"job {self.name}: mode must be sync|async, "
                             f"got {self.mode!r}")
        if not 0 <= self.min_workers <= self.max_workers:
            raise ValueError(f"job {self.name}: need 0 <= min_workers "
                             f"({self.min_workers}) <= max_workers "
                             f"({self.max_workers})")


#: Spec-field parsers; unknown keys raise (a typo'd field must fail the
#: launch, not silently become a no-op).
_FIELD_CASTS = {
    "weight": float,
    "max_inflight": int,
    "mode": str,
    "learning_rate": float,
    "staleness_bound": int,
    "sync_quorum": int,
    "total_workers": int,
    "min_workers": int,
    "max_workers": int,
}


def parse_jobs_spec(spec: str) -> list[JobSpec]:
    """Parse the ``--jobs`` grammar (docs/TENANCY.md):

    ``name[:field=value[,field=value...]]`` entries separated by ``;`` —
    e.g. ``vision:weight=3,mode=sync,sync_quorum=2;ranker:weight=1``.
    Raises ``ValueError`` on any malformed entry; duplicate or
    ``default`` names are rejected (the default job always exists)."""
    jobs: list[JobSpec] = []
    seen: set[str] = set()
    for entry in (e.strip() for e in str(spec).split(";")):
        if not entry:
            continue
        name, _, rest = entry.partition(":")
        name = name.strip()
        fields: dict = {}
        if rest:
            for kv in rest.split(","):
                key, sep, value = kv.partition("=")
                key = key.strip()
                if not sep or key not in _FIELD_CASTS:
                    raise ValueError(
                        f"jobs spec: bad field {kv!r} in {entry!r} "
                        f"(known: {', '.join(sorted(_FIELD_CASTS))})")
                try:
                    fields[key] = _FIELD_CASTS[key](value.strip())
                except ValueError as e:
                    raise ValueError(f"jobs spec: bad value for "
                                     f"{key!r}: {value!r}") from e
        if name == DEFAULT_JOB:
            raise ValueError("jobs spec: 'default' is implicit and "
                             "cannot be redeclared")
        if name in seen:
            raise ValueError(f"jobs spec: duplicate job {name!r}")
        seen.add(name)
        jobs.append(JobSpec(name=name, **fields))
    return jobs


class _JobState:
    """One job's server-side state (store + bookkeeping)."""

    def __init__(self, name: str, index: int, spec: JobSpec | None,
                 store, created_ts: float):
        self.name = name
        self.index = index
        self.spec = spec
        self.store = store
        self.created_ts = created_ts


class JobManager:
    """Registry of live jobs and their per-job stores.

    The default job wraps the server's PRIMARY store (index 0) so a
    tenancy-enabled server with no extra jobs behaves byte-identically
    to a pre-tenancy one. Non-default jobs get their own
    :class:`~.store.ParameterStore`, built from the primary's config
    with the spec's overrides and the primary's CURRENT parameters as
    the init point (a job submitted mid-run starts from the warmest
    available basis; docs/TENANCY.md).

    Thread-safety: ``submit``/``drain`` run on gRPC handler threads
    (the ``SubmitJob`` op) while every push/fetch resolves
    ``store_for``; one small lock guards the table.
    """

    def __init__(self, store, specs=(), registry=None, clock=time.time):
        self.clock = clock
        self._lock = threading.Lock()
        from ..telemetry import get_registry
        self._reg = registry or get_registry()
        #: Optional WeightedFairAdmission (comms/service.py); wired by
        #: ``cli serve`` so drain() can drop the job's QoS series too.
        self.qos = None
        self._jobs: dict[str, _JobState] = {}  # guarded by: self._lock
        self._by_index: list[str] = []  # guarded by: self._lock
        with self._lock:
            self._jobs[DEFAULT_JOB] = _JobState(
                DEFAULT_JOB, 0, None, store, self.clock())
            self._by_index.append(DEFAULT_JOB)
        for spec in specs:
            self.submit(spec)

    # -- lifecycle ------------------------------------------------------------

    def submit(self, spec: JobSpec):
        """Create a job from its spec; returns its ``_JobState``.
        Raises ``ValueError`` on a duplicate name."""
        from .store import ParameterStore
        with self._lock:
            primary = self._jobs[DEFAULT_JOB].store
        cfg = primary.config
        overrides = {"job_id": spec.name}
        if spec.mode is not None:
            overrides["mode"] = spec.mode
        if spec.learning_rate is not None:
            overrides["learning_rate"] = spec.learning_rate
        if spec.staleness_bound is not None:
            overrides["staleness_bound"] = spec.staleness_bound
        if spec.sync_quorum is not None:
            overrides["sync_quorum"] = spec.sync_quorum
        if spec.total_workers is not None:
            overrides["total_workers"] = spec.total_workers
        # Codec sentinel: the primary already resolved push_codec; carry
        # the RESOLVED value so the job store never re-defaults.
        overrides["push_codec"] = primary.push_codec
        job_cfg = replace(cfg, **overrides)
        params, _ = primary.snapshot()
        store = ParameterStore(params, job_cfg)
        with self._lock:
            if spec.name in self._jobs:
                raise ValueError(f"job {spec.name!r} already exists")
            state = _JobState(spec.name, len(self._by_index), spec, store,
                              self.clock())
            self._jobs[spec.name] = state
            self._by_index.append(spec.name)
        print(f"JOB_SUBMITTED job={spec.name} index={state.index} "
              f"mode={store.config.mode}", flush=True)
        return state

    def drain(self, name: str) -> bool:
        """Remove a drained job and its per-job ``dps_job_*`` metric
        series (the PR 11 replica-lag lifecycle fix pattern: a drained
        job's frozen series must not read as a live-but-idle job). The
        default job cannot drain. Returns True when the job existed."""
        if name == DEFAULT_JOB:
            raise ValueError("the default job cannot be drained")
        with self._lock:
            state = self._jobs.pop(name, None)
            # Index slots are NOT reused: a later job must never inherit
            # a drained job's worker-id range (stale global wids would
            # alias into the newcomer).
        if state is None:
            return False
        for series in ("dps_job_queue_depth", "dps_job_admitted_total",
                       "dps_job_throttled_total", "dps_job_workers",
                       "dps_job_autoscale_target_workers"):
            self._reg.remove(series, job=name)
        if self.qos is not None:
            try:
                self.qos.forget_job(name)
            except Exception:  # noqa: BLE001 — drain must not fail late
                pass
        print(f"JOB_DRAINED job={name}", flush=True)
        return True

    # -- resolution -----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return [n for n in self._by_index if n in self._jobs]

    def store_for(self, job: str):
        """The job's store; unknown jobs degrade to the default store
        (the namespace discipline: a stray id must never fail an RPC,
        and the default namespace is where unlabeled traffic lands)."""
        with self._lock:
            state = self._jobs.get(job) or self._jobs[DEFAULT_JOB]
            return state.store

    def has_job(self, job: str) -> bool:
        with self._lock:
            return job in self._jobs

    def index_of(self, job: str) -> int:
        with self._lock:
            state = self._jobs.get(job) or self._jobs[DEFAULT_JOB]
            return state.index

    def job_name_of(self, global_wid) -> str:
        """Job name for a strided global worker id (unknown index
        degrades to the default job — e.g. a drained job's last rows)."""
        try:
            idx, _ = split_wid(global_wid)
        except (TypeError, ValueError):
            return DEFAULT_JOB
        with self._lock:
            if 0 <= idx < len(self._by_index):
                name = self._by_index[idx]
                if name in self._jobs:
                    return name
        return DEFAULT_JOB

    def to_global(self, job: str, local_wid: int) -> int:
        return self.index_of(job) * WID_STRIDE + int(local_wid)

    def qos_table(self) -> dict[str, tuple[float, int]]:
        """``job -> (weight, max_inflight)`` for the admission scheduler
        (comms/service.py WeightedFairAdmission). The spec-less default
        job gets the spec defaults (weight 1.0, max_inflight 8)."""
        with self._lock:
            return {name: ((1.0, 8) if st.spec is None
                           else (st.spec.weight, st.spec.max_inflight))
                    for name, st in self._jobs.items()}

    def spec_for(self, job: str) -> JobSpec | None:
        with self._lock:
            state = self._jobs.get(job)
            return state.spec if state is not None else None

    # -- membership (monitor-facing, global worker ids) -----------------------

    def membership_snapshot(self) -> list[int]:
        """Union of every job's live membership as GLOBAL worker ids —
        the ``ClusterMonitor`` reads this instead of the primary store's
        snapshot when tenancy is on, so ``/cluster`` rows span jobs."""
        out: list[int] = []
        with self._lock:
            states = list(self._jobs.values())
        for st in states:
            base = st.index * WID_STRIDE
            try:
                out.extend(base + int(w)
                           for w in st.store.membership_snapshot())
            except Exception:  # noqa: BLE001 — any backend, any failure
                continue
        return sorted(out)

    @property
    def last_seen(self) -> dict[int, float]:
        """Merged ``last_seen`` across jobs, keyed by global wid."""
        out: dict[int, float] = {}
        with self._lock:
            states = list(self._jobs.values())
        for st in states:
            base = st.index * WID_STRIDE
            for w, ts in (getattr(st.store, "last_seen", {}) or {}).items():
                out[base + int(w)] = float(ts)
        return out

    def expire_stale_workers(self) -> list[int]:
        """Run membership expiry on every job store; returns reaped
        GLOBAL worker ids (the serve loop feeds these to
        ``monitor.note_expired``)."""
        reaped: list[int] = []
        with self._lock:
            states = list(self._jobs.values())
        for st in states:
            fn = getattr(st.store, "expire_stale_workers", None)
            if not callable(fn):
                continue
            base = st.index * WID_STRIDE
            try:
                reaped.extend(base + int(w) for w in fn() or [])
            except Exception:  # noqa: BLE001 — expiry is best-effort
                continue
        return reaped

    # -- read side ------------------------------------------------------------

    def view(self) -> dict:
        """The ``"jobs"`` block of ``GET /cluster`` (docs/TENANCY.md):
        per-job config, live workers (global ids), step, and — when a
        QoS scheduler is attached — admission counters."""
        with self._lock:
            states = list(self._jobs.values())
        qos_view = {}
        if self.qos is not None:
            try:
                qos_view = self.qos.view()
            except Exception:  # noqa: BLE001 — view must render regardless
                qos_view = {}
        jobs = {}
        for st in states:
            base = st.index * WID_STRIDE
            try:
                members = [base + int(w)
                           for w in st.store.membership_snapshot()]
            except Exception:  # noqa: BLE001
                members = []
            cfg = st.store.config
            row = {
                "index": st.index,
                "mode": cfg.mode,
                "global_step": int(getattr(st.store, "global_step", 0)),
                "workers": sorted(members),
                "slots": job_slots(st.name, st.store.param_names()),
            }
            if st.spec is not None:
                row["weight"] = st.spec.weight
                row["max_inflight"] = st.spec.max_inflight
                row["min_workers"] = st.spec.min_workers
                row["max_workers"] = st.spec.max_workers
            if st.name in qos_view:
                row.update(qos_view[st.name])
            self._reg.gauge("dps_job_workers", job=st.name).set(
                len(members))
            jobs[st.name] = row
        return jobs
