"""Periodic registry flush as incremental ``METRICS_JSON`` snapshot lines.

The reference's ETL regex-scrapes ``METRICS_JSON: {...}`` from process logs
(parse_cloudwatch_logs.py:100); this emitter rides the SAME convention —
``utils.metrics.emit_metrics_json`` prints the line — so every existing
collection pipeline (CloudWatch filter, ``analysis/parse_logs.py``, pod-log
ssh ingestion) picks up live time-series for free. Snapshot payloads are
distinguished by ``"kind": "snapshot"``; the final-stats exit line has no
``kind`` field, and :func:`..analysis.parse_logs.parse_experiment` filters
snapshots out of the final aggregation so the reference schema is unchanged.

Snapshot line shape::

    METRICS_JSON: {"kind": "snapshot", "seq": 3, "ts": 1724...,
                   "uptime_seconds": 15.2, "role": "server", "pid": 1234,
                   "counters": {...}, "gauges": {...}, "histograms": {...}}

Values are CUMULATIVE (counters monotonic since process start, histograms
full bucket counts); consumers derive rates from consecutive-snapshot
deltas (``analysis/parse_logs.py:build_telemetry_timeseries``). Cumulative
beats per-interval deltas on a lossy transport: a dropped line costs one
sample, not a permanently skewed running total.
"""

from __future__ import annotations

import os
import threading
import time
from typing import IO

from ..utils.metrics import emit_metrics_json
from .registry import MetricsRegistry, get_registry


class SnapshotEmitter:
    """Daemon thread flushing a registry every ``interval`` seconds.

    ``proc`` labels (role, worker name, ...) are merged into every line so a
    multi-process run's interleaved stdout remains attributable. ``stop()``
    always emits one final snapshot — a run shorter than one interval still
    leaves a complete record (the failure mode that cost round 5 its perf
    number was exactly "process died, nothing written").
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 interval: float = 5.0, role: str = "process",
                 proc: dict | None = None, stream: IO | None = None,
                 clock=time.time):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.registry = registry or get_registry()
        self.interval = float(interval)
        self.proc = {"role": role, "pid": os.getpid(), **(proc or {})}
        self.stream = stream
        self.clock = clock
        self.seq = 0  # guarded by: self._emit_lock
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._emit_lock = threading.Lock()  # tick vs final-flush race

    def emit_once(self) -> dict:
        """Emit one snapshot line; returns the payload (tests, callers)."""
        with self._emit_lock:
            self.seq += 1
            payload = {
                "kind": "snapshot",
                "seq": self.seq,
                "ts": round(self.clock(), 3),
                "uptime_seconds": round(self.clock() - self._t0, 3),
                **self.proc,
                **self.registry.snapshot(),
            }
            emit_metrics_json(payload, self.stream)
            return payload

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.emit_once()

    def flush_now(self) -> None:
        """Shutdown-hook form of :meth:`emit_once`: flush one final
        snapshot unless the emitter was already stopped (whose ``stop``
        emitted the final line). Registered with
        ``telemetry.add_shutdown_flush`` so a SIGTERM'd process's tail
        interval is never silently dropped (ISSUE 3 satellite)."""
        if not self._stop.is_set():
            self.emit_once()

    def start(self) -> "SnapshotEmitter":
        if self._thread is not None:
            raise RuntimeError("emitter already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-snapshot")
        self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the loop; ``final=True`` (default) flushes a last snapshot
        so the stream always ends with the process's complete totals."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval))
            self._thread = None
        if final:
            self.emit_once()

    def __enter__(self) -> "SnapshotEmitter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(final=True)
