"""Periodic registry flush as incremental ``METRICS_JSON`` snapshot lines.

The reference's ETL regex-scrapes ``METRICS_JSON: {...}`` from process logs
(parse_cloudwatch_logs.py:100); this emitter rides the SAME convention —
``utils.metrics.emit_metrics_json`` prints the line — so every existing
collection pipeline (CloudWatch filter, ``analysis/parse_logs.py``, pod-log
ssh ingestion) picks up live time-series for free. Snapshot payloads are
distinguished by ``"kind": "snapshot"``; the final-stats exit line has no
``kind`` field, and :func:`..analysis.parse_logs.parse_experiment` filters
snapshots out of the final aggregation so the reference schema is unchanged.

Snapshot line shape::

    METRICS_JSON: {"kind": "snapshot", "seq": 3, "ts": 1724...,
                   "uptime_seconds": 15.2, "role": "server", "pid": 1234,
                   "counters": {...}, "gauges": {...}, "histograms": {...}}

Values are CUMULATIVE (counters monotonic since process start, histograms
full bucket counts); consumers derive rates from consecutive-snapshot
deltas (``analysis/parse_logs.py:build_telemetry_timeseries``). Cumulative
beats per-interval deltas on a lossy transport: a dropped line costs one
sample, not a permanently skewed running total.
"""

from __future__ import annotations

import os
import threading
import time
from typing import IO

from ..utils.metrics import emit_metrics_json
from .registry import MetricsRegistry, get_registry


class SnapshotEmitter:
    """Daemon thread flushing a registry every ``interval`` seconds.

    ``proc`` labels (role, worker name, ...) are merged into every line so a
    multi-process run's interleaved stdout remains attributable. ``stop()``
    always emits one final snapshot — a run shorter than one interval still
    leaves a complete record (the failure mode that cost round 5 its perf
    number was exactly "process died, nothing written").
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 interval: float = 5.0, role: str = "process",
                 proc: dict | None = None, stream: IO | None = None,
                 journal=None, clock=time.time):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.registry = registry or get_registry()
        self.interval = float(interval)
        self.proc = {"role": role, "pid": os.getpid(), **(proc or {})}
        self.stream = stream
        self.journal = journal  # guarded by: self._emit_lock
        self.clock = clock
        self.seq = 0  # guarded by: self._emit_lock
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._emit_lock = threading.Lock()  # tick vs final-flush race

    def emit_once(self) -> dict:
        """Emit one snapshot line; returns the payload (tests, callers)."""
        with self._emit_lock:
            self.seq += 1
            payload = {
                "kind": "snapshot",
                "seq": self.seq,
                "ts": round(self.clock(), 3),
                "uptime_seconds": round(self.clock() - self._t0, 3),
                **self.proc,
                **self.registry.snapshot(),
            }
            emit_metrics_json(payload, self.stream)
            if self.journal is not None:
                try:
                    self.journal.append("snapshot",
                                        self._journal_payload(payload))
                except Exception:  # noqa: BLE001 — durability is
                    pass           # best-effort beside the live line
            return payload

    @staticmethod
    def _journal_payload(payload: dict) -> dict:
        """The journaled copy of one snapshot, minus the zero-valued
        counter/histogram vocabulary. The live METRICS_JSON line keeps
        zeros on purpose (scrapes must show the full vocabulary), but
        journaling the pre-created alert/fault grids re-serializes
        kilobytes of zeros every interval — measured ~72% of the bytes.
        Retro-query math is cumulative, so an absent series reads as
        zero exactly like a present zero did."""
        out = {k: v for k, v in payload.items() if k != "kind"}
        for group in ("counters", "gauges"):
            vals = out.get(group)
            if isinstance(vals, dict):
                out[group] = {k: v for k, v in vals.items() if v}
        hists = out.get("histograms")
        if isinstance(hists, dict):
            out["histograms"] = {
                k: h for k, h in hists.items()
                if not isinstance(h, dict) or h.get("count")}
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.emit_once()

    def flush_now(self) -> None:
        """Shutdown-hook form of :meth:`emit_once`: flush one final
        snapshot unless the emitter was already stopped (whose ``stop``
        emitted the final line). Registered with
        ``telemetry.add_shutdown_flush`` so a SIGTERM'd process's tail
        interval is never silently dropped (ISSUE 3 satellite). Also
        seals the journal segment (ISSUE 18): the shutdown path must
        leave a crash-consistent, fsync'd tail on disk."""
        if not self._stop.is_set():
            self.emit_once()
        self._seal_journal()

    def start(self) -> "SnapshotEmitter":
        if self._thread is not None:
            raise RuntimeError("emitter already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-snapshot")
        self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the loop; ``final=True`` (default) flushes a last snapshot
        so the stream always ends with the process's complete totals."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval))
            self._thread = None
        if final:
            self.emit_once()
            self._seal_journal()

    def _seal_journal(self) -> None:
        with self._emit_lock:
            if self.journal is not None:
                try:
                    self.journal.seal()
                except Exception:  # noqa: BLE001 — shutdown never raises
                    pass

    def __enter__(self) -> "SnapshotEmitter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(final=True)
