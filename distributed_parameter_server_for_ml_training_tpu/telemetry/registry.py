"""In-process metrics registry: counters, gauges, fixed-bucket histograms.

The reference's observability was stdout prints plus ONE ``METRICS_JSON``
line per process at exit (server.py:367, worker.py:435) — nothing could be
read *while a job ran*, and the signals adaptive-sync/compression work needs
(staleness distributions, per-RPC byte/time accounting; ACE-Sync and the
gradient-compression-utility papers in PAPERS.md) were computed internally
and thrown away. This registry is the live half of the story: hot paths
record into process-global instruments, and two read-side surfaces consume
them — the periodic ``METRICS_JSON`` snapshot stream
(:mod:`.snapshot`, same regex convention as the exit line so the existing
ETL keeps working) and a Prometheus text endpoint (:mod:`.prometheus`).

Design constraints, in order:

1. **Hot-path cost.** A record is one ``perf_counter`` call plus a lock'd
   float add (counter) or bisect+add (histogram) — single-digit
   microseconds. Instruments are created ONCE (at store/client/worker
   construction) and held as attributes; the registry dict is never touched
   per operation. ``tests/test_telemetry.py`` pins the overhead to < 2% of
   a realistic store push/fetch.
2. **Thread safety.** Stores serve pushes from N worker/RPC threads
   concurrently; every instrument guards its state with its own small lock
   (no global registry lock on the hot path).
3. **Fixed bucket schemes.** Histograms use closed, documented edges
   (latency / payload bytes / staleness-versions below) so snapshot streams
   from different processes and runs aggregate without schema negotiation.
"""

from __future__ import annotations

import sys
import threading
import time
from bisect import bisect_left

#: Wall-time buckets (seconds): 100 us .. 60 s, roughly 1-2.5-5 per decade.
#: Covers everything from a device-store dict copy to a cold sync round.
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: THE shared scheme for recorded durations (SLO-grade serving latency):
#: 250 us .. 30 s with extra resolution through the 1-100 ms band where
#: RPC handler latencies and SLO thresholds live — a p99 objective at
#: 50/75/100 ms needs an edge AT the threshold for bucket-counting
#: "good" events to be exact, which the coarser LATENCY_BUCKETS_S
#: (jumping 25 -> 50 -> 100 ms) cannot give. New duration histograms use
#: this scheme; LATENCY_BUCKETS_S remains for the pre-existing series
#: whose committed snapshot history pins their edges.
LATENCY_BUCKETS = (
    0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.0075, 0.01, 0.025, 0.05,
    0.075, 0.1, 0.25, 0.5, 0.75, 1.0, 2.5, 5.0, 10.0, 30.0,
)

#: Payload-size buckets (bytes): 1 KiB .. 1 GiB in x4 steps. The ResNet-18
#: fp32 payload (~45 MB, the reference's dominant wire term, server.py:222)
#: lands mid-scheme; its fp16/int8 codec forms land one/two buckets lower.
BYTES_BUCKETS = (
    1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10,
    1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20, 1 << 30,
)

#: Async staleness buckets (versions behind, server.py:293-294 semantics).
#: Dense through the default bound (DEFAULT_STALENESS_BOUND = 5) so the
#: bounded region is fully resolved, then doubling to the 32-worker cap.
STALENESS_BUCKETS = (0, 1, 2, 3, 4, 5, 8, 16, 32)

#: Value-magnitude buckets (dimensionless, log scale): 1e-4 .. 1e2 at
#: ~1-2.5-5 per decade, then decades to 1e6. The latency/byte schemes above
#: are wrong for LOSS and GRADIENT-NORM magnitudes — a cross-entropy loss
#: lives around 1-5, a healthy grad norm anywhere in 1e-2..1e2, and the
#: interesting excursions (vanishing grads, explosions) are orders of
#: magnitude in either direction. Used by the cluster health monitor's
#: report histograms (telemetry/cluster.py); an observation past the last
#: edge (incl. any finite overflow) lands in the +Inf bucket.
VALUE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    1000.0, 10000.0, 100000.0, 1000000.0,
)


def _label_key(labels: dict) -> str:
    """Stable ``name{k=v,...}`` suffix; '' for an unlabelled instrument."""
    if not labels:
        return ""
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return "{" + inner + "}"


class Counter:
    """Monotonic float counter. ``inc`` rejects negative deltas — the
    monotonicity contract is what lets the ETL derive rates from snapshot
    deltas without sentinel handling."""

    __slots__ = ("name", "labels", "_value", "_lock")
    kind = "counter"

    def __init__(self, name: str, labels: dict | None = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0  # guarded by: self._lock
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Gauge:
    """Point-in-time value (global step, live worker count, last accuracy)."""

    __slots__ = ("name", "labels", "_value", "_lock")
    kind = "gauge"

    def __init__(self, name: str, labels: dict | None = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0  # guarded by: self._lock
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram: per-bucket counts (NON-cumulative), sum, and
    count. ``le`` edges are upper bounds; observations above the last edge
    land in the implicit overflow bucket (rendered ``+Inf`` on the
    Prometheus surface, stored as the final count here).

    An observation may carry an **exemplar** — a trace id sampled by the
    caller (:class:`ExemplarSampler` head sampling) — and the histogram
    keeps the LAST exemplar per bucket: one bounded dict regardless of
    traffic, so a fleet p99 spike in a high bucket always points at a
    recent trace that actually landed there (docs/OBSERVABILITY.md,
    "Fleet observatory").
    """

    __slots__ = ("name", "labels", "buckets", "_counts", "_sum", "_count",
                 "_exemplars", "_lock")
    kind = "histogram"

    def __init__(self, name: str, buckets=LATENCY_BUCKETS_S,
                 labels: dict | None = None):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name}: buckets must be a sorted, "
                             f"non-empty sequence, got {buckets!r}")
        self.name = name
        self.labels = dict(labels or {})
        self.buckets = tuple(float(b) for b in buckets)
        # guarded by: self._lock
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = overflow
        self._sum = 0.0  # guarded by: self._lock
        self._count = 0  # guarded by: self._lock
        self._exemplars: dict[int, dict] = {}  # guarded by: self._lock
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: str | None = None) -> None:
        v = float(v)
        i = bisect_left(self.buckets, v)
        if exemplar is None:
            with self._lock:
                self._counts[i] += 1
                self._sum += v
                self._count += 1
            return
        ex = {"trace_id": exemplar, "value": v, "ts": time.time()}
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._exemplars[i] = ex

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        """JSON-ready view: edges + per-bucket (non-cumulative) counts.
        ``exemplars`` (bucket index, as a string for JSON round-trips ->
        ``{trace_id, value, ts}``) appears only when at least one
        observation carried one — exemplar-free histograms keep the
        exact pre-exemplar snapshot shape."""
        with self._lock:
            out = {"le": list(self.buckets),
                   "counts": list(self._counts),
                   "sum": self._sum,
                   "count": self._count}
            if self._exemplars:
                out["exemplars"] = {str(i): dict(ex)
                                    for i, ex in self._exemplars.items()}
            return out


class ExemplarSampler:
    """Deterministic head sampler for exemplar attachment.

    Counter-based, same discipline as the serving canary split
    (comms/replica.py CanaryController): a rate of ``r`` becomes "every
    round(1/r)-th call samples", with a seed-derived phase so co-started
    processes don't all sample the same beat. No RNG on the hot path —
    one lock'd increment + modulo — which keeps the cost inside the
    <2% overhead guard (tests/test_telemetry.py) and makes sampling
    decisions reproducible under a fixed seed (property-tested in
    tests/test_fleet.py).
    """

    __slots__ = ("period", "_n", "_phase", "_lock")

    def __init__(self, rate: float = 0.1, seed: int = 0):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"exemplar rate must be in (0, 1], got {rate}")
        self.period = max(1, round(1.0 / rate))
        self._phase = seed % self.period
        self._n = 0  # guarded by: self._lock
        self._lock = threading.Lock()

    def sample(self) -> bool:
        """True when this call should attach an exemplar."""
        with self._lock:
            n = self._n
            self._n += 1
        return n % self.period == self._phase


class MetricsRegistry:
    """Get-or-create instrument factory + read-side collection surface.

    Identity is (name, sorted labels): two ``counter()`` calls with the same
    name+labels return the SAME object, so call sites never coordinate.
    Re-requesting a name as a different kind (or a histogram with different
    buckets) raises — silent aliasing would corrupt both surfaces.
    """

    def __init__(self):
        self._instruments: dict[str, object] = {}  # guarded by: self._lock
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, labels: dict, **kwargs):
        key = name + _label_key(labels)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, labels=labels, **kwargs)
                self._instruments[key] = inst
                return inst
        if not isinstance(inst, cls):
            raise TypeError(f"metric {key!r} already registered as "
                            f"{inst.kind}, requested {cls.kind}")
        if kwargs.get("buckets") is not None \
                and inst.buckets != tuple(float(b)
                                          for b in kwargs["buckets"]):
            raise ValueError(f"histogram {key!r} already registered with "
                             f"buckets {inst.buckets}")
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, buckets=LATENCY_BUCKETS_S,
                  **labels) -> Histogram:
        return self._get_or_create(Histogram, name, labels, buckets=buckets)

    def remove(self, name: str, **labels) -> bool:
        """Drop one labelled series from both read surfaces. Returns
        whether anything was removed. This is the lifecycle half the
        get-or-create idiom lacks: a label set keyed on a DYNAMIC member
        (``dps_replica_lag_steps{replica=...}``) outlives the member and
        serves its last value forever unless the owner that learned of
        the departure removes the series. Holders keeping a stale
        reference can still record into it; it just stops being
        collected — and a later get-or-create mints a fresh instrument.
        """
        key = name + _label_key(labels)
        with self._lock:
            return self._instruments.pop(key, None) is not None

    def collect(self) -> list:
        """All live instruments, sorted by key (stable output ordering)."""
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def snapshot(self) -> dict:
        """One JSON-serializable view of everything, grouped by kind:
        ``{"counters": {key: value}, "gauges": {...},
        "histograms": {key: {le, counts, sum, count}}}``. Keys carry their
        labels inline (``name{k=v}``) so the snapshot needs no side table.
        """
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for inst in self.collect():
            key = inst.name + _label_key(inst.labels)
            out[inst.kind + "s"][key] = inst.snapshot()
        return out

    def reset(self) -> None:
        """Drop every instrument (tests; never called on a live process —
        holders keep stale references)."""
        with self._lock:
            self._instruments.clear()


#: Process-global default registry. Hot paths (stores, RPC client/service,
#: workers, trainers) record here; the snapshot emitter and Prometheus
#: endpoint read from here. Tests that need isolation construct their own
#: MetricsRegistry — they don't reset the global one mid-run.
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def register_build_info(registry: MetricsRegistry | None = None) -> Gauge:
    """Register the ``dps_build_info`` gauge (value 1; the information is
    in the labels: package version, jax version, host platform) — the
    standard Prometheus idiom for fleet-wide scrape correlation: join any
    other series on the target to see which build produced it."""
    import jax

    from .. import __version__
    g = (registry or get_registry()).gauge(
        "dps_build_info", version=__version__, jax=jax.__version__,
        platform=sys.platform)
    g.set(1)
    return g
