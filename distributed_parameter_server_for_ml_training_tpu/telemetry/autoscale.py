"""Replica autoscaler: measured fetch load -> replica fleet size.

The serve tier made read capacity HORIZONTAL (docs/SHARDING.md: replicas
are cheap byte-caches, the recorded ≥10× aggregate fetch-QPS lever) but
left its size an operator constant. This module closes that loop at the
shard primary — the one process that already measures the two signals
that matter:

- **fetch QPS** at the primary (``dps_rpc_handler_calls_total{rpc=
  FetchParameters}`` plus any colocated replica's serve counter), read as
  counter DELTAS between ticks — the same snapshot-delta discipline the
  ETL uses, so the autoscaler sees exactly what dashboards see;
- **replica lag** (``dps_replica_lag_steps`` via ShardInfo's view): a
  fleet that cannot keep up with the delta-feed is a reason to stop
  shrinking, not to grow — more replicas multiply the primary's feed
  fan-out, they don't speed it up.

Decisions follow the remediation engine's discipline (telemetry/
remediation.py): rate-limited by a cooldown, bounded by [min, max],
dry-runnable, every decision counted in
``dps_remediation_actions_total{action=replica_grow|replica_shrink}``
and kept in a bounded event list the cluster view serves. The EXECUTE
half lives in :class:`~..ps.supervisor.ReplicaPool` (spawning ``cli
replica`` children); the autoscaler stays a pure policy head so tests
drive it with a fake pool and a fake QPS source.

Ticked from the :class:`~.cluster.ClusterMonitor` background loop
(``monitor.autoscaler = ...``; ``cli serve --autoscale`` wires it) — the
monitor already owns the "periodically look at the cluster" thread, and
a tick that raises must never take the serve loop down, so the monitor's
swallow-and-continue loop is exactly the right host.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from .registry import get_registry
from .remediation import note_action

__all__ = ["AutoscalePolicy", "ReplicaAutoscaler"]

#: Decisions kept for the cluster view (the remediation EVENTS_KEPT idiom).
EVENTS_KEPT = 128


@dataclass
class AutoscalePolicy:
    """Scaling knobs (documented in docs/SHARDING.md "Serve tier")."""

    #: Grow when windowed fetch QPS exceeds this.
    qps_high: float = 50.0
    #: Shrink when windowed fetch QPS falls below this. Must sit well
    #: under ``qps_high`` — the gap is the hysteresis band that keeps a
    #: load hovering at one threshold from flapping the fleet.
    qps_low: float = 5.0
    #: A replica this many steps behind blocks shrinking (losing a
    #: replica while the fleet lags only concentrates the feed).
    lag_high_steps: float = 10.0
    min_replicas: int = 0
    max_replicas: int = 4
    #: Minimum seconds between consecutive scaling actions.
    cooldown_s: float = 10.0
    #: Compute and record every decision; touch the pool never.
    dry_run: bool = False
    #: Deepest tier a new replica may land at. 1 = flat star (every
    #: replica a direct child of the primary — the pre-tree behavior);
    #: >1 lets a grow spawn under the hottest eligible interior node
    #: (docs/SHARDING.md "Fan-out trees").
    max_tier: int = 1
    #: Per-node child budget: a node already feeding this many children
    #: is not an eligible parent — growth spreads across the tree
    #: instead of piling onto one hot interior node.
    fanout: int = 2

    def __post_init__(self):
        if self.qps_low >= self.qps_high:
            raise ValueError(f"qps_low ({self.qps_low}) must be < "
                             f"qps_high ({self.qps_high})")
        if not 0 <= self.min_replicas <= self.max_replicas:
            raise ValueError(f"need 0 <= min ({self.min_replicas}) <= "
                             f"max ({self.max_replicas})")
        if self.max_tier < 1 or self.fanout < 1:
            raise ValueError(f"need max_tier >= 1 (got {self.max_tier}) "
                             f"and fanout >= 1 (got {self.fanout})")


class ReplicaAutoscaler:
    """QPS/lag policy head over a :class:`~..ps.supervisor.ReplicaPool`."""

    def __init__(self, pool, policy: AutoscalePolicy | None = None,
                 sharding=None, registry=None, clock=time.time,
                 fetch_total_fn=None):
        self.pool = pool
        self.policy = policy or AutoscalePolicy()
        #: Optional ShardInfo — supplies the replica-lag view.
        self.sharding = sharding
        self.clock = clock
        self._reg = registry or get_registry()
        self._fetch_total_fn = fetch_total_fn or self._fetch_total
        self._lock = threading.Lock()
        # QPS window anchor: (ts, fetch_total). guarded by: self._lock
        self._window: tuple[float, float] | None = None
        # -inf: the FIRST action is never cooldown-held (a fresh
        # autoscaler facing real load must act now, not in cooldown_s).
        self._last_action_ts = float("-inf")  # guarded by: self._lock
        self._events: deque = deque(maxlen=EVENTS_KEPT)  # guarded by: self._lock
        self.actions = {"replica_grow": 0, "replica_shrink": 0}
        self._tm_qps = self._reg.gauge("dps_autoscale_fetch_qps")
        self._tm_target = self._reg.gauge("dps_autoscale_target_replicas")

    # -- signals --------------------------------------------------------------

    def _fetch_total(self) -> float:
        """Sum of every fetch-serving counter this process hosts. Read
        from the registry SNAPSHOT (not held instrument handles): the
        serving instruments belong to the service/replica objects, and a
        label-blind prefix scan keeps this correct when new fetch-shaped
        series appear."""
        total = 0.0
        counters = self._reg.snapshot()["counters"]
        for key, value in counters.items():
            if (key.startswith("dps_rpc_handler_calls_total")
                    and "rpc=FetchParameters" in key) \
                    or key.startswith("dps_replica_fetches_total"):
                total += float(value)
        return total

    def _max_lag_steps(self) -> float:
        if self.sharding is None:
            return 0.0
        try:
            replicas = self.sharding.view().get("replicas") or []
            return max((float(r.get("lag_steps") or 0.0)
                        for r in replicas), default=0.0)
        except Exception:  # noqa: BLE001 — lag is advisory, never fatal
            return 0.0

    def _tier_rollup(self) -> dict:
        """Per-tier {replicas, max_lag_steps, fetch_qps} from the shard
        view — recorded on every decision so the event stream shows the
        tree shape the policy acted on."""
        if self.sharding is None:
            return {}
        try:
            return dict(self.sharding.view().get("tiers") or {})
        except Exception:  # noqa: BLE001 — advisory, never fatal
            return {}

    def _pick_parent(self, qps: float) -> str | None:
        """Tree-aware grow placement (docs/SHARDING.md "Fan-out trees"):
        rank every node that may still take children — the primary
        (tier 0, by its windowed QPS) and each replica at a tier below
        ``max_tier`` with fewer than ``fanout`` children (by its
        announced per-node ``fetch_qps``) — and spawn under the HOTTEST
        one; the new child drains polls from exactly where the serve
        load concentrates. Returns an address, or None for the primary
        (the flat-star behavior, and the whole story when
        ``max_tier == 1``)."""
        p = self.policy
        if p.max_tier <= 1 or self.sharding is None:
            return None
        try:
            view = self.sharding.view()
            rows = view.get("replicas") or []
        except Exception:  # noqa: BLE001 — placement is advisory
            return None
        primaries = view.get("primaries") or []
        children: dict[str, int] = {}
        for r in rows:
            parent = r.get("parent") or "<primary>"
            if parent in primaries:
                parent = "<primary>"
            children[parent] = children.get(parent, 0) + 1
        best_addr, best_qps = None, float(qps) \
            if children.get("<primary>", 0) < p.fanout else None
        for r in rows:
            addr = r.get("address")
            if not addr or int(r.get("tier") or 1) >= p.max_tier \
                    or children.get(addr, 0) >= p.fanout:
                continue
            node_qps = float(r.get("fetch_qps") or 0.0)
            if best_qps is None or node_qps > best_qps:
                best_addr, best_qps = str(addr), node_qps
        return best_addr

    # -- control --------------------------------------------------------------

    def tick(self) -> dict | None:
        """One control pass; returns the decision record when one was
        made (incl. holds for cooldown/bounds), None while the first
        window anchors or nothing changed."""
        now = self.clock()
        total = float(self._fetch_total_fn())
        with self._lock:
            anchor = self._window
            self._window = (now, total)
        if anchor is None:
            return None
        dt = now - anchor[0]
        if dt <= 0:
            return None
        qps = max(0.0, total - anchor[1]) / dt
        self._tm_qps.set(qps)
        live = int(self.pool.count())
        lag = self._max_lag_steps()
        p = self.policy
        action = None
        if live < p.min_replicas:
            action = "replica_grow"
        elif qps > p.qps_high and live < p.max_replicas:
            action = "replica_grow"
        elif qps < p.qps_low and live > p.min_replicas \
                and lag <= p.lag_high_steps:
            action = "replica_shrink"
        if action is None:
            self._tm_target.set(live)
            return None
        with self._lock:
            if now - self._last_action_ts < p.cooldown_s:
                outcome = "rate_limited"
            elif p.dry_run:
                outcome = "dry_run"
            else:
                self._last_action_ts = now
                outcome = "ok"
        parent = None
        if outcome == "ok":
            try:
                if action == "replica_grow":
                    parent = self._pick_parent(qps)
                    # Positional-free call keeps 1-arg pools (tests,
                    # legacy fakes) working when placement is flat.
                    if parent is None:
                        self.pool.grow()
                    else:
                        self.pool.grow(parent=parent)
                    live += 1
                elif self.pool.shrink() is not None:
                    live -= 1
            except Exception:  # noqa: BLE001 — a failed spawn is an
                outcome = "error"  # outcome, not a monitor-loop crash
        self._tm_target.set(live)
        note_action(action, outcome, registry=self._reg)
        if outcome == "ok":
            self.actions[action] += 1
        event = {"ts": round(now, 3), "action": action,
                 "outcome": outcome, "qps": round(qps, 1),
                 "max_lag_steps": lag, "live": live}
        if parent is not None:
            event["parent"] = parent
        tiers = self._tier_rollup()
        if tiers:
            event["tiers"] = tiers
        with self._lock:
            self._events.append(event)
        return event

    # -- read side ------------------------------------------------------------

    def view(self) -> dict:
        with self._lock:
            events = list(self._events)
        return {"live": int(self.pool.count()),
                "min": self.policy.min_replicas,
                "max": self.policy.max_replicas,
                "qps_high": self.policy.qps_high,
                "qps_low": self.policy.qps_low,
                "dry_run": self.policy.dry_run,
                "max_tier": self.policy.max_tier,
                "fanout": self.policy.fanout,
                "actions": dict(self.actions),
                "events": events[-16:]}
