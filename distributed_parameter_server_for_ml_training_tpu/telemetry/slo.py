"""Serve-tier SLOs: declarative objectives + multi-window burn rates.

PR 11's serve tier measured latency only from the loadgen client; the
server itself had no latency distribution, no objective, and no notion
of an error budget. This module closes that loop server-side:

- **Objectives** are declarative (:class:`SloObjective`): "99% of
  FetchParameters complete under 100 ms", "99.9% of pushes succeed".
  Latency objectives read the ``dps_rpc_server_latency_seconds{method}``
  histogram (comms/service.py, shared ``LATENCY_BUCKETS`` scheme);
  availability objectives read ``dps_rpc_server_errors_total{method}``
  against the same histogram's count.
- **Evaluation** is the multi-window burn-rate recipe (SRE workbook):
  each tick snapshots cumulative (total, bad) per objective; windowed
  DELTAS over a fast and a slow window give the burn rate = observed
  bad fraction / budgeted bad fraction. Fast window hot (burn >= ~14.4)
  means the monthly budget dies in hours -> ``slo_burn_fast``
  (critical); slow window warm (burn >= ~6) means sustained bleed ->
  ``slo_burn_slow`` (warning). Both rules live in the health
  RULE_CATALOG (telemetry/health.py) and ride the existing
  alert -> remediation path; ``GET /cluster`` gains an ``"slo"`` block
  (:meth:`SloEvaluator.view`) and ``cli status`` renders it.

Latency "good" counting is bucket-exact and conservative: the threshold
snaps DOWN to the nearest histogram edge (never up), so a threshold
between edges under-counts good events rather than hiding bad ones.
The snapped value is reported in the view — honesty over flattery.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from collections import deque
from dataclasses import dataclass, field

from .registry import LATENCY_BUCKETS, MetricsRegistry, get_registry
from .stats import histogram_quantile

__all__ = [
    "SLO_RULE_FAST",
    "SLO_RULE_SLOW",
    "SloObjective",
    "SloEvaluator",
    "default_objectives",
]

#: Health-rule names this evaluator feeds (must match RULE_CATALOG keys
#: in telemetry/health.py; tests/test_docs_drift.py pins the catalog).
SLO_RULE_FAST = "slo_burn_fast"
SLO_RULE_SLOW = "slo_burn_slow"


@dataclass(frozen=True)
class SloObjective:
    """One objective over one RPC method.

    ``target`` is the good fraction (0.99 = 99% of events good).
    ``threshold_s`` set -> latency objective (good = completed within
    the threshold); None -> availability objective (good = no error).
    """

    name: str
    method: str
    target: float
    threshold_s: float | None = None

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1), "
                f"got {self.target}")
        if self.threshold_s is not None and self.threshold_s <= 0:
            raise ValueError(
                f"objective {self.name!r}: threshold_s must be > 0, "
                f"got {self.threshold_s}")

    @property
    def budget(self) -> float:
        """Budgeted bad fraction (1 - target)."""
        return 1.0 - self.target


def default_objectives(fetch_p99_ms: float = 100.0,
                       availability: float = 0.99) -> list:
    """The serve-tier defaults ``cli serve`` installs: fetch latency at
    the given p99 threshold, plus fetch/push availability."""
    return [
        SloObjective("fetch_latency", "FetchParameters", 0.99,
                     threshold_s=fetch_p99_ms / 1e3),
        SloObjective("fetch_availability", "FetchParameters", availability),
        SloObjective("push_availability", "PushGradrients", availability),
    ]


@dataclass
class _Window:
    """One burn-rate window: span + the burn threshold that breaches it."""

    window_s: float
    burn_threshold: float
    rule: str = SLO_RULE_FAST
    severity: str = "critical"
    min_events: int = field(default=1)


class SloEvaluator:
    """Window-delta burn-rate evaluator over the server RPC metrics.

    ``evaluate(now)`` is driven by the cluster monitor's tick (no thread
    of its own); ``view()`` may be read concurrently from the HTTP
    surface, so the sample history has its own lock.
    """

    def __init__(self, objectives: list | None = None,
                 registry: MetricsRegistry | None = None,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0,
                 fast_burn_threshold: float = 14.4,
                 slow_burn_threshold: float = 6.0,
                 min_events: int = 1):
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.registry = registry if registry is not None else get_registry()
        if slow_window_s < fast_window_s:
            raise ValueError(
                f"slow window ({slow_window_s}s) must be >= fast window "
                f"({fast_window_s}s)")
        self.windows = (
            _Window(fast_window_s, fast_burn_threshold, SLO_RULE_FAST,
                    "critical", min_events),
            _Window(slow_window_s, slow_burn_threshold, SLO_RULE_SLOW,
                    "warning", min_events),
        )
        self._lock = threading.Lock()
        # (ts, {objective_name: (total, bad)}) — guarded by: self._lock
        self._samples: deque = deque()
        self._last_breaches: list = []  # guarded by: self._lock

    # -- reading the instruments --------------------------------------------

    def _instruments(self, method: str):
        hist = self.registry.histogram("dps_rpc_server_latency_seconds",
                                       buckets=LATENCY_BUCKETS,
                                       method=method)
        errors = self.registry.counter("dps_rpc_server_errors_total",
                                       method=method)
        return hist, errors

    @staticmethod
    def _good_upto(snap: dict, threshold_s: float) -> tuple[int, float]:
        """(good count, snapped threshold): cumulative count through the
        last bucket whose edge <= threshold. Snapping DOWN keeps the
        estimate conservative when the threshold is between edges."""
        edges = snap["le"]
        k = bisect_right(edges, threshold_s)  # buckets [0, k) are good
        if k == 0:
            return 0, 0.0  # threshold below the first edge: nothing provably good
        return sum(snap["counts"][:k]), float(edges[k - 1])

    def _totals(self, obj: SloObjective) -> tuple[int, int]:
        """Cumulative (total, bad) for one objective, right now."""
        hist, errors = self._instruments(obj.method)
        snap = hist.snapshot()
        total = int(snap["count"])
        err = int(errors.value)
        if obj.threshold_s is None:
            return total, min(total, err)
        good, _ = self._good_upto(snap, obj.threshold_s)
        # Errored calls still observe a duration (service.py records in
        # the finally), so a fast abort can land in a "good" latency
        # bucket; adding the error count back may double-count a SLOW
        # error — conservative by design, never flattering.
        return total, min(total, (total - good) + err)

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, now: float) -> list:
        """Record one sample and return current breaches (list of dicts
        ``{rule, severity, objective, window_s, burn, burn_threshold,
        bad, total}``), newest evaluation wins."""
        sample = {o.name: self._totals(o) for o in self.objectives}
        breaches = []
        with self._lock:
            self._samples.append((float(now), sample))
            horizon = now - self.windows[-1].window_s * 1.5
            while len(self._samples) > 1 and self._samples[0][0] < horizon:
                self._samples.popleft()
            samples = list(self._samples)
        for win in self.windows:
            for obj in self.objectives:
                d = self._window_delta(samples, obj.name, now, win.window_s)
                if d is None or d["total"] < win.min_events:
                    continue
                burn = self._burn(obj, d["bad"], d["total"])
                if burn >= win.burn_threshold:
                    breaches.append({
                        "rule": win.rule, "severity": win.severity,
                        "objective": obj.name, "window_s": win.window_s,
                        "burn": round(burn, 2),
                        "burn_threshold": win.burn_threshold,
                        "bad": d["bad"], "total": d["total"],
                    })
        with self._lock:
            self._last_breaches = list(breaches)
        return breaches

    @staticmethod
    def _burn(obj: SloObjective, bad: int, total: int) -> float:
        if total <= 0:
            return 0.0
        return (bad / total) / obj.budget

    @staticmethod
    def _window_delta(samples: list, name: str, now: float,
                      window_s: float) -> dict | None:
        """Delta between the newest sample and the newest sample at or
        before the window start. One sample (no baseline) -> the full
        cumulative value counts as the delta: a freshly started server
        must not get a breach-free grace period just for being new."""
        if not samples:
            return None
        start = now - window_s
        base = None
        for ts, vals in samples:
            if ts <= start:
                base = vals
            else:
                break
        _, newest = samples[-1]
        nt, nb = newest.get(name, (0, 0))
        if base is None:
            bt = bb = 0
        else:
            bt, bb = base.get(name, (0, 0))
        return {"total": max(0, nt - bt), "bad": max(0, nb - bb)}

    # -- read surface ---------------------------------------------------------

    def view(self) -> dict:
        """The ``GET /cluster`` ``"slo"`` block: per-objective lifetime
        quantiles + per-window burn, plus the active breaches from the
        latest :meth:`evaluate` tick."""
        with self._lock:
            samples = list(self._samples)
            breaches = list(self._last_breaches)
        now = samples[-1][0] if samples else 0.0
        out_objs = []
        for obj in self.objectives:
            hist, _ = self._instruments(obj.method)
            snap = hist.snapshot()
            entry = {
                "name": obj.name, "method": obj.method,
                "target": obj.target,
                "kind": ("latency" if obj.threshold_s is not None
                         else "availability"),
                "total": int(snap["count"]),
            }
            if obj.threshold_s is not None:
                _, snapped = self._good_upto(snap, obj.threshold_s)
                entry["threshold_ms"] = round(obj.threshold_s * 1e3, 3)
                entry["snapped_threshold_ms"] = round(snapped * 1e3, 3)
            for pct, key in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
                q = histogram_quantile(snap["le"], snap["counts"], pct)
                entry[key] = None if q is None else round(q * 1e3, 3)
            windows = {}
            for win in self.windows:
                d = self._window_delta(samples, obj.name, now, win.window_s)
                if d is None:
                    d = {"total": 0, "bad": 0}
                burn = self._burn(obj, d["bad"], d["total"])
                windows[win.rule] = {
                    "window_s": win.window_s, "total": d["total"],
                    "bad": d["bad"], "burn": round(burn, 2),
                    "burn_threshold": win.burn_threshold,
                    "breaching": any(b["rule"] == win.rule
                                     and b["objective"] == obj.name
                                     for b in breaches),
                }
            entry["windows"] = windows
            out_objs.append(entry)
        return {"objectives": out_objs, "breaches": breaches}
