"""Latency-summary math shared by the load generator, `cli infer`, and
the server-side SLO evaluator.

Kept separate from the load generator so the math has fast unit tests:
the slow-marker audit (scripts/lint.sh) slow-marks any test file that
touches the generator itself, and percentile arithmetic should not need
a gRPC fleet to verify. :func:`histogram_quantile` is the bucketed
counterpart used server-side (telemetry/slo.py) where only histogram
snapshots exist, not raw samples — one implementation, both surfaces.
"""

from __future__ import annotations

__all__ = ["histogram_quantile", "latency_summary", "merge_histograms",
           "percentile"]


def percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = round(p / 100.0 * (len(sorted_vals) - 1))
    return sorted_vals[min(len(sorted_vals) - 1, max(0, k))]


def histogram_quantile(edges: list[float], counts: list[int],
                       p: float) -> float | None:
    """Quantile estimate from a fixed-bucket histogram snapshot.

    ``edges`` are the inclusive upper bounds; ``counts`` are the
    NON-cumulative per-bucket counts, optionally with one extra trailing
    overflow slot (the registry's ``snapshot()`` shape). Returns the
    upper edge of the bucket containing the p-th observation — a
    conservative (never-understated) estimate, which is the right bias
    for SLO checks. None when the histogram is empty or the quantile
    lands in the overflow bucket (no finite upper bound to report).
    """
    total = sum(counts)
    if total <= 0:
        return None
    rank = p / 100.0 * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank and c > 0:
            if i >= len(edges):
                return None  # overflow bucket: unbounded above
            return float(edges[i])
    return None


def merge_histograms(snaps: list[dict]) -> dict:
    """Merge registry histogram snapshots into one EXACT union histogram.

    Exactness is the whole point (and what the fleet rollups advertise):
    because bucket schemes are pinned in ``registry.py``, every process
    in the fleet records the same series into identical edges, so the
    merged per-bucket counts equal the counts a single histogram would
    have accumulated over the union of all observations — fleet
    p50/p95/p99 from :func:`histogram_quantile` over the merge are the
    true union quantiles, not an estimate-of-estimates. Mismatched
    ``le`` schemes raise (merging them could only be approximate, which
    would silently break that contract).

    The operation is associative and commutative with ``{le, counts:
    zeros, sum: 0, count: 0}`` as identity — property-tested in
    tests/test_fleet.py. Per-bucket ``exemplars`` (when present) merge
    by newest timestamp: the surviving exemplar per bucket is the most
    recently sampled one across the fleet.
    """
    if not snaps:
        raise ValueError("merge_histograms needs at least one snapshot")
    le = list(snaps[0]["le"])
    n_counts = len(snaps[0]["counts"])
    merged_counts = [0] * n_counts
    merged_sum = 0.0
    merged_count = 0
    merged_ex: dict[str, dict] = {}
    for snap in snaps:
        if list(snap["le"]) != le or len(snap["counts"]) != n_counts:
            raise ValueError(
                f"cannot merge histograms with different bucket schemes: "
                f"{le!r} vs {snap['le']!r}")
        for i, c in enumerate(snap["counts"]):
            merged_counts[i] += c
        merged_sum += snap["sum"]
        merged_count += snap["count"]
        for idx, ex in (snap.get("exemplars") or {}).items():
            cur = merged_ex.get(idx)
            if cur is None or ex.get("ts", 0) >= cur.get("ts", 0):
                merged_ex[idx] = dict(ex)
    out = {"le": le, "counts": merged_counts, "sum": merged_sum,
           "count": merged_count}
    if merged_ex:
        out["exemplars"] = merged_ex
    return out


def latency_summary(lat_s: list[float]) -> dict:
    """p50/p95/p99 in milliseconds (the LOADGEN_JSON convention)."""
    s = sorted(lat_s)
    return {"p50": round(percentile(s, 50) * 1e3, 3),
            "p95": round(percentile(s, 95) * 1e3, 3),
            "p99": round(percentile(s, 99) * 1e3, 3),
            "samples": len(s)}
