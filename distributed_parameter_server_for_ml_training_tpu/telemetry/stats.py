"""Latency-summary math shared by the load generator and `cli infer`.

Kept separate from the load generator so the math has fast unit tests:
the slow-marker audit (scripts/lint.sh) slow-marks any test file that
touches the generator itself, and percentile arithmetic should not need
a gRPC fleet to verify.
"""

from __future__ import annotations

__all__ = ["latency_summary", "percentile"]


def percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = round(p / 100.0 * (len(sorted_vals) - 1))
    return sorted_vals[min(len(sorted_vals) - 1, max(0, k))]


def latency_summary(lat_s: list[float]) -> dict:
    """p50/p95/p99 in milliseconds (the LOADGEN_JSON convention)."""
    s = sorted(lat_s)
    return {"p50": round(percentile(s, 50) * 1e3, 3),
            "p95": round(percentile(s, 95) * 1e3, 3),
            "p99": round(percentile(s, 99) * 1e3, 3),
            "samples": len(s)}
