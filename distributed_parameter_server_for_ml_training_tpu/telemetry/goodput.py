"""Goodput accounting: classify every second of worker/trainer wall.

The perf observatory (PR 14) can attribute one *captured step window*;
nothing accounted for where whole training **hours** go — a run that
spends 40% of its wall re-fetching parameters through a slow wire looks
identical to a healthy one in every committed number except the final
throughput. This module is the wall-clock ledger: the training loop
brackets its phases with :meth:`GoodputAccount.span` and every second
lands in exactly one :data:`GOODPUT_CATEGORIES` bucket, cumulative on
``dps_goodput_seconds_total{category=...}`` counters beside a
``dps_goodput_wall_seconds_total`` anchor.

Design constraints:

- **Exclusive categories.** Spans nest (a reconnect inside a boundary
  fetch, a codec encode inside a push wait); a parent is charged only
  its *exclusive* time (duration minus enclosed child spans, tracked on
  a per-thread stack), so the category totals are disjoint and sum to
  at most the wall.
- **Residual reported, never hidden.** ``wall - sum(categories)`` is
  the ``other`` row of every report — the same discipline as
  ``critical_path_report``'s unattributed remainder. A large residual
  means an uninstrumented phase, and the report says so.
- **Always on, beneath measurement.** Unlike trace spans (off by
  default), goodput accounting runs on every instrumented loop: one
  ``perf_counter`` pair plus one lock'd float add per span — inside the
  <2% overhead guard (tests/test_goodput.py).
- **Mergeable.** Counters are cumulative and unlabelled-by-worker, so
  the fleet collector's counter rollups and the journal's snapshot
  stream merge them with zero new plumbing: a fleet fraction is
  "productive worker-seconds over total worker-seconds", and
  ``cli query --goodput`` re-derives any window retroactively by
  counter subtraction.

Category names are a wire/doc contract: the table below is pinned both
directions to docs/OBSERVABILITY.md ("Goodput categories") by dpslint's
``catalog_drift.check_goodput_categories``.
"""

from __future__ import annotations

import threading
import time

from .registry import MetricsRegistry, get_registry

__all__ = [
    "GOODPUT_CATEGORIES",
    "GOODPUT_METRIC",
    "GOODPUT_WALL_METRIC",
    "PRODUCTIVE_CATEGORIES",
    "GoodputAccount",
    "delta_counters",
    "goodput_report",
    "parse_goodput_counters",
    "report_from_counters",
]

#: category -> one-line meaning. The contract table — pinned BOTH
#: directions against docs/OBSERVABILITY.md by dpslint
#: ``catalog_drift.check_goodput_categories``; must stay a pure literal
#: (the drift engine ``ast.literal_eval``'s it).
GOODPUT_CATEGORIES = {
    "compute": "device step work (train + eval): the productive bucket",
    "fetch_wait": "blocked on a boundary parameter fetch (RPC + decode "
                  "wait, net of nested recovery/codec time)",
    "push_wait": "blocked on a gradient push (serial RPC or pipeline "
                 "backpressure, net of nested codec time)",
    "codec": "wire codec work: push quantize/pack/encode + fetch "
             "decompress",
    "checkpoint": "blocked on a checkpoint save in the training loop",
    "reconnect_recovery": "session-resume state machine after a lost "
                          "server (register + refetch + reconcile, "
                          "including backoff sleeps)",
    "quarantine_idle": "step work thrown away while the server had this "
                       "worker's pushes quarantined",
    "startup": "process start to the training loop: registration, "
               "dataset/model/template init",
    "other": "residual: wall seconds no instrumented phase claimed "
             "(reported, never hidden)",
}

#: Categories that count as PRODUCTIVE in the goodput fraction.
PRODUCTIVE_CATEGORIES = ("compute",)

GOODPUT_METRIC = "dps_goodput_seconds_total"
GOODPUT_WALL_METRIC = "dps_goodput_wall_seconds_total"


class _GoodputSpan:
    """One phase bracket. Charges its category the *exclusive* duration
    (total minus enclosed child spans) so nested brackets never double
    count a second. Reentrant-safe via the account's per-thread stack."""

    __slots__ = ("_acct", "category", "_t0", "_child_s")

    def __init__(self, acct: "GoodputAccount", category: str):
        self._acct = acct
        self.category = category
        self._t0 = 0.0
        self._child_s = 0.0

    def __enter__(self):
        self._child_s = 0.0
        self._acct._stack().append(self)
        self._t0 = self._acct._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = self._acct._clock() - self._t0
        stack = self._acct._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if stack:
            stack[-1]._child_s += dt
        self._acct.add(self.category, max(0.0, dt - self._child_s))
        return False


class GoodputAccount:
    """The wall-clock ledger for ONE logical worker/trainer.

    Keeps its own per-instance totals (so a multi-worker process reports
    an honest per-worker fraction) while mirroring every addition onto
    the process-global cumulative counters (which therefore sum
    worker-seconds across however many accounts share the registry —
    exactly the semantics the fleet rollup wants).
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 clock=time.perf_counter):
        reg = registry or get_registry()
        self._clock = clock
        # Literal names at the registration sites (== GOODPUT_METRIC /
        # GOODPUT_WALL_METRIC): the metric<->doc drift pin extracts
        # registrations textually, and these two must stay pinned.
        self._counters = {
            c: reg.counter("dps_goodput_seconds_total", category=c)
            for c in GOODPUT_CATEGORIES}
        self._wall = reg.counter("dps_goodput_wall_seconds_total")
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._local = {c: 0.0 for c in GOODPUT_CATEGORIES}  # by: _lock
        self._local_wall = 0.0   # guarded by: self._lock
        self._wall_mark = None   # guarded by: self._lock

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- recording -----------------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        """Charge ``seconds`` of wall to one catalog category."""
        if category not in GOODPUT_CATEGORIES:
            raise ValueError(f"unknown goodput category {category!r} "
                             f"(catalog: {sorted(GOODPUT_CATEGORIES)})")
        if seconds < 0:
            return
        with self._lock:
            self._local[category] += seconds
        self._counters[category].inc(seconds)

    def span(self, category: str) -> _GoodputSpan:
        """Phase bracket: ``with acct.span("fetch_wait"): ...``."""
        if category not in GOODPUT_CATEGORIES:
            raise ValueError(f"unknown goodput category {category!r}")
        return _GoodputSpan(self, category)

    def start_wall(self, mark: float | None = None) -> None:
        """Anchor the wall clock (loop entry; ``mark`` backdates it to
        an earlier ``clock()`` reading so startup time is inside)."""
        with self._lock:
            self._wall_mark = self._clock() if mark is None else mark

    def tick_wall(self) -> None:
        """Advance the wall counter to now (call once per step/epoch —
        wall accrues regardless of which categories claimed it)."""
        now = self._clock()
        with self._lock:
            if self._wall_mark is None:
                self._wall_mark = now
                return
            dt = now - self._wall_mark
            self._wall_mark = now
            if dt <= 0:
                return
            self._local_wall += dt
        self._wall.inc(dt)

    # -- reading -------------------------------------------------------------

    def totals(self) -> dict:
        """This account's own ledger: ``{"categories": {...},
        "wall_s": float}`` (instance-local, not the shared counters)."""
        with self._lock:
            return {"categories": dict(self._local),
                    "wall_s": self._local_wall}

    def fraction(self) -> float | None:
        """Productive fraction of this account's wall so far, or None
        before any wall has accrued."""
        with self._lock:
            if self._local_wall <= 0:
                return None
            good = sum(self._local[c] for c in PRODUCTIVE_CATEGORIES)
            return min(1.0, good / self._local_wall)


# -- report math (pure; shared by cli goodput, cli query, the demo) ----------

def parse_goodput_counters(counters: dict) -> dict:
    """Extract the goodput ledger from a snapshot ``counters`` mapping
    (``name{category=x}`` -> value, the shape /metrics.json, journal
    snapshots, and fleet rollups all carry). Unknown categories are kept
    — a newer producer's category shows up rather than vanishing."""
    cats: dict[str, float] = {}
    wall = 0.0
    prefix = GOODPUT_METRIC + "{category="
    for key, value in (counters or {}).items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        if key.startswith(prefix) and key.endswith("}"):
            cat = key[len(prefix):-1]
            cats[cat] = cats.get(cat, 0.0) + float(value)
        elif key == GOODPUT_WALL_METRIC \
                or key.startswith(GOODPUT_WALL_METRIC + "{"):
            wall += float(value)
    return {"categories": cats, "wall_s": wall}


def delta_counters(newest: dict, base: dict) -> dict:
    """Per-key counter subtraction (window math for retro queries).
    Negative deltas clamp to 0 — a counter that went backward is a
    process restart, not negative time."""
    out = {}
    for key, v in (newest or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        b = (base or {}).get(key, 0.0)
        b = b if isinstance(b, (int, float)) \
            and not isinstance(b, bool) else 0.0
        out[key] = max(0.0, float(v) - float(b))
    return out


def goodput_report(categories: dict, wall_s: float,
                   tolerance: float = 0.02) -> dict:
    """The reconciliation report over one ledger (cumulative or a
    window delta). The residual (wall minus every recorded category) is
    folded into ``other`` AND reported separately — never hidden; when
    the recorded categories OVERSHOOT the wall by more than
    ``tolerance`` (fraction of wall), ``reconciled`` is False and the
    overshoot is reported too (clock skew or a missing wall tick)."""
    cats = {c: float(categories.get(c, 0.0))
            for c in GOODPUT_CATEGORIES}
    for c, v in (categories or {}).items():  # keep unknown categories
        if c not in cats and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            cats[c] = float(v)
    recorded = sum(v for c, v in cats.items() if c != "other")
    wall = max(0.0, float(wall_s))
    residual = wall - recorded
    overshoot = max(0.0, -residual)
    cats["other"] += max(0.0, residual)
    total = max(wall, recorded)
    good = sum(cats.get(c, 0.0) for c in PRODUCTIVE_CATEGORIES)
    rows = {
        c: {"seconds": round(v, 3),
            "fraction": round(v / total, 4) if total > 0 else 0.0}
        for c, v in sorted(cats.items(), key=lambda kv: -kv[1])
    }
    return {
        "wall_s": round(wall, 3),
        "categories": rows,
        "goodput_fraction": round(good / total, 4) if total > 0 else None,
        "badput_s": round(max(0.0, total - good), 3),
        "residual_s": round(max(0.0, residual), 3),
        "residual_fraction": round(max(0.0, residual) / total, 4)
        if total > 0 else 0.0,
        "overshoot_s": round(overshoot, 3),
        "reconciled": bool(wall > 0
                           and overshoot <= tolerance * max(wall, 1e-9)),
    }


def report_from_counters(counters: dict, tolerance: float = 0.02) -> dict:
    """Convenience: parse + report in one call (live /metrics.json,
    fleet rollup sums, or a window delta from :func:`delta_counters`)."""
    parsed = parse_goodput_counters(counters)
    return goodput_report(parsed["categories"], parsed["wall_s"],
                          tolerance=tolerance)
