"""Training-quality rule engine: worker health reports -> structured alerts.

The reference (and PRs 1-3 here) could tell you a process was *slow*; nothing
anywhere watched whether training was *working* — a NaN loss, a diverging
run, or a silently stalled worker was only discovered by reading plots after
the job burned its budget. This module is the decision half of the cluster
health subsystem (docs/OBSERVABILITY.md): :class:`~.cluster.ClusterMonitor`
aggregates per-worker health reports with the store's membership state into a
:class:`ClusterState`, and :class:`HealthRuleEngine` evaluates the fixed rule
catalog below against it, emitting **deduplicated, rate-limited** alert
events.

Design constraints:

- **Fixed rule catalog.** Rule names are a wire/doc contract exactly like
  metric and span names: :data:`RULE_CATALOG` is the single source of truth,
  pinned to docs/OBSERVABILITY.md both directions by
  ``tests/test_docs_drift.py``. Thresholds are configurable
  (:class:`HealthThresholds`); the *names and severities* are not.
- **Alerts are stateful, not log lines.** A condition FIRES once when it
  starts holding, stays in the active set while it holds (re-emitting at
  most every ``realert_interval_s``), and RESOLVES once when it stops.
  Consumers (``/cluster``, ``cli status``, the flight recorder, the
  ``"kind": "cluster"`` stream) therefore see edge events plus a live
  active set, never a firehose of one alert per evaluation tick.
- **Never trust a report.** Reports cross the wire from arbitrary peers;
  every field access degrades (missing/garbled -> ignored), and evaluation
  never raises — a malformed report must not take down the server's
  monitoring, let alone the server.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field

__all__ = [
    "RULE_CATALOG",
    "SEVERITIES",
    "Alert",
    "ClusterState",
    "HealthRuleEngine",
    "HealthThresholds",
    "WorkerState",
]

#: Alert severities, most severe first. ``critical`` drives the ``/healthz``
#: readiness flip (503) and the nonzero ``cli status`` exit code.
SEVERITIES = ("critical", "warning", "info")

#: rule name -> (severity, one-line meaning). The contract table —
#: docs/OBSERVABILITY.md documents exactly these rows and
#: ``tests/test_docs_drift.py`` pins the two to each other both directions.
RULE_CATALOG = {
    "nonfinite_loss": (
        "critical", "a worker reported a NaN/Inf training loss"),
    "nonfinite_grad": (
        "critical", "a worker reported a NaN/Inf gradient global-norm"),
    "dead_worker": (
        "critical", "a worker stopped reporting/pinging (membership expiry "
                    "or report age past dead_after_s) without JobFinished"),
    "grad_explosion": (
        "warning", "gradient global-norm above grad_explosion_factor x the "
                   "worker's rolling median (or the absolute ceiling)"),
    "loss_divergence": (
        "warning", "loss above loss_divergence_factor x the worker's best "
                   "loss after a warmup of reports"),
    "worker_stall": (
        "warning", "a worker's step stopped advancing for stall_after_s "
                   "while the cluster's global step kept moving"),
    "staleness_spike": (
        "warning", "rejected-push fraction over the evaluation window above "
                   "staleness_reject_ratio (async staleness gate thrashing)"),
    "wire_corrupt": (
        "warning", "a push payload failed the wire CRC check this window "
                   "and was refused (dps_wire_corrupt_total)"),
    "memory_growth": (
        "warning", "host RSS grew faster than memory_growth_bytes_per_s "
                   "over the sampling window (telemetry/memory.py leak "
                   "slope; an OOM in the making)"),
    "loss_plateau": (
        "info", "best loss improved less than plateau_min_improvement over "
                "plateau_window_s of reports"),
    "straggler_lag": (
        "info", "a worker's reported step more than straggler_lag_steps "
                "behind the fastest reporting worker"),
    "slo_burn_fast": (
        "critical", "an SLO objective's fast-window error-budget burn rate "
                    "crossed its threshold (telemetry/slo.py; budget gone "
                    "in hours at this rate)"),
    "slo_burn_slow": (
        "warning", "an SLO objective's slow-window error-budget burn rate "
                   "crossed its threshold (sustained budget bleed)"),
}


@dataclass
class HealthThresholds:
    """Default detector thresholds (documented in docs/OBSERVABILITY.md).

    Chosen for the CIFAR-scale runs this repo records: conservative enough
    that a healthy control run fires nothing (pinned by the recorded demo),
    tight enough that the seeded faults fire within one heartbeat interval.
    """

    grad_explosion_factor: float = 10.0
    #: Absolute grad-norm backstop: fires grad_explosion even before a
    #: rolling median exists.
    grad_norm_ceiling: float = 1e6
    #: Reports needed before the rolling-median explosion check engages.
    grad_median_warmup: int = 5
    loss_divergence_factor: float = 3.0
    loss_divergence_warmup: int = 5
    plateau_window_s: float = 300.0
    plateau_min_improvement: float = 1e-3
    stall_after_s: float = 30.0
    straggler_lag_steps: int = 100
    staleness_reject_ratio: float = 0.5
    #: Minimum pushes in the window before the spike ratio is meaningful.
    staleness_min_pushes: int = 8
    #: A worker whose newest report/liveness is older than this while the
    #: cluster is otherwise alive is declared dead (membership expiry
    #: reported by the store fires the same rule immediately).
    dead_after_s: float = 30.0
    #: Sustained host-RSS growth slope above this fires memory_growth
    #: (8 MiB/s leaks a v4 host's 400-ish GB in under a day — early
    #: enough to act, far above healthy allocator jitter).
    memory_growth_bytes_per_s: float = 8388608.0
    #: The slope is meaningless over a blip: the sampling window must
    #: span at least this long and hold this many samples first.
    memory_growth_min_window_s: float = 20.0
    memory_growth_min_samples: int = 5
    #: Re-emit cooldown per (rule, worker): an alert that KEEPS firing
    #: produces at most one event per interval (dedupe/rate-limit).
    realert_interval_s: float = 60.0
    #: Hard cap on fresh fire events per evaluation pass.
    max_alerts_per_eval: int = 16


@dataclass
class WorkerState:
    """One worker's slice of a :class:`ClusterState`."""

    worker_id: int
    report: dict | None = None
    #: When the newest report arrived (monitor clock).
    received_ts: float = 0.0
    #: Store-side liveness (``last_seen`` from fetch/push/ping), 0 if unknown.
    last_seen: float = 0.0
    in_membership: bool = True


@dataclass
class ClusterState:
    """Everything one evaluation pass sees. Built by ClusterMonitor."""

    ts: float
    global_step: int = 0
    mode: str = "sync"
    workers: dict[int, WorkerState] = field(default_factory=dict)
    #: Worker ids the membership layer expired since the last pass.
    expired: list[int] = field(default_factory=list)
    #: Push outcome deltas since the last pass (async staleness gate).
    pushes_accepted_delta: int = 0
    pushes_rejected_delta: int = 0
    #: Corrupt push frames REFUSED over the evaluation window (wire CRC
    #: trailer, comms/service.py) — any nonzero value alerts.
    corrupt_frames_delta: int = 0
    #: SLO burn-rate breaches from the attached SloEvaluator this pass
    #: (telemetry/slo.py ``evaluate()`` dicts); empty when no evaluator.
    slo_breaches: list = field(default_factory=list)
    #: Memory verdict from the attached MemoryMonitor
    #: (telemetry/memory.py ``observe()`` dict); None when no monitor.
    memory: dict | None = None


@dataclass
class Alert:
    """A firing condition: identity (rule, worker), evidence, lifecycle."""

    rule: str
    severity: str
    worker: int | None
    message: str
    value: float | None = None
    threshold: float | None = None
    first_ts: float = 0.0
    last_ts: float = 0.0
    #: Evaluation passes this alert has been continuously firing.
    count: int = 1

    def key(self) -> tuple:
        return (self.rule, self.worker)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "worker": self.worker, "message": self.message,
            "value": self.value, "threshold": self.threshold,
            "since": round(self.first_ts, 3),
            "last_ts": round(self.last_ts, 3), "count": self.count,
        }


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


class _WorkerTrack:
    """Per-worker rolling history the detectors read (engine-private)."""

    __slots__ = ("grad_norms", "best_loss", "best_loss_ts", "first_report_ts",
                 "reports", "last_report_ts", "last_step",
                 "last_step_change_ts", "step_at_last_change")

    def __init__(self):
        self.grad_norms: deque = deque(maxlen=32)
        self.best_loss: float | None = None
        self.best_loss_ts: float = 0.0
        self.first_report_ts: float = 0.0
        self.reports = 0
        #: received_ts of the newest report folded into the history above.
        #: Evaluation frequency is set by /healthz + /cluster scrape rates,
        #: not report arrival (the same report is re-seen many times), so
        #: warmup counts and the grad-norm median window only advance on a
        #: report NEWER than this — a 2 s readiness probe must not rush a
        #: 5-report warmup in 10 s or flood the median with duplicates.
        self.last_report_ts: float = 0.0
        self.last_step: int | None = None
        self.last_step_change_ts: float = 0.0
        #: Cluster global step when this worker's step last advanced — the
        #: stall rule only fires if the CLUSTER moved since (a fully idle
        #: cluster, e.g. between epochs, is not N stalled workers).
        self.step_at_last_change: int = 0


class HealthRuleEngine:
    """Evaluates :data:`RULE_CATALOG` against successive cluster states.

    Stateful: keeps per-worker rolling history (for median/best-loss/stall
    tracking) and the active-alert set (for dedupe + resolution). One engine
    per monitor; ``evaluate`` is called under the monitor's lock, so no
    internal locking here.
    """

    def __init__(self, thresholds: HealthThresholds | None = None):
        self.thresholds = thresholds or HealthThresholds()
        self._tracks: dict[int, _WorkerTrack] = {}
        self._active: dict[tuple, Alert] = {}
        self._last_emit: dict[tuple, float] = {}
        #: Workers currently considered dead -> when the latch was set.
        #: The expiry notice arrives once, but the alert must stay active
        #: until evidence NEWER than the latch shows the worker back (a
        #: fresh report, or a re-registration bumping last_seen) — a
        #: report from before the expiry must not resolve it.
        self._dead: dict[int, float] = {}

    # -- public surface ------------------------------------------------------

    def active_alerts(self) -> list[Alert]:
        order = {s: i for i, s in enumerate(SEVERITIES)}
        return sorted(self._active.values(),
                      key=lambda a: (order.get(a.severity, 9), a.rule,
                                     -1 if a.worker is None else a.worker))

    def evaluate(self, state: ClusterState) -> list[dict]:
        """One pass: returns the EDGE events (fired/resolved) this state
        produced; read the ongoing set from :meth:`active_alerts`."""
        firing = self._detect(state)
        now = state.ts
        events: list[dict] = []
        fired_budget = self.thresholds.max_alerts_per_eval
        for key, alert in firing.items():
            prev = self._active.get(key)
            if prev is None:
                if fired_budget <= 0:
                    # Burst cap: defer admission entirely — the condition
                    # still holds next pass and fires then (with its
                    # "fired" edge), rather than slipping into the active
                    # set eventless and surfacing as a refire-without-fire.
                    continue
                fired_budget -= 1
                alert.first_ts = now
                alert.last_ts = now
                self._active[key] = alert
                self._last_emit[key] = now
                events.append({"state": "fired", **alert.to_dict()})
            else:
                prev.last_ts = now
                prev.count += 1
                prev.message = alert.message
                prev.value = alert.value
                # Re-emit at most once per cooldown — a condition that
                # holds for an hour is one alert, not 720.
                if now - self._last_emit.get(key, 0.0) \
                        >= self.thresholds.realert_interval_s:
                    self._last_emit[key] = now
                    events.append({"state": "refired", **prev.to_dict()})
        for key in [k for k in self._active if k not in firing]:
            resolved = self._active.pop(key)
            self._last_emit.pop(key, None)
            resolved.last_ts = now
            events.append({"state": "resolved", **resolved.to_dict()})
        return events

    # -- detectors -----------------------------------------------------------

    def _detect(self, state: ClusterState) -> dict[tuple, Alert]:
        t = self.thresholds
        firing: dict[tuple, Alert] = {}

        def fire(rule: str, worker: int | None, message: str,
                 value=None, threshold=None) -> None:
            sev = RULE_CATALOG[rule][0]
            a = Alert(rule=rule, severity=sev, worker=worker,
                      message=message, value=value, threshold=threshold)
            firing.setdefault(a.key(), a)

        now = state.ts
        # Liveness bookkeeping first: expiry notices latch workers dead.
        for wid in state.expired:
            self._dead.setdefault(wid, now)
        reporting_steps: list[tuple[int, int]] = []

        for wid, ws in sorted(state.workers.items()):
            r = ws.report if isinstance(ws.report, dict) else None
            alive_ts = max(ws.received_ts, ws.last_seen)
            latch = self._dead.get(wid)
            if latch is not None and alive_ts > latch:
                del self._dead[wid]  # seen AFTER the latch: dead resolves
                latch = None
            if latch is not None:
                fire("dead_worker", wid,
                     f"worker {wid} expired from membership "
                     f"(no liveness for {now - alive_ts:.0f}s)",
                     value=round(now - alive_ts, 1),
                     threshold=t.dead_after_s)
                continue
            if alive_ts and now - alive_ts > t.dead_after_s \
                    and ws.in_membership:
                # Faithful-mode store never expires (SURVEY quirk 10): the
                # monitor still notices a silent worker by report age.
                fire("dead_worker", wid,
                     f"worker {wid} silent for {now - alive_ts:.0f}s "
                     f"(> {t.dead_after_s:.0f}s)",
                     value=round(now - alive_ts, 1),
                     threshold=t.dead_after_s)
                continue
            if r is None:
                continue

            track = self._tracks.setdefault(wid, _WorkerTrack())
            fresh = ws.received_ts > track.last_report_ts
            if fresh:
                if track.reports == 0:
                    track.first_report_ts = ws.received_ts
                track.reports += 1
                track.last_report_ts = ws.received_ts

            step = r.get("step")
            step = step if isinstance(step, int) \
                and not isinstance(step, bool) else None
            loss = r.get("loss")
            gnorm = r.get("grad_norm")
            loss_finite = bool(r.get("loss_finite", True))
            grad_finite = bool(r.get("grad_finite", True))

            # 1) non-finite signals (reports null the value and flag it, so
            # NaN never has to survive a JSON hop).
            if not loss_finite:
                fire("nonfinite_loss", wid,
                     f"worker {wid} reported a non-finite loss at step "
                     f"{step}")
            if not grad_finite:
                fire("nonfinite_grad", wid,
                     f"worker {wid} reported a non-finite gradient norm "
                     f"at step {step}")

            # 2) gradient explosion.
            if _finite(gnorm):
                med = None
                if len(track.grad_norms) >= t.grad_median_warmup:
                    s = sorted(track.grad_norms)
                    med = s[len(s) // 2]
                limit = t.grad_norm_ceiling
                if med is not None and med > 0:
                    limit = min(limit, t.grad_explosion_factor * med)
                if gnorm > limit:
                    fire("grad_explosion", wid,
                         f"worker {wid} grad norm {gnorm:.3g} > "
                         f"{limit:.3g} at step {step}",
                         value=float(gnorm), threshold=float(limit))
                elif fresh:
                    # Only healthy observations from NEW reports feed the
                    # median — one explosion must not drag the baseline up
                    # after it, and a re-evaluated stale report must not
                    # flood the window with duplicates.
                    track.grad_norms.append(float(gnorm))

            # 3) loss divergence / plateau.
            if _finite(loss):
                if track.best_loss is None or loss < track.best_loss \
                        - t.plateau_min_improvement:
                    track.best_loss = float(loss)
                    track.best_loss_ts = ws.received_ts
                elif track.best_loss is not None \
                        and loss < track.best_loss:
                    track.best_loss = float(loss)
                if track.reports > t.loss_divergence_warmup \
                        and track.best_loss is not None \
                        and track.best_loss > 1e-8 \
                        and loss > t.loss_divergence_factor \
                        * track.best_loss:
                    fire("loss_divergence", wid,
                         f"worker {wid} loss {loss:.4g} > "
                         f"{t.loss_divergence_factor:g}x best "
                         f"{track.best_loss:.4g}",
                         value=float(loss),
                         threshold=t.loss_divergence_factor
                         * track.best_loss)
                if track.best_loss_ts \
                        and ws.received_ts - track.best_loss_ts \
                        > t.plateau_window_s \
                        and ws.received_ts - track.first_report_ts \
                        > t.plateau_window_s:
                    fire("loss_plateau", wid,
                         f"worker {wid} loss has not improved by "
                         f"{t.plateau_min_improvement:g} in "
                         f"{ws.received_ts - track.best_loss_ts:.0f}s",
                         value=float(loss),
                         threshold=t.plateau_min_improvement)

            # 4) stall: the worker's own step froze while the cluster moved.
            if step is not None:
                if track.last_step is None or step != track.last_step:
                    track.last_step = step
                    track.last_step_change_ts = ws.received_ts
                    track.step_at_last_change = state.global_step
                elif now - track.last_step_change_ts > t.stall_after_s \
                        and state.global_step > track.step_at_last_change:
                    fire("worker_stall", wid,
                         f"worker {wid} stuck at step {step} for "
                         f"{now - track.last_step_change_ts:.0f}s while "
                         f"the cluster advanced",
                         value=round(now - track.last_step_change_ts, 1),
                         threshold=t.stall_after_s)
                reporting_steps.append((wid, step))

        # 5) stragglers, relative to the fastest reporting worker.
        if len(reporting_steps) >= 2:
            max_step = max(s for _, s in reporting_steps)
            for wid, s in reporting_steps:
                if max_step - s > t.straggler_lag_steps \
                        and ("worker_stall", wid) not in firing:
                    fire("straggler_lag", wid,
                         f"worker {wid} at step {s}, "
                         f"{max_step - s} behind the leader",
                         value=float(max_step - s),
                         threshold=float(t.straggler_lag_steps))

        # Workers latched dead that have dropped out of the state entirely
        # (expired AND pruned from membership): the alert must stay active
        # until they are seen again, not resolve because they vanished.
        for wid in sorted(self._dead):
            if wid not in state.workers \
                    and ("dead_worker", wid) not in firing:
                fire("dead_worker", wid,
                     f"worker {wid} expired from membership and has not "
                     f"returned", threshold=t.dead_after_s)

        # 6) staleness-rejection spike (cluster-wide, async mode).
        total = state.pushes_accepted_delta + state.pushes_rejected_delta
        ratio = state.pushes_rejected_delta / total if total else 0.0
        if ratio > t.staleness_reject_ratio and (
                total >= t.staleness_min_pushes
                # Resolution hysteresis: once ACTIVE, the spike holds while
                # a freshly-rolled (still undersampled) window shows the
                # same thrash ratio, instead of emitting one resolved +
                # re-fired pair per window roll during sustained thrashing
                # (each fresh "fired" edge bypasses the re-alert cooldown
                # and bumps dps_alerts_total). A genuinely quiet or
                # healthy-ratio window still resolves immediately.
                or ("staleness_spike", None) in self._active):
            fire("staleness_spike", None,
                 f"{state.pushes_rejected_delta}/{total} pushes "
                 f"rejected by the staleness gate this window",
                 value=round(ratio, 4),
                 threshold=t.staleness_reject_ratio)

        # 6b) corrupt wire frames (push CRC trailer, comms/service.py).
        # Unlike the staleness spike there is no healthy baseline rate:
        # ONE refused frame means either real wire/memory damage or an
        # injected chaos schedule doing its job, so any nonzero window
        # fires. The window is time-anchored by the monitor (one
        # interval), so the alert outlives the single scrape that saw it.
        if state.corrupt_frames_delta > 0:
            fire("wire_corrupt", None,
                 f"{state.corrupt_frames_delta} corrupt push frame(s) "
                 f"refused this window (wire CRC mismatch)",
                 value=float(state.corrupt_frames_delta), threshold=0.0)

        # 6c) host memory leak slope (telemetry/memory.py, attached by
        # the monitor). Server-scope like the SLO rules: the verdict is
        # THIS process's RSS, so worker identity is None. Gated on a
        # minimum window span + sample count — two samples a second
        # apart during an allocation burst are not a leak.
        mem = state.memory if isinstance(state.memory, dict) else None
        if mem:
            slope = mem.get("growth_bytes_per_s")
            span = mem.get("window_span_s")
            n = mem.get("samples")
            if _finite(slope) and _finite(span) \
                    and isinstance(n, int) \
                    and span >= t.memory_growth_min_window_s \
                    and n >= t.memory_growth_min_samples \
                    and slope > t.memory_growth_bytes_per_s:
                fire("memory_growth", None,
                     f"host RSS growing {slope / 1048576.0:.1f} MiB/s "
                     f"over a {span:.0f}s window "
                     f"(rss {(mem.get('rss_bytes') or 0) / 1048576.0:.0f}"
                     f" MiB)",
                     value=round(float(slope), 1),
                     threshold=t.memory_growth_bytes_per_s)

        # 7) SLO burn-rate breaches (telemetry/slo.py, attached by the
        # monitor). One aggregated alert per rule — alert identity is
        # (rule, worker) and these are server-side conditions with no
        # worker — naming every breaching objective, value = worst burn.
        for rule in ("slo_burn_fast", "slo_burn_slow"):
            hits = [b for b in state.slo_breaches
                    if isinstance(b, dict) and b.get("rule") == rule]
            if not hits:
                continue
            worst = max(hits, key=lambda b: b.get("burn") or 0.0)
            names = ", ".join(sorted(str(b.get("objective")) for b in hits))
            fire(rule, None,
                 f"SLO burn over {worst.get('window_s', 0):.0f}s window: "
                 f"{names} (worst burn {worst.get('burn', 0):.1f}x budget)",
                 value=worst.get("burn"),
                 threshold=worst.get("burn_threshold"))

        # A departed-for-good worker's history must not pin memory forever.
        for wid in [w for w in self._tracks
                    if w not in state.workers and w not in self._dead]:
            del self._tracks[wid]
        return firing
