"""Durable append-only telemetry journal: segmented JSONL time-series.

Every live surface this repo grew — ``/metrics``, ``/cluster``,
``/fleet``, the flight recorder, SLO burn — keeps its history in bounded
in-memory deques that die with the process. The reference answered
"what happened at 03:12?" by regex-scraping CloudWatch
(parse_cloudwatch_logs.py); this module is the native replacement: every
process (``cli serve/replica/worker/observe``) streams its typed events
into an on-disk journal that survives a SIGKILL and is queryable after
the fact (``cli query``, ``cli incident report``, ``cli top --replay``).

Layout (one directory per run, shared by all local processes)::

    journal/
      journal-<ms>-<pid>-<n>.jsonl          # raw segments, append-only
      journal-<ms>-<pid>-<n>.coarse.jsonl   # downsampled old segments

Record envelope — one JSON object per line::

    {"v": 1, "type": "alert", "ts": 1724.5, "role": "server",
     "pid": 1234, "seq": 17, ...payload}

``type`` must be a key of :data:`EVENT_CATALOG` (drift-pinned against
docs/OBSERVABILITY.md by dpslint's ``catalog_drift`` check). Payload keys
never override the envelope.

Durability model, in order of the failure modes it survives:

- **Torn tail**: every ``append`` writes one full line and flushes; a
  SIGKILL can tear at most the final line of the active segment, and
  :class:`JournalReader` skips a torn tail (counted, never fatal).
- **Rotation** by size (``max_segment_bytes``) and age
  (``max_segment_age_s``): a sealed segment is fsync'd, so only the
  active segment is ever at risk.
- **Retention**: when sealed raw segments exceed ``retention_bytes``
  the oldest are not deleted but *downsampled* into a coarse tier —
  every ``coarse_keep_every``-th cumulative snapshot per (role, pid)
  stream plus the stream's first and last, and ALL non-snapshot events
  (alerts, remediations, ... are the forensic record; only the dense
  metric samples thin out). Because snapshots are cumulative, the kept
  samples stay *exact* — downsampling coarsens time resolution, never
  the counts. The coarse tier has its own ``coarse_retention_bytes``
  cap after which the oldest coarse segments finally drop.

Writes are cheap by design — one ``json.dumps`` + buffered write +
``flush`` per record, fsync only at seal time — so journaling rides the
serving path at well under the 2% overhead budget (bench.py measures
``journal_write_us`` / ``journal_bytes_per_tick``; benchwatch tracks
both as lower-is-better series).

A process-global hub (:func:`set_journal` / :func:`journal_event`) lets
subsystem chokepoints (alert edges, remediation actions, directives,
migration phases, re-parents, checkpoints) journal in one line each,
compiling to a no-op when no journal is configured.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .registry import MetricsRegistry, get_registry

__all__ = [
    "EVENT_CATALOG",
    "JournalReader",
    "JournalWriter",
    "get_journal",
    "journal_event",
    "read_journal",
    "set_journal",
]

#: Typed record catalog: type -> one-line meaning. Drift-pinned BOTH
#: directions against the docs/OBSERVABILITY.md "Event catalog" table by
#: dpslint's ``catalog_drift.check_event_catalog`` — adding a type here
#: without documenting it (or vice versa) fails lint and tier-1. Must
#: stay a pure literal (the drift engine ``ast.literal_eval``'s it).
EVENT_CATALOG = {
    "snapshot": "cumulative per-process metrics registry snapshot "
                "(SnapshotEmitter tick; counters/gauges/histograms)",
    "fleet_tick": "one FleetCollector scrape tick: the merged /fleet "
                  "view minus its history rings (replay source)",
    "alert": "health-rule edge from ClusterMonitor: fired, refired, or "
             "resolved, with rule/severity/worker/value",
    "slo_burn": "fleet-scope SLO burn-rate breach edge from the "
                "collector windows (objective, window_s, burn)",
    "remediation": "remediation engine action outcome "
                   "(quorum_exclude, rebalance, quarantine, refetch, ...)",
    "respawn": "supervisor worker respawn attempt and its outcome "
               "(ok, crash_loop)",
    "directive": "coordinator posted a control-plane directive to a "
                 "worker mailbox (action, seq)",
    "migration": "live shard-migration phase transition "
                 "(export, import, apply_ranges, commit) with role",
    "reparent": "edge replica re-parented to a new upstream feed "
                "(shard, old, new, tier)",
    "checkpoint": "checkpoint manager published an atomic store "
                  "snapshot (step, path)",
    "fault": "a seeded fault-injection plan was armed on this process "
             "(spec string, PR 13 grammar)",
    "incident": "incident capture engine froze a forensic bundle "
                "(id, rule, path)",
    "profile": "profile trigger engine captured and attributed a "
               "device-profile window (id, rule, path)",
}

_SNAPSHOT_TYPES = ("snapshot", "fleet_tick")


def _now_ms(ts: float) -> int:
    return int(ts * 1000.0)


class JournalWriter:
    """Append-only segmented JSONL writer for one process.

    Thread-safe; every public method takes the internal lock. Failures
    to write (disk full, directory removed) raise to the caller —
    :func:`journal_event` is the swallow-everything wrapper used on
    serving paths.
    """

    def __init__(self, directory: str, role: str = "process",
                 max_segment_bytes: int = 4 * 1024 * 1024,
                 max_segment_age_s: float = 300.0,
                 retention_bytes: int = 64 * 1024 * 1024,
                 coarse_keep_every: int = 10,
                 coarse_retention_bytes: int = 16 * 1024 * 1024,
                 registry: MetricsRegistry | None = None,
                 clock=time.time):
        if max_segment_bytes <= 0 or retention_bytes <= 0:
            raise ValueError("segment/retention byte caps must be > 0")
        if coarse_keep_every < 1:
            raise ValueError(
                f"coarse_keep_every must be >= 1, got {coarse_keep_every}")
        self.directory = directory
        self.role = role
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segment_age_s = float(max_segment_age_s)
        self.retention_bytes = int(retention_bytes)
        self.coarse_keep_every = int(coarse_keep_every)
        self.coarse_retention_bytes = int(coarse_retention_bytes)
        self.clock = clock
        self._pid = os.getpid()
        os.makedirs(directory, exist_ok=True)
        reg = registry or get_registry()
        self._tm_records = reg.counter("dps_journal_records_total")
        self._tm_bytes = reg.counter("dps_journal_bytes_total")
        self._tm_segments = reg.counter("dps_journal_segments_total")
        self._lock = threading.Lock()
        self._fh = None            # guarded by: self._lock
        self._seg_path = None      # guarded by: self._lock
        self._seg_bytes = 0        # guarded by: self._lock
        self._seg_opened = 0.0     # guarded by: self._lock
        self._seg_n = 0            # guarded by: self._lock
        self._seq = 0              # guarded by: self._lock

    # -- segment lifecycle -------------------------------------------------

    def _open_segment_locked(self, now: float) -> None:
        self._seg_n += 1
        name = (f"journal-{_now_ms(now):013d}-{self._pid}-"
                f"{self._seg_n:04d}.jsonl")
        self._seg_path = os.path.join(self.directory, name)
        self._fh = open(self._seg_path, "a", encoding="utf-8")
        self._seg_bytes = 0
        self._seg_opened = now
        self._tm_segments.inc()

    def _seal_locked(self) -> None:
        if self._fh is None:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        finally:
            self._fh.close()
            self._fh = None
            self._seg_path = None

    def seal(self) -> None:
        """Flush + fsync + close the active segment (crash-consistent
        tail). The next ``append`` opens a fresh segment. Called from
        ``SnapshotEmitter.stop(final=True)`` and the SIGTERM
        shutdown-flush path so a killed process's journal ends clean."""
        with self._lock:
            self._seal_locked()

    close = seal

    # -- writes ------------------------------------------------------------

    def append(self, type: str, payload: dict | None = None) -> dict:
        """Validate against the catalog, write one line, maybe rotate.
        Returns the full record as written (tests, incident capture)."""
        if type not in EVENT_CATALOG:
            raise ValueError(
                f"unknown journal event type {type!r}; "
                f"known: {sorted(EVENT_CATALOG)}")
        with self._lock:
            now = self.clock()
            self._seq += 1
            rec = dict(payload or {})
            rec.setdefault("ts", round(now, 3))
            rec.update({"v": 1, "type": type, "role": self.role,
                        "pid": self._pid, "seq": self._seq})
            line = json.dumps(rec, separators=(",", ":"), default=str)
            data = line + "\n"
            if (self._fh is None
                    or (self._seg_bytes > 0
                        and (self._seg_bytes + len(data)
                             > self.max_segment_bytes
                             or now - self._seg_opened
                             > self.max_segment_age_s))):
                self._seal_locked()
                self._enforce_retention_locked()
                self._open_segment_locked(now)
            self._fh.write(data)
            self._fh.flush()
            self._seg_bytes += len(data)
            self._tm_records.inc()
            self._tm_bytes.inc(len(data))
            return rec

    # -- retention / downsampling -----------------------------------------

    def _list_locked(self, coarse: bool) -> list:
        """Sorted (path, size) for sealed segments of one tier; raw tier
        excludes the active segment."""
        out = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for name in names:
            is_coarse = name.endswith(".coarse.jsonl")
            if (not name.startswith("journal-")
                    or not name.endswith(".jsonl")
                    or is_coarse is not coarse):
                continue
            path = os.path.join(self.directory, name)
            if path == self._seg_path:
                continue
            try:
                out.append((path, os.path.getsize(path)))
            except OSError:
                continue
        return out

    def _enforce_retention_locked(self) -> None:
        raw = self._list_locked(coarse=False)
        total = sum(size for _, size in raw)
        while raw and total > self.retention_bytes:
            path, size = raw.pop(0)
            self._compact_segment(path)
            total -= size
        coarse = self._list_locked(coarse=True)
        ctotal = sum(size for _, size in coarse)
        while coarse and ctotal > self.coarse_retention_bytes:
            path, size = coarse.pop(0)
            try:
                os.remove(path)
            except OSError:
                pass
            ctotal -= size

    def _compact_segment(self, path: str) -> None:
        """Downsample one sealed raw segment into the coarse tier, then
        drop the raw file. Keeps all non-snapshot events; snapshots thin
        to every k-th per (role, pid) stream plus first and last —
        cumulative payloads make the kept samples exact."""
        stats = {"torn_tails": 0, "corrupt_lines": 0}
        records = list(_iter_segment(path, stats))
        streams: dict = {}
        for rec in records:
            if rec.get("type") in _SNAPSHOT_TYPES:
                key = (rec.get("role"), rec.get("pid"), rec.get("type"))
                streams.setdefault(key, []).append(rec)
        keep_ids = set()
        for stream in streams.values():
            n = len(stream)
            for i, rec in enumerate(stream):
                if i % self.coarse_keep_every == 0 or i == n - 1:
                    keep_ids.add(id(rec))
        out_path = path[:-len(".jsonl")] + ".coarse.jsonl"
        tmp_path = out_path + ".tmp"
        with open(tmp_path, "w", encoding="utf-8") as f:
            for rec in records:
                if (rec.get("type") not in _SNAPSHOT_TYPES
                        or id(rec) in keep_ids):
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, out_path)
        try:
            os.remove(path)
        except OSError:
            pass


def _iter_segment(path: str, stats: dict):
    """Yield decodable records from one segment, tolerating torn tails
    and corrupt mid-file lines (each counted, never fatal)."""
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            data = f.read()
    except OSError:
        stats["corrupt_lines"] += 1
        return
    lines = data.split("\n")
    last_idx = max((i for i, ln in enumerate(lines) if ln.strip()),
                   default=-1)
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == last_idx and not data.endswith("\n"):
                stats["torn_tails"] += 1
            else:
                stats["corrupt_lines"] += 1
            continue
        if not isinstance(rec, dict) or "type" not in rec \
                or "ts" not in rec:
            stats["corrupt_lines"] += 1
            continue
        yield rec


class JournalReader:
    """Merged, time-ordered view over a journal directory (raw + coarse
    tiers) or a single segment file. Read-only; safe against torn tails
    and corrupt lines (``self.stats`` reports what was skipped)."""

    def __init__(self, path: str):
        self.path = path
        self.stats = {"segments": 0, "records": 0, "torn_tails": 0,
                      "corrupt_lines": 0}

    def segments(self) -> list:
        if os.path.isfile(self.path):
            return [self.path]
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return []
        return [os.path.join(self.path, n) for n in names
                if n.startswith("journal-") and n.endswith(".jsonl")]

    def records(self, types=None, start_ts: float | None = None,
                end_ts: float | None = None, roles=None) -> list:
        """All matching records across every segment, sorted by
        ``(ts, pid, seq)``. ``types``/``roles`` are iterables of exact
        names; time bounds are inclusive."""
        types = set(types) if types is not None else None
        roles = set(roles) if roles is not None else None
        out = []
        for path in self.segments():
            self.stats["segments"] += 1
            for rec in _iter_segment(path, self.stats):
                if types is not None and rec.get("type") not in types:
                    continue
                if roles is not None and rec.get("role") not in roles:
                    continue
                ts = rec.get("ts")
                if not isinstance(ts, (int, float)):
                    self.stats["corrupt_lines"] += 1
                    continue
                if start_ts is not None and ts < start_ts:
                    continue
                if end_ts is not None and ts > end_ts:
                    continue
                out.append(rec)
        out.sort(key=lambda r: (r.get("ts", 0.0), r.get("pid", 0),
                                r.get("seq", 0)))
        self.stats["records"] += len(out)
        return out


def read_journal(path: str, **kwargs) -> list:
    """One-shot convenience: ``JournalReader(path).records(**kwargs)``."""
    return JournalReader(path).records(**kwargs)


# -- process-global hub ----------------------------------------------------

_hub_lock = threading.Lock()
_JOURNAL: JournalWriter | None = None


def set_journal(writer: JournalWriter | None) -> None:
    """Install (or clear, with ``None``) the process-global journal that
    :func:`journal_event` chokepoints write through."""
    global _JOURNAL
    with _hub_lock:
        _JOURNAL = writer


def get_journal() -> JournalWriter | None:
    with _hub_lock:
        return _JOURNAL


def journal_event(type: str, **payload) -> None:
    """Fire-and-forget chokepoint append: a cheap no-op when no journal
    is configured, and never raises — subsystem hot paths (alert edges,
    directives, migrations) must not fail because forensics did."""
    writer = _JOURNAL
    if writer is None:
        return
    try:
        writer.append(type, payload)
    except Exception:  # noqa: BLE001 — forensics never breaks serving
        pass
