"""Remediation policy engine: cluster alerts -> self-healing actions.

PR 5's :class:`~.cluster.ClusterMonitor` closed the *detect* half of the
loop — a `dead_worker` alert fires, lands in `/cluster`, and then sits
there while the sync round keeps waiting on the corpse. This module is the
*act* half (docs/ROBUSTNESS.md "Self-healing"): it listens to the
monitor's alert edge events and maps rule firings to concrete actions
through a fixed, drift-pinned action catalog:

- ``dead_worker`` -> **respawn**: the process restart itself belongs to
  the :class:`~..ps.supervisor.WorkerSupervisor` colocated with the worker
  (it sees the child die within its poll interval); the server-side engine
  records the request so ``/cluster`` shows the loop closing end to end.
- ``straggler_lag`` -> **quorum_exclude** (the store stops sizing sync
  rounds to include the laggard, ``ps/store.py:exclude_worker``) +
  **rebalance** (a ``rebalance_shard`` directive so the cluster resharding
  covers the work it is no longer keeping up with).
- ``nonfinite_loss``/``nonfinite_grad`` -> **quarantine** (the service
  refuses the worker's pushes server-side — even a legacy peer can't
  poison the aggregate — and a ``quarantine`` directive tells capable
  workers to pause pushing and reset error-feedback residuals) +
  **refetch** (a ``refetch_params`` directive: drop the possibly-poisoned
  local basis, take a full fresh fetch).

Alert *resolution* lifts what it caused: a resolved ``straggler_lag``
re-includes the worker, a resolved non-finite alert unquarantines it.

Discipline, in the monitor's image: actions are **rate-limited** per
(action, worker) pair (``cooldown_s``), **dry-runnable** (compute and
record everything, touch nothing), and every decision is a stateful
**remediation event** — counted in
``dps_remediation_actions_total{action,outcome}``, dropped into the flight
recorder as a ``cluster.remediation`` record, embedded in the
``"kind": "cluster"`` stream via the monitor's view, and served live in
``GET /cluster`` under ``"remediation"``. The engine never raises into the
monitor: remediating a cluster must not be able to take its server down.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from .journal import journal_event
from .registry import get_registry

__all__ = [
    "ACTION_CATALOG",
    "ACTION_OUTCOMES",
    "DEFAULT_POLICY_RULES",
    "RemediationEngine",
    "RemediationPolicy",
    "WorkerAutoscalePolicy",
    "WorkerAutoscaler",
    "note_action",
]

#: action name -> one-line meaning. A wire/doc contract like rule and
#: directive names: docs/ROBUSTNESS.md documents exactly these rows and
#: ``tests/test_docs_drift.py`` pins the two to each other both
#: directions. ``dps_remediation_actions_total`` label values come from
#: this table (plus the supervisor's own ``respawn`` increments).
ACTION_CATALOG = {
    "respawn": "restart a dead worker's process — executed by the "
               "supervisor watching it; the server-side engine records "
               "the request (outcome `delegated`)",
    "quorum_exclude": "drop a straggler from the sync round target so "
                      "rounds stop waiting for it (its pushes still "
                      "land; late ones reconcile via staleness)",
    "rebalance": "post a `rebalance_shard` directive: finish the epoch "
                 "early and reshard from live membership",
    "quarantine": "refuse the worker's pushes server-side and post a "
                  "`quarantine` directive (pause pushes, reset error "
                  "feedback)",
    "refetch": "post a `refetch_params` directive: drop the delta "
               "basis, take a full fresh fetch",
    "replica_grow": "spawn one read replica — decided by the "
                    "autoscaler (telemetry/autoscale.py) from windowed "
                    "fetch QPS, executed by the ReplicaPool",
    "replica_shrink": "retire the youngest read replica when fetch "
                      "load stays under the low-water mark and no "
                      "replica lags",
    "worker_grow": "add one worker slot for a job whose admission "
                   "queue depth / straggler pressure stays high — "
                   "decided by the WorkerAutoscaler, executed by the "
                   "WorkerSupervisor colocated with the workers "
                   "(outcome `delegated` when recorded server-side)",
    "worker_shrink": "retire a job's youngest worker slot once "
                     "pressure stays under the low-water mark for the "
                     "full sustain window",
}

#: Every outcome an action decision can record. Counters are pre-created
#: for the full action x outcome grid so scrapes show the vocabulary at
#: zero (the ``dps_alerts_total`` discipline).
ACTION_OUTCOMES = ("ok", "delegated", "dry_run", "rate_limited",
                   "skipped", "error", "lifted", "crash_loop")

#: rule -> actions, the default policy table (docs/ROBUSTNESS.md).
DEFAULT_POLICY_RULES = {
    "dead_worker": ("respawn",),
    "straggler_lag": ("quorum_exclude", "rebalance"),
    "nonfinite_loss": ("quarantine", "refetch"),
    "nonfinite_grad": ("quarantine", "refetch"),
}

#: Remediation events kept for the `/cluster` view.
EVENTS_KEPT = 256


def note_action(action: str, outcome: str, registry=None) -> None:
    """Count one remediation action outcome. The ONE place the metric
    name lives, shared by the server-side engine and the worker-process
    supervisor (which executes ``respawn`` where the process actually
    lives)."""
    reg = registry or get_registry()
    reg.counter("dps_remediation_actions_total", action=action,
                outcome=outcome).inc()
    journal_event("respawn" if action == "respawn" else "remediation",
                  action=action, outcome=outcome)


@dataclass
class RemediationPolicy:
    """Engine knobs (defaults documented in docs/ROBUSTNESS.md)."""

    #: Compute and record every decision; execute nothing.
    dry_run: bool = False
    #: Minimum seconds between repeated decisions for the same
    #: (action, worker) pair — an alert that refires every evaluation
    #: produces one action per cooldown, not one per tick.
    cooldown_s: float = 30.0
    #: Hard cap on actions executed per event batch.
    max_actions_per_batch: int = 8
    #: Server-side push-refusal window for the quarantine action.
    quarantine_s: float = 30.0
    #: Boundary windows the quarantine directive tells the worker to skip.
    quarantine_steps: int = 3
    #: rule -> tuple of action names (see :data:`DEFAULT_POLICY_RULES`).
    rules: dict = field(default_factory=lambda: dict(DEFAULT_POLICY_RULES))


class RemediationEngine:
    """Maps alert edge events to actions against the store + service.

    Attach with ``monitor.add_listener(engine.handle_events)`` (and
    ``monitor.remediation = engine`` so ``cluster_view`` carries the
    remediation state). ``handle_events`` runs on whatever thread
    evaluated the monitor — it must stay cheap and must never raise.
    """

    def __init__(self, store, service=None,
                 policy: RemediationPolicy | None = None,
                 clock=time.time, registry=None, role: str = "server"):
        self.store = store
        self.service = service
        self.policy = policy or RemediationPolicy()
        self.clock = clock
        self.role = role
        self._lock = threading.Lock()
        self._last_action: dict[tuple, float] = {}  # guarded by: self._lock
        #: (action, worker) -> the event dict that activated it; an entry
        #: here is an ACTIVE remediation (shown in /cluster, lifted on
        #: alert resolution).
        self._active: dict[tuple, dict] = {}  # guarded by: self._lock
        self.events: deque = deque(maxlen=EVENTS_KEPT)  # guarded by: self._lock
        reg = registry or get_registry()
        self._tm = {
            (a, o): reg.counter("dps_remediation_actions_total",
                                action=a, outcome=o)
            for a in ACTION_CATALOG for o in ACTION_OUTCOMES
        }

    # -- event intake ---------------------------------------------------------

    def handle_events(self, events) -> list[dict]:
        """Consume one batch of monitor edge events; returns the
        remediation events recorded. Never raises."""
        out: list[dict] = []
        try:
            budget = self.policy.max_actions_per_batch
            for ev in events or []:
                state = ev.get("state")
                rule = ev.get("rule")
                worker = ev.get("worker")
                actions = self.policy.rules.get(rule) or ()
                if state in ("fired", "refired"):
                    for action in actions:
                        if budget <= 0:
                            break
                        rec = self._act(action, rule, worker)
                        if rec is not None:
                            out.append(rec)
                            if rec["outcome"] not in ("rate_limited",):
                                budget -= 1
                elif state == "resolved":
                    for action in actions:
                        rec = self._lift(action, rule, worker)
                        if rec is not None:
                            out.append(rec)
        except Exception:  # noqa: BLE001 — remediation must not hurt
            pass
        return out

    # -- decisions ------------------------------------------------------------

    def _act(self, action: str, rule: str, worker) -> dict | None:
        now = self.clock()
        key = (action, worker)
        with self._lock:
            last = self._last_action.get(key)
            limited = (last is not None
                       and now - last < self.policy.cooldown_s)
            if not limited:
                self._last_action[key] = now
        if limited:
            return self._record(action, rule, worker, "rate_limited", now)
        if self.policy.dry_run:
            rec = self._record(action, rule, worker, "dry_run", now)
        else:
            try:
                outcome = self._execute(action, worker)
            except Exception as e:  # noqa: BLE001
                rec = self._record(action, rule, worker, "error", now,
                                   detail=repr(e))
                return rec
            rec = self._record(action, rule, worker, outcome, now)
        if rec["outcome"] in ("ok", "delegated", "dry_run"):
            with self._lock:
                self._active[key] = rec
        return rec

    def _execute(self, action: str, worker) -> str:
        store, svc = self.store, self.service
        if action == "respawn":
            # Process restarts belong to the supervisor colocated with
            # the worker (ps/supervisor.py detects the death itself and
            # counts its own respawn outcome); the server records the
            # request so the healing loop is visible end to end.
            return "delegated"
        if worker is None:
            return "skipped"
        if action == "quorum_exclude":
            fn = getattr(store, "exclude_worker", None)
            if not callable(fn):
                return "skipped"  # backend without quorum rounds
            fn(worker)
            return "ok"
        if action == "rebalance":
            if svc is None:
                return "skipped"
            seq = svc.post_directive(worker, "rebalance_shard")
            return "ok" if seq is not None else "skipped"  # legacy peer
        if action == "quarantine":
            if svc is None:
                return "skipped"
            svc.quarantine(worker, self.policy.quarantine_s)
            # The directive half is best-effort: a legacy peer can't
            # hear it, but the server-side refusal above already holds.
            svc.post_directive(worker, "quarantine",
                               steps=self.policy.quarantine_steps)
            return "ok"
        if action == "refetch":
            if svc is None:
                return "skipped"
            seq = svc.post_directive(worker, "refetch_params")
            return "ok" if seq is not None else "skipped"
        return "skipped"

    def _lift(self, action: str, rule: str, worker) -> dict | None:
        key = (action, worker)
        with self._lock:
            active = self._active.pop(key, None)
            if active is None:
                return None
        if not self.policy.dry_run:
            try:
                if action == "quorum_exclude":
                    fn = getattr(self.store, "include_worker", None)
                    if callable(fn) and worker is not None:
                        fn(worker)
                elif action == "quarantine" and self.service is not None \
                        and worker is not None:
                    self.service.unquarantine(worker)
            except Exception:  # noqa: BLE001
                pass
        return self._record(action, rule, worker, "lifted", self.clock())

    # -- recording ------------------------------------------------------------

    def _record(self, action: str, rule: str, worker, outcome: str,
                ts: float, detail: str | None = None) -> dict:
        rec = {"ts": round(ts, 3), "action": action, "rule": rule,
               "worker": worker, "outcome": outcome,
               "dry_run": self.policy.dry_run}
        if detail:
            rec["detail"] = detail
        counter = self._tm.get((action, outcome))
        if counter is not None:
            counter.inc()
        with self._lock:
            self.events.append(rec)
        self._flight_record(rec)
        if outcome != "rate_limited":
            print(f"REMEDIATION action={action} rule={rule} "
                  f"worker={worker} outcome={outcome}", flush=True)
        return rec

    def _flight_record(self, rec: dict) -> None:
        """Span-shaped ``cluster.remediation`` record beside the
        ``cluster.alert`` ones, so post-mortem dumps and ``/debug/trace``
        carry the action history too."""
        from .trace import get_recorder
        try:
            get_recorder().record({
                "name": "cluster.remediation",
                "trace_id": os.urandom(8).hex(),
                "span_id": os.urandom(8).hex(),
                "parent_id": None,
                "ts": rec["ts"],
                "dur": 0.0,
                "role": self.role,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "attrs": {k: v for k, v in rec.items() if v is not None},
            })
        except Exception:  # noqa: BLE001
            pass

    # -- read side ------------------------------------------------------------

    def view(self) -> dict:
        """The ``"remediation"`` block of ``GET /cluster``
        (docs/ROBUSTNESS.md)."""
        with self._lock:
            active = sorted(self._active.values(),
                            key=lambda r: (r["action"],
                                           -1 if r["worker"] is None
                                           else r["worker"]))
            recent = list(self.events)[-32:]
        out = {
            "dry_run": self.policy.dry_run,
            "cooldown_s": self.policy.cooldown_s,
            "policy": {rule: list(actions)
                       for rule, actions in self.policy.rules.items()},
            "active": active,
            "recent": recent,
        }
        svc = self.service
        if svc is not None:
            try:
                q = svc.quarantine_view()
                if q:
                    out["quarantined"] = {str(w): s for w, s in q.items()}
            except Exception:  # noqa: BLE001
                pass
        return out


@dataclass
class WorkerAutoscalePolicy:
    """Per-job worker-scaling knobs (docs/TENANCY.md "Scaling policy").

    Same discipline as :class:`~.autoscale.AutoscalePolicy`, but the
    signal is QUEUE PRESSURE, not QPS: admission queue depth is spiky
    (one push storm fills it for a tick), so both directions require the
    condition to hold for ``sustain_ticks`` CONSECUTIVE ticks before
    acting — the hysteresis band plus the sustain window together keep a
    job hovering near one threshold from flapping its worker fleet.
    """

    #: Grow when the job's admission queue depth (waiting RPCs) exceeds
    #: this for ``sustain_ticks`` consecutive ticks — or when any of the
    #: job's workers holds an active straggler alert.
    depth_high: float = 4.0
    #: Shrink when depth stays below this (and no straggler pressure)
    #: for the full sustain window. Must sit under ``depth_high``.
    depth_low: float = 1.0
    #: Consecutive ticks a condition must hold before it acts.
    sustain_ticks: int = 3
    min_workers: int = 1
    max_workers: int = 4
    #: Minimum seconds between consecutive scaling actions.
    cooldown_s: float = 15.0
    #: Compute and record every decision; touch the supervisor never.
    dry_run: bool = False

    def __post_init__(self):
        if self.depth_low >= self.depth_high:
            raise ValueError(f"depth_low ({self.depth_low}) must be < "
                             f"depth_high ({self.depth_high})")
        if not 0 <= self.min_workers <= self.max_workers:
            raise ValueError(f"need 0 <= min ({self.min_workers}) <= "
                             f"max ({self.max_workers})")
        if self.sustain_ticks < 1:
            raise ValueError(f"sustain_ticks must be >= 1, "
                             f"got {self.sustain_ticks}")


class WorkerAutoscaler:
    """Queue-pressure policy head scaling ONE job's worker count.

    ``pressure_fn() -> dict`` supplies the signals (``queue_depth``,
    ``stragglers``, and — when no actuator is attached — ``workers``);
    ``cli supervise --autoscale-job`` builds one that polls the server's
    ``GET /cluster`` jobs block, and tests inject a fake. The EXECUTE
    half is ``supervisor.grow()/shrink()/count()``
    (:class:`~..ps.supervisor.WorkerSupervisor` slot add/remove); with
    ``supervisor=None`` the autoscaler is a server-side policy recorder
    — decisions land with outcome ``delegated`` (the remediation
    engine's respawn idiom: the process restart belongs to the
    supervisor colocated with the workers).
    """

    def __init__(self, job: str, pressure_fn, supervisor=None,
                 policy: WorkerAutoscalePolicy | None = None,
                 registry=None, clock=time.time):
        self.job = str(job)
        self.pressure_fn = pressure_fn
        self.supervisor = supervisor
        self.policy = policy or WorkerAutoscalePolicy()
        self.clock = clock
        self._reg = registry or get_registry()
        self._lock = threading.Lock()
        # Consecutive ticks the grow/shrink condition held.
        self._hot = 0    # guarded by: self._lock
        self._cold = 0   # guarded by: self._lock
        # -inf: the first action is never cooldown-held.
        self._last_action_ts = float("-inf")  # guarded by: self._lock
        self._events: deque = deque(maxlen=EVENTS_KEPT)  # guarded by: self._lock
        self.actions = {"worker_grow": 0, "worker_shrink": 0}
        self._tm_target = self._reg.gauge(
            "dps_job_autoscale_target_workers", job=self.job)

    def _live(self, signals: dict) -> int:
        if self.supervisor is not None:
            return int(self.supervisor.count())
        return int(signals.get("workers") or 0)

    def tick(self) -> dict | None:
        """One control pass; returns the decision record when one was
        made, None while pressure is in-band or still building its
        sustain window. Never raises (monitor-loop hosted)."""
        now = self.clock()
        try:
            signals = dict(self.pressure_fn() or {})
        except Exception:  # noqa: BLE001 — a poll miss is not a crash
            return None
        depth = float(signals.get("queue_depth") or 0.0)
        stragglers = int(signals.get("stragglers") or 0)
        live = self._live(signals)
        p = self.policy
        with self._lock:
            if depth > p.depth_high or stragglers > 0:
                self._hot += 1
                self._cold = 0
            elif depth < p.depth_low:
                self._cold += 1
                self._hot = 0
            else:
                self._hot = self._cold = 0
            hot, cold = self._hot, self._cold
        action = None
        if live < p.min_workers:
            action = "worker_grow"  # floor breach: act NOW, no sustain
        elif hot >= p.sustain_ticks and live < p.max_workers:
            action = "worker_grow"
        elif cold >= p.sustain_ticks and live > p.min_workers:
            action = "worker_shrink"
        if action is None:
            self._tm_target.set(live)
            return None
        with self._lock:
            if now - self._last_action_ts < p.cooldown_s:
                outcome = "rate_limited"
            elif p.dry_run:
                outcome = "dry_run"
            else:
                self._last_action_ts = now
                outcome = ("ok" if self.supervisor is not None
                           else "delegated")
                # An executed decision spends the sustain window; the
                # pressure must rebuild before the next one.
                self._hot = self._cold = 0
        if outcome == "ok":
            try:
                if action == "worker_grow":
                    self.supervisor.grow()
                    live += 1
                elif self.supervisor.shrink() is not None:
                    live -= 1
            except Exception:  # noqa: BLE001 — a failed spawn is an
                outcome = "error"  # outcome, not a host-loop crash
        self._tm_target.set(live)
        note_action(action, outcome, registry=self._reg)
        if outcome in ("ok", "delegated"):
            self.actions[action] += 1
        event = {"ts": round(now, 3), "job": self.job, "action": action,
                 "outcome": outcome, "queue_depth": round(depth, 1),
                 "stragglers": stragglers, "live": live}
        with self._lock:
            self._events.append(event)
        print(f"WORKER_AUTOSCALE job={self.job} action={action} "
              f"outcome={outcome} depth={depth:.1f} live={live}",
              flush=True)
        return event

    def view(self) -> dict:
        with self._lock:
            events = list(self._events)
            hot, cold = self._hot, self._cold
        return {"job": self.job,
                "min": self.policy.min_workers,
                "max": self.policy.max_workers,
                "depth_high": self.policy.depth_high,
                "depth_low": self.policy.depth_low,
                "sustain_ticks": self.policy.sustain_ticks,
                "hot_ticks": hot, "cold_ticks": cold,
                "dry_run": self.policy.dry_run,
                "actions": dict(self.actions),
                "events": events[-16:]}
