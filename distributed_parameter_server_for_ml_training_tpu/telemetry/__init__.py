"""Live telemetry: registry + spans + snapshot stream + Prometheus endpoint
+ distributed tracing with a crash-safe flight recorder.

The layer SURVEY.md §5.5 couldn't have: the reference emitted one
``METRICS_JSON`` line per process *at exit* and nothing before it. Here the
hot paths (train step, push/fetch RPC client and handler, store aggregation
in all three backends) record into a process-global
:class:`~.registry.MetricsRegistry`, and two read surfaces expose it live:

- :class:`~.snapshot.SnapshotEmitter` — periodic ``METRICS_JSON``
  ``"kind": "snapshot"`` lines, same regex convention as the exit line, so
  the existing ETL (`analysis/parse_logs.py`, CloudWatch-style scraping,
  pod-log ssh collection) gains time-series without changes;
- :func:`~.prometheus.start_metrics_server` — ``GET /metrics`` text
  exposition + ``/healthz`` + ``/debug/trace`` from the serving process.

The third surface is causal rather than aggregate: :mod:`.trace` carries a
per-step trace context through the worker loop and across the wire, records
finished spans into a bounded per-process flight recorder, and dumps the
tail on SIGTERM/unhandled-fault/atexit — see docs/OBSERVABILITY.md.

Metric names, bucket schemes, span names, and the snapshot line format are
documented in docs/OBSERVABILITY.md.
"""

from .registry import (
    BYTES_BUCKETS,
    Counter,
    ExemplarSampler,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    STALENESS_BUCKETS,
    VALUE_BUCKETS,
    get_registry,
    register_build_info,
)
from .autoscale import AutoscalePolicy, ReplicaAutoscaler
from .fleet import (
    FLEET_ROLLUP_FIELDS,
    FleetCollector,
    parse_prometheus_text,
    start_fleet_server,
)
from .stats import histogram_quantile, merge_histograms
from .journal import (
    EVENT_CATALOG,
    JournalReader,
    JournalWriter,
    get_journal,
    journal_event,
    read_journal,
    set_journal,
)
from .incidents import MANIFEST_FIELDS, IncidentCapture
from .goodput import (
    GOODPUT_CATEGORIES,
    GoodputAccount,
    goodput_report,
    parse_goodput_counters,
    report_from_counters,
)
from .memory import MemoryMonitor, read_device_memory, read_host_rss
from .proftrigger import PROFILE_RECORD_FIELDS, ProfileTrigger
from .cluster import (
    ClusterMonitor,
    get_cluster_monitor,
    set_cluster_monitor,
)
from .health import (
    RULE_CATALOG,
    Alert,
    ClusterState,
    HealthRuleEngine,
    HealthThresholds,
    WorkerState,
)
from .remediation import (
    ACTION_CATALOG,
    RemediationEngine,
    RemediationPolicy,
    note_action,
)
from .slo import SloEvaluator, SloObjective, default_objectives
from .snapshot import SnapshotEmitter
from .spans import now, span
from .prometheus import render_prometheus, start_metrics_server
from .trace import (
    SPAN_CATALOG,
    FlightRecorder,
    TraceContext,
    add_shutdown_flush,
    current_context,
    current_wire_trace,
    disable_tracing,
    enable_tracing,
    get_recorder,
    install_shutdown_hooks,
    remove_shutdown_flush,
    trace_enabled,
    trace_span,
    use_wire_context,
)

__all__ = [
    "ACTION_CATALOG",
    "Alert",
    "AutoscalePolicy",
    "BYTES_BUCKETS",
    "ClusterMonitor",
    "ClusterState",
    "Counter",
    "EVENT_CATALOG",
    "ExemplarSampler",
    "FLEET_ROLLUP_FIELDS",
    "FleetCollector",
    "FlightRecorder",
    "GOODPUT_CATEGORIES",
    "Gauge",
    "GoodputAccount",
    "HealthRuleEngine",
    "HealthThresholds",
    "Histogram",
    "IncidentCapture",
    "JournalReader",
    "JournalWriter",
    "LATENCY_BUCKETS",
    "LATENCY_BUCKETS_S",
    "MANIFEST_FIELDS",
    "MemoryMonitor",
    "MetricsRegistry",
    "PROFILE_RECORD_FIELDS",
    "ProfileTrigger",
    "RULE_CATALOG",
    "RemediationEngine",
    "RemediationPolicy",
    "ReplicaAutoscaler",
    "STALENESS_BUCKETS",
    "SPAN_CATALOG",
    "SloEvaluator",
    "SloObjective",
    "SnapshotEmitter",
    "TraceContext",
    "VALUE_BUCKETS",
    "WorkerState",
    "add_shutdown_flush",
    "current_context",
    "current_wire_trace",
    "default_objectives",
    "disable_tracing",
    "enable_tracing",
    "get_cluster_monitor",
    "get_journal",
    "get_recorder",
    "get_registry",
    "goodput_report",
    "histogram_quantile",
    "install_shutdown_hooks",
    "journal_event",
    "merge_histograms",
    "note_action",
    "now",
    "parse_goodput_counters",
    "parse_prometheus_text",
    "read_device_memory",
    "read_host_rss",
    "read_journal",
    "register_build_info",
    "report_from_counters",
    "remove_shutdown_flush",
    "render_prometheus",
    "set_cluster_monitor",
    "set_journal",
    "span",
    "start_fleet_server",
    "start_metrics_server",
    "trace_enabled",
    "trace_span",
    "use_wire_context",
]
