"""Live telemetry: registry + spans + snapshot stream + Prometheus endpoint.

The layer SURVEY.md §5.5 couldn't have: the reference emitted one
``METRICS_JSON`` line per process *at exit* and nothing before it. Here the
hot paths (train step, push/fetch RPC client and handler, store aggregation
in all three backends) record into a process-global
:class:`~.registry.MetricsRegistry`, and two read surfaces expose it live:

- :class:`~.snapshot.SnapshotEmitter` — periodic ``METRICS_JSON``
  ``"kind": "snapshot"`` lines, same regex convention as the exit line, so
  the existing ETL (`analysis/parse_logs.py`, CloudWatch-style scraping,
  pod-log ssh collection) gains time-series without changes;
- :func:`~.prometheus.start_metrics_server` — ``GET /metrics`` text
  exposition + ``/healthz`` from the serving process.

Metric names, bucket schemes, and the snapshot line format are documented
in docs/OBSERVABILITY.md.
"""

from .registry import (
    BYTES_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    STALENESS_BUCKETS,
    get_registry,
)
from .snapshot import SnapshotEmitter
from .spans import now, span
from .prometheus import render_prometheus, start_metrics_server

__all__ = [
    "BYTES_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricsRegistry",
    "STALENESS_BUCKETS",
    "SnapshotEmitter",
    "get_registry",
    "now",
    "render_prometheus",
    "span",
    "start_metrics_server",
]
