"""Automatic black-box capture: freeze a forensic bundle at the edge.

The moment a critical health rule fires (or a fleet SLO burn window
breaches) is exactly when the evidence is richest and the operator is
absent. :class:`IncidentCapture` rides the existing edge sources —
``ClusterMonitor.add_listener`` on the serving coordinator,
``FleetCollector`` view polling on the observer — and freezes a bundle
into ``incidents/<id>/`` the instant an edge arrives:

- ``manifest.json`` — the :data:`MANIFEST_FIELDS` schema (drift-pinned
  against docs/OBSERVABILITY.md);
- ``journal_window.jsonl`` — the merged journal slice covering
  ``window_s`` seconds before the edge (the causal record ``cli
  incident report`` replays);
- ``snapshots.json`` — point-in-time ``/cluster`` and ``/fleet`` views
  from ``views_fn``;
- ``traces/`` — flight-recorder dumps and exemplar traces pulled from
  implicated targets via ``traces_fn``.

An alert storm must yield ONE bundle, not a bundle per refire: captures
dedupe per rule inside ``cooldown_s`` (suppressions are counted on
``dps_incidents_suppressed_total``; captures on
``dps_incidents_captured_total``). Capture is best-effort everywhere —
a missing trace endpoint degrades the bundle, never the serving path.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .journal import JournalReader, JournalWriter, journal_event
from .registry import MetricsRegistry, get_registry

__all__ = ["MANIFEST_FIELDS", "IncidentCapture"]

#: ``manifest.json`` schema: field -> meaning. Drift-pinned BOTH
#: directions against the docs/OBSERVABILITY.md "Incident manifest"
#: table by dpslint's ``catalog_drift.check_incident_manifest``; must
#: stay a pure literal (the drift engine ``ast.literal_eval``'s it).
MANIFEST_FIELDS = {
    "id": "bundle id: inc-<utc stamp>-<pid>-<rule>",
    "created_ts": "unix seconds the capture fired",
    "role": "role of the capturing process (server, observer, ...)",
    "trigger": "the full edge event that fired the capture "
               "(rule, severity, worker, value, threshold, ...)",
    "window_s": "seconds of journal history frozen before the edge",
    "journal_dir": "journal directory the window was sliced from",
    "files": "bundle-relative file names actually written",
    "records": "record count inside journal_window.jsonl",
}


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)


class IncidentCapture:
    """Edge-triggered bundle freezer with per-rule cooldown dedupe.

    ``journal`` is a :class:`JournalWriter` (sealed best-effort before
    slicing so the window includes the freshest records) or a journal
    directory path. ``views_fn()`` returns ``{name: snapshot}`` dicts;
    ``traces_fn(trigger)`` returns ``[(file_name, payload), ...]``.
    """

    def __init__(self, incidents_dir: str, journal=None, views_fn=None,
                 traces_fn=None, window_s: float = 120.0,
                 cooldown_s: float = 120.0, role: str = "server",
                 registry: MetricsRegistry | None = None,
                 clock=time.time):
        if window_s <= 0 or cooldown_s < 0:
            raise ValueError("window_s must be > 0 and cooldown_s >= 0")
        self.incidents_dir = incidents_dir
        self.journal = journal
        self.views_fn = views_fn
        self.traces_fn = traces_fn
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.role = role
        self.clock = clock
        reg = registry or get_registry()
        self._tm_captured = reg.counter("dps_incidents_captured_total")
        self._tm_suppressed = reg.counter(
            "dps_incidents_suppressed_total")
        self._lock = threading.Lock()
        self._last_capture = {}   # guarded by: self._lock
        self._seen_edges = set()  # guarded by: self._lock

    # -- edge sources ------------------------------------------------------

    def on_alert_events(self, events) -> None:
        """``ClusterMonitor.add_listener`` entry: capture on every
        *newly fired* critical edge (refires and resolves never
        trigger; the cooldown handles storms of distinct fires)."""
        for ev in events:
            if ev.get("state") == "fired" \
                    and ev.get("severity") == "critical":
                self.maybe_capture(dict(ev))

    def on_fleet_view(self, view: dict) -> None:
        """Observer-side edge source: scan one ``/fleet`` view for
        critical active alerts and fleet SLO breaches, triggering once
        per distinct edge identity (then cooldown applies)."""
        triggers = []
        for alert in view.get("alerts") or ():
            if alert.get("severity") != "critical":
                continue
            key = ("alert", alert.get("rule"), alert.get("worker"),
                   alert.get("since"))
            triggers.append((key, dict(alert)))
        for breach in (view.get("slo") or {}).get("breaches") or ():
            if breach.get("severity") != "critical":
                continue
            key = ("slo", breach.get("rule"), breach.get("objective"))
            triggers.append((key, dict(breach)))
        for key, trigger in triggers:
            with self._lock:
                if key in self._seen_edges:
                    continue
                self._seen_edges.add(key)
            self.maybe_capture(trigger)

    # -- capture -----------------------------------------------------------

    def maybe_capture(self, trigger: dict) -> str | None:
        """Freeze one bundle unless the rule is inside its cooldown.
        Returns the bundle directory, or ``None`` when suppressed."""
        rule = trigger.get("rule") or "unknown"
        now = self.clock()
        with self._lock:
            last = self._last_capture.get(rule)
            if last is not None and now - last < self.cooldown_s:
                self._tm_suppressed.inc()
                return None
            self._last_capture[rule] = now
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(now))
        inc_id = f"inc-{stamp}-{os.getpid()}-{rule}"
        bundle = os.path.join(self.incidents_dir, inc_id)
        n = 1
        while os.path.exists(bundle):
            # two same-rule edges inside one second (cooldown_s=0, or
            # distinct fleet-edge identities) must not share a bundle
            n += 1
            inc_id = f"inc-{stamp}-{os.getpid()}-{rule}-{n}"
            bundle = os.path.join(self.incidents_dir, inc_id)
        os.makedirs(bundle)
        files = []
        records = 0
        journal_dir = self._journal_dir()
        if journal_dir:
            records = self._freeze_window(bundle, journal_dir, now)
            files.append("journal_window.jsonl")
        if self.views_fn is not None:
            try:
                views = self.views_fn()
            except Exception:  # noqa: BLE001 — degrade, never fail
                views = None
            if views is not None:
                _atomic_json(os.path.join(bundle, "snapshots.json"),
                             views)
                files.append("snapshots.json")
        if self.traces_fn is not None:
            try:
                traces = list(self.traces_fn(trigger) or ())
            except Exception:  # noqa: BLE001 — degrade, never fail
                traces = []
            if traces:
                tdir = os.path.join(bundle, "traces")
                os.makedirs(tdir, exist_ok=True)
                for name, payload in traces:
                    base = os.path.basename(str(name)) or "trace.json"
                    _atomic_json(os.path.join(tdir, base), payload)
                    files.append(os.path.join("traces", base))
        manifest = {
            "id": inc_id,
            "created_ts": round(now, 3),
            "role": self.role,
            "trigger": trigger,
            "window_s": self.window_s,
            "journal_dir": journal_dir,
            "files": sorted(files),
            "records": records,
        }
        _atomic_json(os.path.join(bundle, "manifest.json"), manifest)
        self._tm_captured.inc()
        journal_event("incident", id=inc_id, rule=rule, path=bundle)
        return bundle

    def _journal_dir(self) -> str | None:
        if isinstance(self.journal, JournalWriter):
            try:
                self.journal.seal()
            except Exception:  # noqa: BLE001 — stale tail beats no tail
                pass
            return self.journal.directory
        if isinstance(self.journal, str):
            return self.journal
        return None

    def _freeze_window(self, bundle: str, journal_dir: str,
                       now: float) -> int:
        reader = JournalReader(journal_dir)
        try:
            window = reader.records(start_ts=now - self.window_s)
        except Exception:  # noqa: BLE001 — degrade, never fail
            window = []
        path = os.path.join(bundle, "journal_window.jsonl")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in window:
                f.write(json.dumps(rec, separators=(",", ":"),
                                   default=str) + "\n")
        os.replace(tmp, path)
        return len(window)
