"""Trigger-driven continuous profiling: capture a window at the edge.

``cli perf profile`` is a manual one-shot — by the time an operator
runs it the regression that mattered is hours old. This engine rides
the same edge sources the incident capturer does and freezes a bounded
``jax.profiler`` window the instant something degrades, while the
degraded behavior is still on the devices:

- **benchwatch regression verdict** (``on_bench_verdict``) — a bench
  round's ledger check came back ``regression``;
- **SLO burn edge** (``on_alert_events`` via
  ``ClusterMonitor.add_listener``) — a freshly fired ``slo_burn_*``
  alert;
- **goodput-fraction drop edge** (``observe_goodput``) — the fleet's
  productive fraction fell through the threshold after having been
  healthy.

Each capture runs through :func:`..analysis.device_profile.
attribute_profile` and lands as ONE self-contained
``PROFILE_*.json`` record in the committed ``profiles/`` ledger (the
per-op-class time series ``tools/benchwatch`` validates and
regression-checks — the artifact every kernel PR cites). Raw Chrome
traces are pruned after a successful attribution and kept as evidence
when the join fails (:func:`..telemetry.profiler.prune_capture`).

A degradation storm must yield ONE capture, not one per refire:
triggers dedupe per rule inside ``cooldown_s`` exactly like
:class:`~.incidents.IncidentCapture` (suppressions counted on
``dps_profiles_suppressed_total``, captures on
``dps_profiles_captured_total``). Capture is best-effort everywhere —
a backend without a profiler degrades to a ledger record with
``basis: none``, never a broken serving path.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .journal import journal_event
from .registry import MetricsRegistry, get_registry

__all__ = ["PROFILE_RECORD_FIELDS", "ProfileTrigger"]

#: ``PROFILE_*.json`` ledger record schema: field -> meaning. Pinned
#: BOTH directions against the docs/OBSERVABILITY.md "Profile ledger"
#: table by dpslint's ``catalog_drift.check_profile_record``; must stay
#: a pure literal (the drift engine ``ast.literal_eval``'s it).
PROFILE_RECORD_FIELDS = {
    "id": "record id: prof-<utc stamp>-<pid>-<rule>",
    "created_ts": "unix seconds the capture fired",
    "role": "role of the capturing process (server, bench, demo, ...)",
    "rule": "trigger rule: bench_regression, slo_burn, or goodput_drop",
    "trigger": "the full edge event that fired the capture",
    "window_s": "seconds of device activity the capture bracketed",
    "profile": "attribution artifact: basis, lanes, per-op-class "
               "time_s/events/fraction, total_attributed_s, "
               "trace_wall_s (analysis/device_profile.py)",
    "parse_errors": "per-file attribution failures (traces kept on "
                    "disk when any are fatal)",
    "traces_pruned": "whether the raw capture dir was deleted after a "
                     "successful join",
}


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)


def _default_capture(logdir: str, window_s: float) -> None:
    """Real capture: bracket ``window_s`` seconds of whatever the
    process's devices are doing with the jax profiler."""
    from .profiler import capture
    with capture(logdir):
        time.sleep(window_s)


class ProfileTrigger:
    """Edge-triggered profile capturer with per-rule cooldown dedupe.

    ``capture_fn(logdir, window_s)`` produces the raw dump (injectable:
    tests write synthetic Chrome traces; the default brackets a real
    ``jax.profiler`` window). ``profiles_dir`` receives the
    ``PROFILE_*.json`` ledger records; raw dumps go under
    ``profiles_dir/raw/<id>/`` and are pruned on a successful join.
    """

    def __init__(self, profiles_dir: str, capture_fn=_default_capture,
                 window_s: float = 1.5, cooldown_s: float = 600.0,
                 goodput_drop_threshold: float = 0.5,
                 role: str = "server",
                 registry: MetricsRegistry | None = None,
                 clock=time.time):
        if window_s <= 0 or cooldown_s < 0:
            raise ValueError("window_s must be > 0 and cooldown_s >= 0")
        if not 0.0 < goodput_drop_threshold <= 1.0:
            raise ValueError("goodput_drop_threshold must be in (0, 1]")
        self.profiles_dir = profiles_dir
        self.capture_fn = capture_fn
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.goodput_drop_threshold = float(goodput_drop_threshold)
        self.role = role
        self.clock = clock
        reg = registry or get_registry()
        self._tm_captured = reg.counter("dps_profiles_captured_total")
        self._tm_suppressed = reg.counter("dps_profiles_suppressed_total")
        self._lock = threading.Lock()
        self._last_capture = {}          # guarded by: self._lock
        self._last_goodput: float | None = None  # guarded by: self._lock

    # -- edge sources ------------------------------------------------------

    def on_alert_events(self, events) -> None:
        """``ClusterMonitor.add_listener`` entry: capture on every
        *newly fired* SLO-burn edge (refires and resolves never
        trigger; the cooldown handles storms of distinct fires)."""
        for ev in events:
            if ev.get("state") == "fired" \
                    and str(ev.get("rule", "")).startswith("slo_burn"):
                self.maybe_capture({**dict(ev), "rule": "slo_burn",
                                    "slo_rule": ev.get("rule")})

    def on_bench_verdict(self, verdict: dict) -> str | None:
        """benchwatch edge source: a ``regression`` verdict triggers a
        capture naming the regressed metrics; pass/malformed never
        does."""
        if not isinstance(verdict, dict) \
                or verdict.get("status") != "regression":
            return None
        return self.maybe_capture({
            "rule": "bench_regression",
            "regressions": list(verdict.get("regressions") or ()),
        })

    def observe_goodput(self, fraction, now: float | None = None) -> str | None:
        """Goodput-drop edge source: triggers once when the observed
        productive fraction FALLS THROUGH the threshold (the previous
        observation was at or above it) — a run that starts degraded
        never edges, and a run sitting below re-arms only by climbing
        back over."""
        if not isinstance(fraction, (int, float)) \
                or isinstance(fraction, bool):
            return None
        with self._lock:
            prev = self._last_goodput
            self._last_goodput = float(fraction)
        thr = self.goodput_drop_threshold
        if prev is None or prev < thr or fraction >= thr:
            return None
        return self.maybe_capture({
            "rule": "goodput_drop",
            "fraction": round(float(fraction), 4),
            "previous": round(float(prev), 4),
            "threshold": thr,
        })

    # -- capture -----------------------------------------------------------

    def maybe_capture(self, trigger: dict) -> str | None:
        """Capture + attribute + ledger-append one window unless the
        rule is inside its cooldown. Returns the ledger record path, or
        ``None`` when suppressed."""
        rule = trigger.get("rule") or "unknown"
        now = self.clock()
        with self._lock:
            last = self._last_capture.get(rule)
            if last is not None and now - last < self.cooldown_s:
                self._tm_suppressed.inc()
                return None
            self._last_capture[rule] = now
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(now))
        prof_id = f"prof-{stamp}-{os.getpid()}-{rule}"
        record_path = os.path.join(self.profiles_dir,
                                   f"PROFILE_{stamp}_{rule}.json")
        n = 1
        while os.path.exists(record_path):
            # two same-rule edges inside one second (cooldown_s=0) must
            # not clobber each other's ledger record
            n += 1
            prof_id = f"prof-{stamp}-{os.getpid()}-{rule}-{n}"
            record_path = os.path.join(
                self.profiles_dir, f"PROFILE_{stamp}_{rule}-{n}.json")
        raw_dir = os.path.join(self.profiles_dir, "raw", prof_id)
        os.makedirs(raw_dir, exist_ok=True)
        try:
            self.capture_fn(raw_dir, self.window_s)
        except Exception:  # noqa: BLE001 — degrade, never fail the edge
            pass
        artifact = self._attribute(raw_dir)
        profile = artifact.get("profile") or {}
        parse_errors = artifact.get("parse_errors") or []
        # Prune the raw dump only once the join SUCCEEDED (something was
        # attributed and nothing failed to parse); a failed join keeps
        # the traces as the evidence — the ISSUE-20 uniform-prune fix.
        pruned = False
        if profile.get("basis") not in (None, "none") \
                and not parse_errors:
            from .profiler import prune_capture
            prune_capture(raw_dir)
            pruned = True
            # raw/<id>/ then raw/ if empty — but never ascend past
            # raw/ (os.removedirs would take the empty profiles_dir
            # with it, right before the record write needs it).
            for d in (raw_dir, os.path.dirname(raw_dir)):
                try:
                    os.rmdir(d)
                except OSError:
                    break
        record = {
            "id": prof_id,
            "created_ts": round(now, 3),
            "role": self.role,
            "rule": rule,
            "trigger": trigger,
            "window_s": self.window_s,
            "profile": profile,
            "parse_errors": parse_errors,
            "traces_pruned": pruned,
        }
        _atomic_json(record_path, record)
        self._tm_captured.inc()
        journal_event("profile", id=prof_id, rule=rule, path=record_path)
        return record_path

    def _attribute(self, raw_dir: str) -> dict:
        try:
            from ..analysis.device_profile import attribute_profile
            return attribute_profile(raw_dir)
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            return {"profile": {"basis": "none", "op_classes": {},
                                "total_attributed_s": 0.0,
                                "trace_wall_s": None},
                    "parse_errors": [f"attribution failed: {e}"]}
