"""ClusterMonitor: the parameter server's live cluster-wide health view.

PR 1/PR 3 made every PROCESS observable (registry, snapshot stream,
Prometheus endpoint, traces); the cluster itself remained N disjoint scrape
targets with no central aggregation. This module closes that gap at the one
process that already talks to every worker — the parameter server:

- workers piggyback a compact **health report** on their heartbeat pings and
  pushes (``comms/client.py`` attaches it to the envelope meta,
  capability-gated at registration exactly like delta-fetch/trace-context;
  legacy peers degrade to report-less heartbeats);
- :meth:`ClusterMonitor.ingest` collects those reports,
  :meth:`ClusterMonitor.evaluate` joins them with the store's membership
  state (``MembershipMixin.membership_snapshot`` / ``last_seen`` / the serve
  loop's ``expire_stale_workers`` results via :meth:`note_expired`) into a
  :class:`~.health.ClusterState` and runs the
  :class:`~.health.HealthRuleEngine` over it;
- alert events land in the **flight recorder** (``cluster.alert`` records
  beside the trace spans, so a post-mortem dump carries the alert history),
  increment ``dps_alerts_total{rule,severity}``, ride the snapshot stream as
  ``"kind": "cluster"`` METRICS_JSON records, and are served live as JSON at
  ``GET /cluster`` beside ``/metrics`` (:mod:`.prometheus`), where
  ``cli status`` renders them.

Everything here is observe-only: ingest and evaluation never touch the
store's training state, and every consumer-facing entry point swallows its
own failures — monitoring a server must never be able to break it.
"""

from __future__ import annotations

import math
import os
import threading
import time

from .health import (
    RULE_CATALOG,
    SEVERITIES,
    ClusterState,
    HealthRuleEngine,
    HealthThresholds,
    WorkerState,
)
from .journal import journal_event
from .registry import VALUE_BUCKETS, get_registry

__all__ = [
    "ClusterMonitor",
    "REPORT_FIELDS",
    "get_cluster_monitor",
    "sanitize_report",
    "set_cluster_monitor",
]

#: The wire report schema (docs/OBSERVABILITY.md): every field optional,
#: unknown fields dropped, values coerced/nulled by :func:`sanitize_report`.
#: Non-finite loss/grad values are transmitted as ``None`` + a false
#: ``*_finite`` flag so NaN never has to survive a JSON hop.
REPORT_FIELDS = {
    "step": int,
    "epoch": int,
    "loss": float,
    "grad_norm": float,
    "loss_finite": bool,
    "grad_finite": bool,
    "examples_per_s": float,
    "pipeline_depth": int,
    "reconnects": int,
    "heartbeat_errors": int,
    # Negotiated push codec as the worker currently runs it, e.g.
    # "int4+ef" or "adaptive(topk)+ef" (docs/WIRE_PROTOCOL.md); length-
    # capped on ingest so a hostile peer can't balloon the view.
    "push_codec": str,
    # Productive fraction of this worker's wall so far (telemetry/
    # goodput.py) — the `cli status`/`cli top` goodput column.
    "goodput_fraction": float,
}


def sanitize_report(report) -> dict | None:
    """Coerce a wire health report to the schema; None if unusable.

    Never raises: a garbled report from a buggy/hostile peer degrades to
    "no report", not a failed RPC or a poisoned monitor."""
    if not isinstance(report, dict):
        return None
    out: dict = {}
    for name, cast in REPORT_FIELDS.items():
        v = report.get(name)
        if v is None:
            continue
        try:
            if cast is bool:
                out[name] = bool(v)
            elif cast is str:
                s = str(v)[:32]
                if s:
                    out[name] = s
            elif cast is int:
                if isinstance(v, bool):
                    continue
                out[name] = int(v)
            else:
                v = float(v)
                if not math.isfinite(v):
                    # Belt and braces: a peer that DID ship a NaN through
                    # (python json accepts it) gets normalized to the
                    # null-plus-flag convention.
                    out[name] = None
                    out.setdefault(
                        "loss_finite" if name == "loss" else "grad_finite",
                        False)
                else:
                    out[name] = v
        except (TypeError, ValueError):
            continue
    return out if out else None


class ClusterMonitor:
    """Aggregates worker health reports + membership into alerts and a view.

    Thread-safety: ``ingest`` is called from gRPC handler threads on every
    reporting fetch/push; ``evaluate``/``cluster_view`` from the background
    tick, the HTTP endpoint (possibly many concurrent scrapes), and the
    serve loop. A single monitor lock guards the report table and the
    engine; every critical section is small and touches no store locks
    other than the registration lock inside ``membership_snapshot``.
    """

    def __init__(self, store, thresholds: HealthThresholds | None = None,
                 interval: float = 5.0, role: str = "server",
                 emit_stream: bool = False, registry=None,
                 clock=time.time):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.store = store
        self.interval = float(interval)
        self.role = role
        self.emit_stream = emit_stream
        self.clock = clock
        self.engine = HealthRuleEngine(thresholds)
        self._lock = threading.Lock()
        # Serializes whole evaluation passes (the engine is stateful and
        # the push-delta accounting is read-modify-write); concurrent
        # /cluster scrapes queue here briefly instead of corrupting state.
        self._eval_lock = threading.Lock()
        self._reports: dict[int, tuple[dict, float]] = {}  # guarded by: self._lock
        self._expired_pending: list[int] = []  # guarded by: self._lock
        self._started_ts = clock()
        self._seq = 0  # guarded by: self._lock
        self._last_events: list[dict] = []  # guarded by: self._lock
        # Staleness-spike measurement window, anchored in TIME — (start_ts,
        # accepted_total, rejected_total at start). Rolled at most once per
        # monitor interval, NOT per evaluation: /healthz and /cluster each
        # trigger an evaluation, and a 2 s readiness probe consuming the
        # window per scrape would slice it so thin the spike rule could
        # never accumulate staleness_min_pushes.
        self._push_window: tuple[float, int, int] = \
            (clock(), *self._push_totals())
        # Corrupt-frame refusals (wire CRC, comms/service.py): a running
        # total fed by note_corrupt_frame, windowed exactly like the push
        # deltas so the wire_corrupt alert holds for a full monitor
        # interval rather than the single scrape that drained it.
        self._corrupt_total = 0  # guarded by: self._lock
        self._corrupt_window: tuple[float, int] = (clock(), 0)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Alert edge-event listeners (the remediation engine attaches
        # here, docs/ROBUSTNESS.md): called with each non-empty batch of
        # events after an evaluation pass. Listener failures are
        # swallowed — acting on alerts must not break detecting them.
        self._listeners: list = []  # guarded by: self._lock
        #: Optional RemediationEngine; when set, cluster_view() carries
        #: its state under "remediation" (cli serve --remediate wires it).
        self.remediation = None
        #: Optional sharding state (ps/sharding.py ShardInfo); when set,
        #: cluster_view() carries shard identity, the live shard map
        #: version, and per-replica lag under "sharding" (cli serve
        #: --shard-count wires it) — the surface the remediation engine
        #: and `cli status` read to act on a lagging replica.
        self.sharding = None
        #: Optional ReplicaAutoscaler (telemetry/autoscale.py); when set,
        #: the background tick drives its control loop and
        #: cluster_view() carries its state under "autoscale" (cli serve
        #: --autoscale wires it).
        self.autoscaler = None
        #: Optional SloEvaluator (telemetry/slo.py); when set, every
        #: evaluation pass folds its burn-rate breaches into the
        #: ClusterState (-> slo_burn_fast/slo_burn_slow alerts) and
        #: cluster_view() carries its state under "slo" (cli serve
        #: wires it unless --no-slo).
        self.slo = None
        #: Optional JobManager (ps/tenancy.py); when set, membership and
        #: last_seen come from the UNION of every job's store (global,
        #: strided worker ids), worker rows carry a "job" column, and
        #: cluster_view() serves the per-job block under "jobs" (cli
        #: serve --jobs wires it).
        self.jobs = None
        #: Optional WorkerAutoscaler (telemetry/remediation.py); when
        #: set, the background tick drives its control loop and
        #: cluster_view() carries its state under "worker_autoscale".
        self.worker_autoscaler = None
        #: Optional MemoryMonitor (telemetry/memory.py); when set, every
        #: evaluation pass folds its self-paced sample verdict into the
        #: ClusterState (-> memory_growth alerts) and cluster_view()
        #: carries it under "memory" (cli serve wires it unless
        #: --no-memory-telemetry).
        self.memory = None
        #: Optional ProfileTrigger (telemetry/proftrigger.py); when set,
        #: every evaluation feeds it the fleet-merged goodput fraction so
        #: a goodput-drop edge freezes a device-profile window (cli serve
        #: --profile-triggers). Its slo_burn edge source attaches via
        #: add_listener separately.
        self.profile_trigger = None

        reg = registry or get_registry()
        # Alert counters pre-created for every rule so a scrape shows the
        # full rule vocabulary at zero, not a table that grows as things
        # break (docs/OBSERVABILITY.md).
        self._tm_alerts = {
            rule: reg.counter("dps_alerts_total", rule=rule, severity=sev)
            for rule, (sev, _) in RULE_CATALOG.items()
        }
        self._tm_reports = reg.counter("dps_cluster_reports_total")
        self._tm_workers = reg.gauge("dps_cluster_workers")
        self._tm_active = reg.gauge("dps_cluster_alerts_active")
        # Value-scale (log) buckets — the satellite scheme added for
        # loss/grad-norm magnitudes (telemetry/registry.py VALUE_BUCKETS).
        self._tm_loss = reg.histogram("dps_cluster_report_loss",
                                      buckets=VALUE_BUCKETS)
        self._tm_grad = reg.histogram("dps_cluster_report_grad_norm",
                                      buckets=VALUE_BUCKETS)

    # -- write side ----------------------------------------------------------

    def ingest(self, worker_id, report) -> bool:
        """Record one worker's wire health report. Returns True when the
        report was usable. Never raises (handler hot path)."""
        try:
            wid = int(worker_id)
        except (TypeError, ValueError):
            return False
        clean = sanitize_report(report)
        if clean is None:
            return False
        now = self.clock()
        with self._lock:
            prev = self._reports.get(wid)
            self._reports[wid] = (clean, now)
        self._tm_reports.inc()
        # The worker rebuilds its report at push boundaries but EVERY
        # fetch/push/heartbeat carries the current one, so the same values
        # arrive once per RPC. Only a changed report feeds the value
        # histograms — otherwise their distributions are weighted by each
        # worker's RPC rate (slow-pushing fast-pinging workers dominate),
        # not by actual training observations.
        if prev is None or prev[0] != clean:
            loss, gn = clean.get("loss"), clean.get("grad_norm")
            if isinstance(loss, (int, float)):
                self._tm_loss.observe(loss)
            if isinstance(gn, (int, float)):
                self._tm_grad.observe(gn)
        return True

    def note_corrupt_frame(self, n: int = 1) -> None:
        """Count one refused corrupt push frame (the service calls this
        beside ``dps_wire_corrupt_total``); feeds the ``wire_corrupt``
        health rule on the next evaluation pass."""
        with self._lock:
            self._corrupt_total += int(n)

    def note_expired(self, worker_ids) -> None:
        """Feed membership-expiry results (the serve loop already calls
        ``store.expire_stale_workers()`` every tick; it hands the reaped ids
        here so dead-worker alerts fire on the very next evaluation)."""
        if not worker_ids:
            return
        with self._lock:
            self._expired_pending.extend(int(w) for w in worker_ids)

    # -- evaluation ----------------------------------------------------------

    def _push_totals(self) -> tuple[int, int]:
        stats = getattr(self.store, "stats", None)
        return (int(getattr(stats, "gradients_processed", 0)),
                int(getattr(stats, "gradients_rejected", 0)))

    def _build_state(self, now: float) -> ClusterState:
        # Tenancy: the JobManager unions every job store's membership /
        # last_seen under GLOBAL strided worker ids, so one flat rule
        # engine covers all jobs.
        source = self.jobs if self.jobs is not None else self.store
        try:
            membership = list(source.membership_snapshot())
        except Exception:  # noqa: BLE001 — any store backend, any failure
            membership = []
        last_seen = dict(getattr(source, "last_seen", {}) or {})
        cfg = getattr(self.store, "config", None)
        with self._lock:
            reports = dict(self._reports)
            expired = self._expired_pending
            self._expired_pending = []
            corrupt_total = self._corrupt_total
            # A worker that left membership WITHOUT being expired finished
            # cleanly — drop its report so it neither alerts nor lingers
            # in the view. Expired workers keep theirs (the dead-worker
            # alert's evidence).
            dead = set(self.engine._dead) | set(expired)
            for wid in [w for w in self._reports
                        if w not in membership and w not in dead]:
                del self._reports[wid]
                reports.pop(wid, None)
        workers: dict[int, WorkerState] = {}
        for wid in set(membership) | set(reports) | set(expired):
            rep, rts = reports.get(wid, (None, 0.0))
            workers[wid] = WorkerState(
                worker_id=wid, report=rep, received_ts=rts,
                last_seen=float(last_seen.get(wid, 0.0)),
                in_membership=wid in membership)
        # Push-outcome deltas over the CURRENT window. The store counts
        # accepted pushes in gradients_processed and rejected ones ONLY in
        # gradients_rejected (ps/store.py:_push_async), so the two deltas
        # are independent — no cross-subtraction.
        acc, rej = self._push_totals()
        w_start, acc0, rej0 = self._push_window
        if now - w_start >= self.interval:
            self._push_window = (now, acc, rej)
        c_start, c0 = self._corrupt_window
        if now - c_start >= self.interval:
            self._corrupt_window = (now, corrupt_total)
        slo_breaches: list = []
        if self.slo is not None:
            try:
                slo_breaches = self.slo.evaluate(now)
            except Exception:  # noqa: BLE001 — SLO math must not stop health
                slo_breaches = []
        memory = None
        if self.memory is not None:
            try:
                memory = self.memory.observe(now)
            except Exception:  # noqa: BLE001 — sampling must not stop health
                memory = None
        return ClusterState(
            ts=now,
            global_step=int(getattr(self.store, "global_step", 0)),
            mode=getattr(cfg, "mode", "sync"),
            workers=workers,
            expired=expired,
            pushes_accepted_delta=max(0, acc - acc0),
            pushes_rejected_delta=max(0, rej - rej0),
            corrupt_frames_delta=max(0, corrupt_total - c0),
            slo_breaches=slo_breaches,
            memory=memory)

    def evaluate(self) -> list[dict]:
        """One evaluation pass; returns the new edge events. Serialized
        under the monitor lock (the engine is stateful); callers include
        the background tick, every ``/cluster``/``/healthz`` request, and
        tests."""
        with self._eval_lock:
            now = self.clock()
            state = self._build_state(now)
            with self._lock:
                events = self.engine.evaluate(state)
                active = self.engine.active_alerts()
            for ev in events:
                if ev["state"] in ("fired", "refired"):
                    counter = self._tm_alerts.get(ev["rule"])
                    if counter is not None:
                        counter.inc()
                self._record_event(ev)
                journal_event("alert",
                              **{k: v for k, v in ev.items()
                                 if v is not None})
            self._tm_workers.set(len([w for w in state.workers.values()
                                      if w.in_membership]))
            self._tm_active.set(len(active))
            if events:
                # Listener snapshot under the lock: an unguarded
                # list() raced add_listener's append from another
                # thread (remediation attaches mid-flight).
                with self._lock:
                    self._last_events.extend(events)
                    listeners = list(self._listeners)
                for fn in listeners:
                    try:
                        fn(events)
                    except Exception:  # noqa: BLE001
                        pass
            if self.profile_trigger is not None:
                fracs = [w.report.get("goodput_fraction")
                         for w in state.workers.values() if w.report]
                fracs = [f for f in fracs
                         if isinstance(f, (int, float))
                         and not isinstance(f, bool)]
                if fracs:
                    try:
                        # Fleet-merged productive fraction (mean of the
                        # reporting workers): a fall through the trigger's
                        # threshold captures a profile window.
                        self.profile_trigger.observe_goodput(
                            sum(fracs) / len(fracs), now=now)
                    except Exception:  # noqa: BLE001 — capture is best-effort
                        pass
            self._state_cache = state
            return events

    def add_listener(self, fn) -> None:
        """Subscribe to alert edge events: ``fn(events)`` is called after
        every evaluation pass that produced any (the remediation engine's
        intake; docs/ROBUSTNESS.md)."""
        with self._lock:
            self._listeners.append(fn)

    def _record_event(self, ev: dict) -> None:
        """Drop the alert event into the flight recorder, span-shaped so
        trace dumps and ``/debug/trace`` carry the alert history beside the
        spans a post-mortem already shows."""
        from .trace import get_recorder
        try:
            get_recorder().record({
                "name": "cluster.alert",
                "trace_id": os.urandom(8).hex(),
                "span_id": os.urandom(8).hex(),
                "parent_id": None,
                "ts": ev.get("last_ts") or self.clock(),
                "dur": 0.0,
                "role": self.role,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "attrs": {k: v for k, v in ev.items() if v is not None},
            })
        except Exception:  # noqa: BLE001 — recording must not hurt
            pass

    # -- read side -----------------------------------------------------------

    def active_alerts(self, evaluate: bool = True) -> list[dict]:
        if evaluate:
            self.evaluate()
        with self._lock:
            return [a.to_dict() for a in self.engine.active_alerts()]

    def has_critical(self) -> bool:
        return any(a["severity"] == "critical"
                   for a in self.active_alerts())

    def cluster_view(self, evaluate: bool = True) -> dict:
        """The JSON served at ``GET /cluster`` and embedded in the
        ``"kind": "cluster"`` stream records (docs/OBSERVABILITY.md)."""
        if evaluate:
            self.evaluate()
        now = self.clock()
        state = getattr(self, "_state_cache", None) \
            or self._build_state(now)
        with self._lock:
            alerts = [a.to_dict() for a in self.engine.active_alerts()]
        totals = {s: 0 for s in SEVERITIES}
        for a in alerts:
            totals[a["severity"]] = totals.get(a["severity"], 0) + 1
        rows = []
        for wid, ws in sorted(state.workers.items()):
            row: dict = {"worker": wid, "alive": ws.in_membership
                         and ("dead_worker", wid)
                         not in self.engine._active}
            if self.jobs is not None:
                row["job"] = self.jobs.job_name_of(wid)
            if ws.report:
                row.update(ws.report)
                row["report_age_s"] = round(max(0.0, now - ws.received_ts),
                                            3)
            if ws.last_seen:
                row["last_seen_age_s"] = round(max(0.0, now - ws.last_seen),
                                               3)
            rows.append(row)
        out = {
            "ts": round(now, 3),
            "role": self.role,
            "pid": os.getpid(),
            "mode": state.mode,
            "global_step": state.global_step,
            "uptime_seconds": round(now - self._started_ts, 3),
            "monitor_interval_s": self.interval,
            "workers": rows,
            "alerts": alerts,
            "alerts_total": totals,
        }
        gfs = [r.get("goodput_fraction") for r in rows]
        gfs = [f for f in gfs if isinstance(f, (int, float))
               and not isinstance(f, bool)]
        if gfs:
            # Fleet-merged productive fraction (mean over reporting
            # workers) — the `cli status` header goodput figure.
            out["goodput_fraction"] = round(sum(gfs) / len(gfs), 4)
        # Self-healing surfaces (docs/ROBUSTNESS.md): live quorum-round
        # state from the store and the remediation engine's active/recent
        # actions. Both best-effort — the health view must render even if
        # the healing layer breaks.
        rs = getattr(self.store, "round_status", None)
        if callable(rs) and state.mode == "sync":
            try:
                out["round"] = rs()
            except Exception:  # noqa: BLE001
                pass
        if self.remediation is not None:
            try:
                out["remediation"] = self.remediation.view()
            except Exception:  # noqa: BLE001
                pass
        if self.sharding is not None:
            try:
                out["sharding"] = self.sharding.view()
            except Exception:  # noqa: BLE001
                pass
        if self.autoscaler is not None:
            try:
                out["autoscale"] = self.autoscaler.view()
            except Exception:  # noqa: BLE001
                pass
        if self.slo is not None:
            try:
                out["slo"] = self.slo.view()
            except Exception:  # noqa: BLE001
                pass
        if self.memory is not None:
            try:
                out["memory"] = self.memory.observe(now)
            except Exception:  # noqa: BLE001
                pass
        if self.jobs is not None:
            try:
                out["jobs"] = self.jobs.view()
            except Exception:  # noqa: BLE001
                pass
        if self.worker_autoscaler is not None:
            try:
                out["worker_autoscale"] = self.worker_autoscaler.view()
            except Exception:  # noqa: BLE001
                pass
        return out

    # -- snapshot-stream record ---------------------------------------------

    def emit_once(self, stream=None) -> dict:
        """Emit one ``"kind": "cluster"`` METRICS_JSON record: the cluster
        view plus the edge events since the previous emit. Rides the same
        wire convention as the snapshot stream, so the existing log ETL
        collects cluster history for free
        (``analysis/parse_logs.py:parse_cluster_series``)."""
        from ..utils.metrics import emit_metrics_json
        view = self.cluster_view()
        with self._lock:
            self._seq += 1
            events, self._last_events = self._last_events, []
            payload = {"kind": "cluster", "seq": self._seq, **view,
                       "events": events}
        emit_metrics_json(payload, stream)
        return payload

    # -- background tick -----------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                if self.emit_stream:
                    self.emit_once()
                else:
                    self.evaluate()
            except Exception:  # noqa: BLE001
                pass  # the monitor must never take the server down
            if self.autoscaler is not None:
                try:
                    self.autoscaler.tick()
                except Exception:  # noqa: BLE001
                    pass  # scaling must never take the server down
            if self.worker_autoscaler is not None:
                try:
                    self.worker_autoscaler.tick()
                except Exception:  # noqa: BLE001
                    pass

    def start(self) -> "ClusterMonitor":
        if self._thread is not None:
            raise RuntimeError("monitor already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cluster-monitor")
        self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval))
            self._thread = None
        if final and self.emit_stream:
            try:
                self.emit_once()
            except Exception:  # noqa: BLE001 — shutdown path must not raise
                pass


# -- process-global handle (the HTTP endpoint needs one) ----------------------

_MONITOR: ClusterMonitor | None = None
_MONITOR_LOCK = threading.Lock()


def set_cluster_monitor(monitor: ClusterMonitor | None) -> None:
    """Register the process's monitor for the ``/cluster`` endpoint and the
    ``/healthz`` readiness check (``cli serve`` wires this)."""
    global _MONITOR
    with _MONITOR_LOCK:
        _MONITOR = monitor


def get_cluster_monitor() -> ClusterMonitor | None:
    with _MONITOR_LOCK:
        return _MONITOR
