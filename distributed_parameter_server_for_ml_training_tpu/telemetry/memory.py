"""Memory telemetry: device HBM stats + host RSS, with a leak-slope rule.

Until now memory was entirely unobserved: a worker whose host arrays
leak (a codec residual pile-up, an unbounded history deque) or whose
device allocator creeps toward its HBM limit dies by OOM with no
recorded warning. This module is the sampling half of the
``memory_growth`` health rule (:mod:`.health`):

- **Device HBM** via ``device.memory_stats()`` — present on TPU/GPU
  backends, ``None`` on CPU — exported as
  ``dps_device_memory_bytes{kind=...}`` gauges for the
  :data:`DEVICE_MEMORY_KINDS` it reports.
- **Host RSS** from ``/proc/self/status`` (``VmRSS``/``VmHWM``, stdlib
  only, graceful ``None`` off Linux) exported as
  ``dps_host_rss_bytes``.
- **Leak slope**: a least-squares line through the RSS samples in a
  sliding window; the slope (bytes/s) rides the monitor's
  ``ClusterState.memory`` verdict into the rule engine, which fires
  ``memory_growth`` when sustained growth crosses the threshold.

Attached to :class:`~.cluster.ClusterMonitor` like the SLO evaluator
(``monitor.memory = MemoryMonitor(...)``); ``observe()`` self-paces on
``interval_s`` so the monitor can call it every evaluation tick.
"""

from __future__ import annotations

import time
from collections import deque

from .registry import MetricsRegistry, get_registry

__all__ = [
    "DEVICE_MEMORY_KINDS",
    "DEVICE_MEMORY_METRIC",
    "HOST_RSS_METRIC",
    "MemoryMonitor",
    "read_device_memory",
    "read_host_rss",
]

HOST_RSS_METRIC = "dps_host_rss_bytes"
DEVICE_MEMORY_METRIC = "dps_device_memory_bytes"

#: ``memory_stats()`` keys exported as gauge labels (the stable core of
#: the jax allocator stats; backends may report more — ignored, so a
#: new runtime can't mint unbounded label sets).
DEVICE_MEMORY_KINDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def read_host_rss() -> dict | None:
    """``{"rss_bytes", "peak_rss_bytes"}`` from ``/proc/self/status``
    (``VmRSS`` / ``VmHWM``, kB lines), or None off Linux / on any read
    failure. Stdlib only — no psutil dependency."""
    try:
        with open("/proc/self/status", encoding="ascii",
                  errors="replace") as f:
            text = f.read()
    except OSError:
        return None
    out = {}
    for line in text.splitlines():
        for field, key in (("VmRSS:", "rss_bytes"),
                           ("VmHWM:", "peak_rss_bytes")):
            if line.startswith(field):
                parts = line.split()
                try:
                    out[key] = int(parts[1]) * 1024
                except (IndexError, ValueError):
                    pass
    return out if "rss_bytes" in out else None


def read_device_memory(device=None) -> dict | None:
    """One device's ``memory_stats()`` restricted to
    :data:`DEVICE_MEMORY_KINDS` plus the device kind, or None when the
    backend has no allocator stats (CPU) or jax is unavailable."""
    try:
        import jax
        if device is None:
            devices = jax.local_devices()
            if not devices:
                return None
            device = devices[0]
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backend-optional surface
        return None
    if not isinstance(stats, dict):
        return None
    out = {k: int(stats[k]) for k in DEVICE_MEMORY_KINDS
           if isinstance(stats.get(k), int)}
    if not out:
        return None
    out["device_kind"] = str(getattr(device, "device_kind", "unknown"))
    return out


def _slope_bytes_per_s(samples) -> float | None:
    """Least-squares slope through ``[(ts, bytes), ...]``; None below
    two distinct timestamps."""
    n = len(samples)
    if n < 2:
        return None
    t0 = samples[0][0]
    xs = [t - t0 for t, _ in samples]
    ys = [float(v) for _, v in samples]
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    denom = sum((x - mean_x) ** 2 for x in xs)
    if denom <= 0:
        return None
    return sum((x - mean_x) * (y - mean_y)
               for x, y in zip(xs, ys)) / denom


class MemoryMonitor:
    """Periodic sampler + windowed leak-slope detector.

    ``rss_fn`` / ``device_fn`` are injectable for tests (fake clocks and
    seeded leaks); real callers take the defaults. Not thread-safe by
    itself — the cluster monitor calls ``observe`` under its own lock,
    the same discipline as the rule engine.
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 interval_s: float = 5.0, window_s: float = 120.0,
                 clock=time.time, rss_fn=read_host_rss,
                 device_fn=read_device_memory):
        if interval_s <= 0 or window_s <= 0:
            raise ValueError("interval_s and window_s must be > 0")
        reg = registry or get_registry()
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        self.clock = clock
        self._rss_fn = rss_fn
        self._device_fn = device_fn
        # Literal names at the registration sites (== HOST_RSS_METRIC /
        # DEVICE_MEMORY_METRIC): the metric<->doc drift pin extracts
        # registrations textually.
        self._tm_rss = reg.gauge("dps_host_rss_bytes")
        self._tm_device = {
            k: reg.gauge("dps_device_memory_bytes", kind=k)
            for k in DEVICE_MEMORY_KINDS
        }
        self._samples: deque = deque()  # (ts, rss_bytes)
        self._last_sample_ts: float | None = None
        self._last: dict = {}

    def sample(self, now: float | None = None) -> dict:
        """Take one sample unconditionally; returns the verdict."""
        now = self.clock() if now is None else now
        self._last_sample_ts = now
        host = None
        try:
            host = self._rss_fn()
        except Exception:  # noqa: BLE001 — sampling must never raise out
            host = None
        device = None
        try:
            device = self._device_fn()
        except Exception:  # noqa: BLE001 — sampling must never raise out
            device = None
        if host:
            self._tm_rss.set(host["rss_bytes"])
            self._samples.append((now, host["rss_bytes"]))
            cutoff = now - self.window_s
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
        if device:
            for k in DEVICE_MEMORY_KINDS:
                if k in device:
                    self._tm_device[k].set(device[k])
        self._last = self._verdict(host, device)
        return self._last

    def observe(self, now: float | None = None) -> dict:
        """Self-paced sample: re-samples only once per ``interval_s``,
        otherwise returns the last verdict (the monitor calls this every
        evaluation tick)."""
        now = self.clock() if now is None else now
        if self._last_sample_ts is None \
                or now - self._last_sample_ts >= self.interval_s:
            return self.sample(now)
        return self._last

    def _verdict(self, host, device) -> dict:
        span = 0.0
        if len(self._samples) >= 2:
            span = self._samples[-1][0] - self._samples[0][0]
        return {
            "rss_bytes": (host or {}).get("rss_bytes"),
            "peak_rss_bytes": (host or {}).get("peak_rss_bytes"),
            "growth_bytes_per_s": _slope_bytes_per_s(self._samples),
            "window_span_s": round(span, 3),
            "samples": len(self._samples),
            "device": device,
        }
