"""Distributed tracing + crash-safe flight recorder.

The snapshot stream (PR 1) answers "how fast is each process, over time";
it cannot answer "which phase of WHICH STEP made this worker slow" — local
compute, fetch wait against a stale server, wire codec, or server-side
apply contention — and nothing survives a SIGKILL'd process to say what it
was doing. This module is that missing causal layer:

- **Trace context** — every worker step opens a root span with a fresh
  ``trace_id``; child spans (fetch wait, compute, codec, RPC attempts)
  nest via a thread-local context stack, and the context crosses the wire
  to the server (``comms/wire.py`` v2 header field + RPC envelope meta,
  capability-gated at registration) so server-side push/fetch/apply spans
  attach causally to the worker step that caused them.
- **Flight recorder** — a bounded in-memory ring buffer of finished spans
  per process. Recording is a deque append under a small lock; the buffer
  dumps its tail as JSON on SIGTERM / unhandled exception / atexit
  (:func:`install_shutdown_hooks`) and on demand via the ``/debug/trace``
  endpoint (:mod:`.prometheus`), so a hung or killed process leaves a
  post-mortem.
- **Analysis** lives in ``analysis/traces.py``: trace assembly (join
  worker+server span dumps by trace_id into per-step trees), Chrome
  trace-event / Perfetto export, and critical-path straggler attribution.

Tracing is OFF by default: every span site costs one module-global check
plus a shared no-op context manager (~100 ns), so the always-on metrics
overhead budget (docs/OBSERVABILITY.md, the <2% tier-1 guard) is
untouched. Enable with ``--trace`` (CLI) or :func:`enable_tracing`.

Span timestamps are ``time.time()`` (wall clock — comparable across the
processes of one host, which is what the multi-process demo assembles);
durations are ``perf_counter`` deltas (monotonic). Span names come from
:data:`SPAN_CATALOG`; ``tests/test_docs_drift.py`` pins catalog, call
sites, and docs/OBSERVABILITY.md to each other.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from time import perf_counter as _pc
from typing import NamedTuple

__all__ = [
    "SPAN_CATALOG",
    "TraceContext",
    "FlightRecorder",
    "enable_tracing",
    "disable_tracing",
    "trace_enabled",
    "get_recorder",
    "trace_span",
    "current_context",
    "current_wire_trace",
    "use_wire_context",
    "install_shutdown_hooks",
    "add_shutdown_flush",
    "remove_shutdown_flush",
]

#: Canonical span names -> one-line meaning. The single source of truth:
#: every ``trace_span(...)`` call site uses a key from this table, and
#: docs/OBSERVABILITY.md documents exactly these names (both pinned by
#: ``tests/test_docs_drift.py``).
SPAN_CATALOG = {
    "worker.step": "one PS-worker loop iteration (root; attrs: worker, "
                   "step, epoch; epoch_open=True for the epoch's opening "
                   "fetch-only entry)",
    "worker.fetch_wait": "training thread blocked on a params fetch "
                         "(serial fetch or pipeline await)",
    "worker.push_wait": "training thread blocked on a gradient push "
                        "(serial push or pipeline submit backpressure)",
    "worker.compute": "compiled grad-step call (synchronized on the "
                      "result while tracing, so device time is "
                      "attributed here, not to the first consumer)",
    "worker.codec": "worker-side codec work (attr stage=encode|decode: "
                    "flatten+compress before push / decompress+unflatten "
                    "after fetch)",
    "worker.eval": "per-epoch full test-set eval (root)",
    "worker.reconnect": "session-resume state machine after a lost "
                        "server connection (root; attrs attempts, "
                        "new_worker_id, inflight=repushed|discarded|none, "
                        "outcome=gave_up on failure)",
    "pipeline.comms": "overlapped comms-thread item: push + prefetch, "
                      "parented under the submitting step",
    "rpc.client": "one client RPC attempt (attr rpc=<name>; failures "
                  "recorded with error attr)",
    "rpc.server": "server-side handler span (attr rpc=<name>), parented "
                  "on the wire-propagated worker context",
    "rpc.replica_serve": "replica serving one client fetch/infer from "
                         "cached bytes (local root; attr shard) — the "
                         "serve-tier exemplar source",
    "store.push": "store push incl. codec decode (attrs backend, "
                  "accepted)",
    "store.fetch": "store fetch incl. codec encode (attrs backend, "
                   "not_modified when delta-gated)",
    "store.apply": "parameter update apply (sync round aggregate+apply "
                   "or async staleness-weighted apply; attrs backend, "
                   "staleness/weight in async mode)",
    "trainer.step": "SPMD sync-trainer step (root; attr mode=sync)",
}


class TraceContext(NamedTuple):
    """Identity of one span: (trace_id, span_id, parent span_id|None)."""

    trace_id: str
    span_id: str
    parent_id: str | None = None


def _new_id() -> str:
    return os.urandom(8).hex()


class FlightRecorder:
    """Bounded ring buffer of finished spans (dicts), oldest evicted first.

    A record is one lock'd deque append — cheap enough to leave on for a
    whole run; the bound means a week-long process still holds only the
    tail, which is exactly what a post-mortem wants (what was it doing
    *when it died*, not in hour one).
    """

    def __init__(self, maxlen: int = 4096, role: str = "process"):
        if maxlen < 1:
            raise ValueError(f"maxlen must be >= 1, got {maxlen}")
        self.maxlen = int(maxlen)
        self.role = role
        self._spans: deque = deque(maxlen=self.maxlen)  # guarded by: self._lock
        self._lock = threading.Lock()
        self._dropped = 0  # guarded by: self._lock

    def record(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) == self.maxlen:
                self._dropped += 1
            self._spans.append(span)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def tail(self, n: int | None = None) -> list[dict]:
        """Most recent ``n`` spans (all when None), oldest first."""
        with self._lock:
            spans = list(self._spans)
        if n is None:
            return spans
        n = int(n)
        return spans[-n:] if n > 0 else []  # [-0:] would mean "all"

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def dump_payload(self, reason: str = "on_demand",
                     n: int | None = None) -> dict:
        """JSON-ready post-mortem record (the /debug/trace body and the
        crash-dump file content share this shape)."""
        spans = self.tail(n)
        with self._lock:
            dropped = self._dropped
        return {
            "kind": "flight_recorder",
            "role": self.role,
            "pid": os.getpid(),
            "reason": reason,
            "dumped_at": round(time.time(), 6),
            "buffer_size": self.maxlen,
            "dropped_spans": dropped,
            "span_count": len(spans),
            "spans": spans,
        }

    def dump_to_dir(self, dump_dir: str, reason: str) -> str:
        """Write the tail as ``trace-<role>-<pid>-<reason>.json``; returns
        the path. One file per (process, reason): a SIGTERM dump is never
        clobbered by the atexit dump that follows it."""
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(
            dump_dir, f"trace-{self.role}-{os.getpid()}-{reason}.json")
        payload = self.dump_payload(reason)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # crash mid-write never leaves torn JSON
        return path


# -- process-global state ----------------------------------------------------

_RECORDER = FlightRecorder()
_ENABLED = False
_TLS = threading.local()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def trace_enabled() -> bool:
    return _ENABLED


def enable_tracing(buffer: int | None = None,
                   role: str | None = None) -> FlightRecorder:
    """Turn span recording on (idempotent). ``buffer`` resizes the ring
    (existing tail kept); ``role`` labels this process's spans/dumps."""
    global _ENABLED, _RECORDER
    if buffer is not None and int(buffer) != _RECORDER.maxlen:
        fresh = FlightRecorder(maxlen=int(buffer), role=_RECORDER.role)
        for s in _RECORDER.tail():
            fresh.record(s)
        _RECORDER = fresh
    if role is not None:
        _RECORDER.role = role
    _ENABLED = True
    return _RECORDER


def disable_tracing() -> None:
    global _ENABLED
    _ENABLED = False


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_context() -> TraceContext | None:
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def current_wire_trace() -> dict | None:
    """Current context as the wire header field ``{"trace_id", "span_id"}``
    (docs/WIRE_PROTOCOL.md), or None when tracing is off / no span open."""
    if not _ENABLED:
        return None
    ctx = current_context()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


class _NullSpan:
    """Shared no-op for disabled tracing: the entire cost of a disabled
    span site is one global check + this allocation-free enter/exit."""

    __slots__ = ()
    ctx = None

    @property
    def attrs(self) -> dict:
        # Fresh throwaway per access: call sites may write into it
        # (``sp.attrs["accepted"] = ok``) and a shared dict would leak
        # state between unrelated disabled spans.
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Live span: pushes its context for the body, records on exit.

    ``__enter__`` returns the span itself — call sites may mutate
    ``.attrs`` before exit (e.g. ``sp.attrs["accepted"] = ok``) and read
    ``.ctx`` for explicit propagation (the comms pipeline captures it at
    submit time)."""

    __slots__ = ("name", "attrs", "ctx", "_root", "_ts", "_t0")

    def __init__(self, name: str, root: bool, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._root = root

    def __enter__(self):
        parent = None if self._root else current_context()
        if parent is None:
            self.ctx = TraceContext(_new_id(), _new_id(), None)
        else:
            self.ctx = TraceContext(parent.trace_id, _new_id(),
                                    parent.span_id)
        _stack().append(self.ctx)
        self._ts = time.time()
        self._t0 = _pc()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = _pc() - self._t0
        st = _stack()
        if st and st[-1] is self.ctx:
            st.pop()
        elif self.ctx in st:  # misnested exit: drop ours, keep the rest
            st.remove(self.ctx)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        span = {
            "name": self.name,
            "trace_id": self.ctx.trace_id,
            "span_id": self.ctx.span_id,
            "parent_id": self.ctx.parent_id,
            "ts": self._ts,
            "dur": dur,
            "role": _RECORDER.role,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.attrs:
            span["attrs"] = self.attrs
        _RECORDER.record(span)
        return False


def trace_span(name: str, root: bool = False, **attrs):
    """Context manager recording one flight-recorder span around the body.

    No-op (shared singleton, ~100 ns) when tracing is disabled. ``root``
    opens a fresh ``trace_id`` regardless of the current context (worker
    step / trainer step roots); otherwise the span parents on the
    thread-local current context (or becomes a root if there is none).
    """
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, root, attrs)


class _WireCtx:
    """Adopt a wire-propagated ``{"trace_id", "span_id"}`` as the current
    context, so server-side spans parent on the originating worker span."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: TraceContext):
        self._ctx = ctx

    def __enter__(self):
        _stack().append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        st = _stack()
        if st and st[-1] is self._ctx:
            st.pop()
        return False


def use_wire_context(trace_field) -> "_WireCtx | _NullSpan":
    """Context manager entering a remote peer's context. Accepts the wire
    header field dict; anything malformed (or tracing off) degrades to a
    no-op — a garbled trace field must never fail an RPC."""
    if not _ENABLED or not isinstance(trace_field, dict):
        return _NULL_SPAN
    tid, sid = trace_field.get("trace_id"), trace_field.get("span_id")
    if (not isinstance(tid, str) or not isinstance(sid, str)
            or not 0 < len(tid) <= 64 or not 0 < len(sid) <= 64):
        return _NULL_SPAN
    return _WireCtx(TraceContext(tid, sid, None))


# -- crash-safe shutdown: SIGTERM / unhandled fault / atexit -----------------

_shutdown_lock = threading.Lock()
_flush_fns: list = []
_exit_hooks_installed = False
_sigterm_installed = False
_dump_dir: str | None = None
_prev_sigterm = None
_prev_excepthook = None


def add_shutdown_flush(fn) -> None:
    """Register ``fn()`` to run at SIGTERM/atexit/unhandled-fault (e.g.
    the snapshot emitter's final flush, so a terminating process's tail
    interval is never silently dropped). Idempotent per callable."""
    with _shutdown_lock:
        if fn not in _flush_fns:
            _flush_fns.append(fn)


def remove_shutdown_flush(fn) -> None:
    with _shutdown_lock:
        if fn in _flush_fns:
            _flush_fns.remove(fn)


def _run_shutdown(reason: str) -> None:
    """Dump the recorder tail (if a dump dir is configured and anything
    was recorded) and run every registered flush. Never raises: this runs
    on the way DOWN, where a secondary failure would mask the first."""
    with _shutdown_lock:
        fns = list(_flush_fns)
        dump_dir = _dump_dir
    if dump_dir and len(_RECORDER):
        try:
            path = _RECORDER.dump_to_dir(dump_dir, reason)
            print(f"flight recorder: dumped {len(_RECORDER)} spans -> "
                  f"{path} ({reason})", file=sys.stderr, flush=True)
        except Exception:  # noqa: BLE001 — crash dump is best-effort
            pass
    for fn in fns:
        try:
            fn()
        except Exception:  # noqa: BLE001 — one bad hook can't block the rest
            pass


def _sigterm_handler(signum, frame):
    _run_shutdown("sigterm")
    if callable(_prev_sigterm):
        _prev_sigterm(signum, frame)
        return
    # Default disposition would have killed us with no cleanup; the dump
    # and flushes above ARE the cleanup. Exit hard rather than unwinding:
    # raising SystemExit from a signal handler tears down live jax/XLA
    # worker threads mid-computation, which segfaults the interpreter on
    # the way out (observed: rc -11 instead of a clean exit). 143 = 128 +
    # SIGTERM, the status a shell reports for a TERM'd process.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(143)


def _excepthook(exc_type, exc, tb):
    _run_shutdown("unhandled_exception")
    (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)


def install_shutdown_hooks(dump_dir: str | None = None,
                           role: str | None = None) -> None:
    """Install the SIGTERM handler, ``sys.excepthook`` wrapper, and atexit
    hook (once per process; later calls just update ``dump_dir``/role).

    Safe from non-main threads: ``signal.signal`` only works on the main
    thread, so there the SIGTERM leg is skipped (atexit/excepthook still
    fire) — in-process CLI tests run command bodies on daemon threads.
    """
    global _exit_hooks_installed, _sigterm_installed, _dump_dir, \
        _prev_sigterm, _prev_excepthook
    with _shutdown_lock:
        if dump_dir is not None:
            _dump_dir = dump_dir
        if role is not None:
            _RECORDER.role = role
        install_exit = not _exit_hooks_installed
        _exit_hooks_installed = True
        # The SIGTERM leg is tracked SEPARATELY: a first call from a
        # non-main thread must not latch it off for the process — the
        # next main-thread call still gets to install the handler.
        try_sigterm = not _sigterm_installed
    if try_sigterm:
        try:
            prev = signal.signal(signal.SIGTERM, _sigterm_handler)
        except ValueError:
            pass  # not the main thread; retry on a later call
        else:
            with _shutdown_lock:
                _sigterm_installed = True
            _prev_sigterm = prev
    if install_exit:
        _prev_excepthook, sys.excepthook = sys.excepthook, _excepthook
        atexit.register(_run_shutdown, "atexit")
