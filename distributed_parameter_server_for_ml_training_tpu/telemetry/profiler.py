"""Profiler capture + achieved-FLOPs accounting for the hot loop.

``--profile-dir`` has always dumped raw ``jax.profiler`` traces that
nobody parsed; this module is the write half of the perf observatory
(the read half is :mod:`..analysis.device_profile`):

- :func:`capture` — the capture bracket (same
  ``jax.profiler.start_trace`` / ``stop_trace`` pair as
  ``utils.tracing.trace``, re-exported here so profiler consumers have
  one import surface) plus dump discovery.
- :func:`compiled_cost` — ``lowered.compile().cost_analysis()`` flops +
  bytes for ONE compiled step. Always compile the SINGLE step for this
  (not a scanned window): XLA reports the whole program, and a
  80-step scan would over-state per-step flops by 80x.
- :func:`mfu` — achieved / peak FLOPs. Peak comes from
  :data:`PEAK_FLOPS_BY_KIND` keyed on ``jax.devices()[0].device_kind``;
  an unknown kind yields ``None`` rather than an invented number — an
  MFU against a guessed peak is worse than no MFU.
"""

from __future__ import annotations

import glob
import os

from ..utils.tracing import trace as _trace

__all__ = [
    "PEAK_FLOPS_BY_KIND",
    "capture",
    "compiled_cost",
    "find_profile_dumps",
    "mfu",
    "peak_flops",
    "prune_capture",
]

#: device_kind -> peak dense-matmul FLOP/s at the precision the training
#: step actually runs (bf16 on TPU, fp32 on CPU-like hosts has no
#: meaningful peak so CPU kinds are deliberately absent). Sources: cloud
#: TPU spec sheets (v4 275 TF bf16; v5e 197 TF bf16; v5p 459 TF bf16).
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275.0e12,
    "TPU v5 lite": 197.0e12,
    "TPU v5e": 197.0e12,
    "TPU v5p": 459.0e12,
}


def capture(logdir: str):
    """Profiler capture bracket: ``with capture(dir): hot_loop()``.

    Creates ``logdir`` and brackets the body with
    ``jax.profiler.start_trace``/``stop_trace``; the dump lands under
    ``logdir/plugins/profile/<timestamp>/`` (one xplane.pb + one
    Chrome-format ``*.trace.json.gz`` per host)."""
    os.makedirs(logdir, exist_ok=True)
    return _trace(logdir)


def find_profile_dumps(logdir: str) -> list[str]:
    """Chrome-trace files under a capture dir, newest run first.

    Accepts the capture root (scans ``plugins/profile/*/``), a specific
    run dir, or a direct path to one trace file."""
    if os.path.isfile(logdir):
        return [logdir]
    found: list[str] = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        found += glob.glob(os.path.join(
            logdir, "plugins", "profile", "*", pat))
        found += glob.glob(os.path.join(logdir, pat))
    # Newest capture first: the run timestamp is the parent dir name.
    return sorted(set(found), key=lambda p: (os.path.dirname(p), p),
                  reverse=True)


def prune_capture(logdir: str) -> list[str]:
    """Delete the raw profiler dump under a capture dir once attribution
    has JOINED it into an artifact; returns the paths removed.

    The capture dirs are big (one xplane.pb + one multi-MB Chrome trace
    per host per capture) and, before this, only the codec-profile
    experiment cleaned up after itself — every other capture path
    (``cli perf profile``, bench, the trigger engine) left them on disk
    forever. Callers prune ONLY after a successful attribution: a
    failed parse keeps the raw dump as the evidence. Removes the whole
    ``plugins/`` capture tree plus any direct ``*.trace.json[.gz]``
    files; never raises (a half-pruned dir degrades to stray files, not
    a failed capture)."""
    import shutil

    removed: list[str] = []
    if os.path.isfile(logdir):
        try:
            os.remove(logdir)
            return [logdir]
        except OSError:
            return []
    plugins = os.path.join(logdir, "plugins")
    if os.path.isdir(plugins):
        shutil.rmtree(plugins, ignore_errors=True)
        if not os.path.exists(plugins):
            removed.append(plugins)
    for pat in ("*.trace.json.gz", "*.trace.json", "*.xplane.pb"):
        for path in glob.glob(os.path.join(logdir, pat)):
            try:
                os.remove(path)
                removed.append(path)
            except OSError:
                pass
    return removed


def _as_cost_dict(cost) -> dict:
    """``cost_analysis()`` returns a dict on current jax, a list of one
    dict on older releases, and None on backends that don't implement
    it; normalize to a (possibly empty) dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    return cost if isinstance(cost, dict) else {}


def compiled_cost(compiled) -> dict:
    """``{"flops": float|None, "bytes_accessed": float|None}`` from a
    ``Compiled`` object (``jax.jit(f).lower(*args).compile()``). Never
    raises: backends without cost analysis report None values."""
    try:
        cost = _as_cost_dict(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — backend-optional surface
        cost = {}
    flops = cost.get("flops")
    by = cost.get("bytes accessed", cost.get("bytes_accessed"))
    return {
        "flops": float(flops) if isinstance(flops, (int, float)) else None,
        "bytes_accessed": float(by) if isinstance(by, (int, float))
        else None,
    }


def peak_flops(device_kind: str) -> float | None:
    """Peak FLOP/s for a device kind, or None when unknown (CPU, new
    hardware this table hasn't met) — callers degrade to mfu=None."""
    return PEAK_FLOPS_BY_KIND.get(str(device_kind))


def mfu(flops_per_step: float | None, steps_per_s: float | None,
        device_kind: str, n_devices: int = 1) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s over peak. None when any
    input is unavailable (no cost analysis, unknown device kind, no
    measured rate) — never a made-up number."""
    peak = peak_flops(device_kind)
    if not peak or not flops_per_step or not steps_per_s:
        return None
    if n_devices < 1:
        return None
    return (flops_per_step * steps_per_s) / (peak * n_devices)
