"""Prometheus text exposition (format 0.0.4) over stdlib HTTP.

Serves ``GET /metrics`` from the same process as the gRPC parameter server
(``cli serve --metrics-port N``) — the pull-based complement to the
push-style snapshot stream: snapshots feed the log-scrape ETL the reference
already had; this endpoint feeds anything Prometheus-shaped without log
plumbing. ``GET /healthz`` answers 200 with a tiny JSON body, giving
load-balancer health checks the capability the reference's intended-but-
dead health_check_loop (worker.py:112-119, SURVEY.md quirk 8) never
delivered server-side.

No third-party dependency: the renderer writes the text format directly and
``ThreadingHTTPServer`` (stdlib) serves it. Scrapes read instrument
snapshots under each instrument's own lock — consistent per instrument,
lock-free across instruments, never blocking a hot path for the whole
scrape.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import Histogram, MetricsRegistry, get_registry

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


def _name(n: str) -> str:
    return _NAME_OK.sub("_", n)


def _labels(labels: dict, extra: str = "") -> str:
    parts = [f'{_LABEL_OK.sub("_", k)}="{v}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    return repr(v) if isinstance(v, float) and not v.is_integer() \
        else str(int(v))


def render_prometheus(registry: MetricsRegistry | None = None) -> str:
    """Registry -> Prometheus text format. Histograms render cumulative
    ``_bucket{le=...}`` series (the registry stores per-bucket counts;
    the cumulative sum happens here), plus ``_sum``/``_count``."""
    registry = registry or get_registry()
    lines: list[str] = []
    seen_types: set[str] = set()
    for inst in registry.collect():
        name = _name(inst.name)
        if name not in seen_types:
            seen_types.add(name)
            lines.append(f"# TYPE {name} {inst.kind}")
        if isinstance(inst, Histogram):
            snap = inst.snapshot()
            cum = 0
            for le, c in zip(snap["le"], snap["counts"]):
                cum += c
                extra = 'le="%s"' % _fmt(le)
                lines.append(f"{name}_bucket{_labels(inst.labels, extra)} "
                             f"{cum}")
            cum += snap["counts"][-1]
            inf_extra = 'le="+Inf"'
            lines.append(f"{name}_bucket{_labels(inst.labels, inf_extra)} "
                         f"{cum}")
            lines.append(f"{name}_sum{_labels(inst.labels)} "
                         f"{_fmt(snap['sum'])}")
            lines.append(f"{name}_count{_labels(inst.labels)} "
                         f"{snap['count']}")
        else:
            lines.append(f"{name}{_labels(inst.labels)} "
                         f"{_fmt(inst.value)}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set by start_metrics_server

    def do_GET(self):  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        status = 200
        if path == "/metrics":
            body = render_prometheus(self.registry).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/metrics.json":
            # The registry snapshot verbatim (docs/OBSERVABILITY.md):
            # NON-cumulative bucket counts + exemplars, i.e. the exact
            # shape merge_histograms consumes. The fleet collector
            # prefers this over re-deriving it from the lossier
            # cumulative text rendering.
            body = json.dumps(self.registry.snapshot()).encode()
            ctype = "application/json"
        elif path == "/healthz":
            # Readiness semantics (docs/OBSERVABILITY.md): with a cluster
            # monitor attached, an active CRITICAL alert flips the probe
            # to 503 with a body naming the offenders — a k8s/LB can now
            # rotate a server whose cluster is on fire, not just one whose
            # HTTP thread died. A broken monitor degrades to 200 (losing
            # the readiness signal must not take down serving traffic).
            payload: dict = {"ok": True}
            from .cluster import get_cluster_monitor
            monitor = get_cluster_monitor()
            if monitor is not None:
                try:
                    critical = [
                        {"rule": a["rule"], "worker": a["worker"],
                         "message": a["message"]}
                        for a in monitor.active_alerts()
                        if a["severity"] == "critical"]
                    if critical:
                        status = 503
                        payload = {"ok": False, "critical": critical}
                except Exception as e:  # noqa: BLE001
                    payload = {"ok": True, "monitor_error": repr(e)}
            body = json.dumps(payload).encode()
            ctype = "application/json"
        elif path == "/cluster":
            # Live cluster health view (docs/OBSERVABILITY.md): the
            # ClusterMonitor's worker table + active alerts, evaluated
            # fresh per request; `cli status` renders this payload.
            from .cluster import get_cluster_monitor
            monitor = get_cluster_monitor()
            if monitor is None:
                status = 404
                body = json.dumps(
                    {"error": "no cluster monitor in this process "
                              "(serve runs one unless --no-health-monitor)"}
                ).encode()
            else:
                try:
                    body = json.dumps(monitor.cluster_view()).encode()
                except Exception as e:  # noqa: BLE001
                    status = 500
                    body = json.dumps({"error": repr(e)}).encode()
            ctype = "application/json"
        elif path == "/debug/trace":
            # On-demand flight-recorder dump (docs/OBSERVABILITY.md): the
            # same payload a SIGTERM post-mortem writes, served live.
            # ``?n=100`` limits to the most recent N spans.
            from .trace import get_recorder, trace_enabled
            n = None
            for part in query.split("&"):
                if part.startswith("n="):
                    try:
                        n = max(0, int(part[2:]))
                    except ValueError:
                        pass
            payload = get_recorder().dump_payload(reason="on_demand", n=n)
            payload["enabled"] = trace_enabled()
            body = json.dumps(payload).encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stdout —
        pass                       # METRICS_JSON lines live there


def start_metrics_server(registry: MetricsRegistry | None = None,
                         port: int = 0, addr: str = "0.0.0.0"
                         ) -> tuple[ThreadingHTTPServer, int]:
    """Start the exposition endpoint on a daemon thread.

    Returns (server, bound_port) — pass ``port=0`` to pick a free port
    (tests), a fixed one for real deployments. Callers own shutdown
    (``server.shutdown()``).
    """
    handler = type("BoundMetricsHandler", (_MetricsHandler,),
                   {"registry": registry or get_registry()})
    server = ThreadingHTTPServer((addr, port), handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="telemetry-http").start()
    return server, server.server_address[1]
