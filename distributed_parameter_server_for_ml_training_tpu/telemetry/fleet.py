"""Fleet observatory: cross-process metrics aggregation plane.

Every observability surface before this PR was per-process: ``/metrics``
and ``/cluster`` describe ONE process and ``cli status`` polls exactly
one URL — unusable for a fleet of sharded primaries, delta-fed replicas
and supervised workers (and exactly the gap ACE-Sync's cloud-edge
hierarchy calls out: hierarchical tiers demand tier-aware merged
visibility, not N disjoint scrapes). :class:`FleetCollector` closes it:

- **Discovery.** Explicit ``--targets`` seed the scrape set; every
  scraped ``/cluster`` view then contributes more processes — shard
  peers and announced replicas (a replica that announces a ``metrics``
  address becomes a scrape target), supervisor children and job
  membership (inventory tiers; they have no metrics endpoint of their
  own and are reported from the primaries' views).
- **Ring TSDB.** Per-target, per-series fixed-depth rings
  (``collections.deque(maxlen=ring_depth)``) — bounded memory, no
  external deps, enough history for rates and sparklines.
- **Honest rollups.** Counters roll up as sums + ring-delta rate sums;
  gauges as sum/min/max/mean; histograms via
  :func:`..telemetry.stats.merge_histograms` — bucket-EXACT because the
  bucket schemes are pinned in ``registry.py``, so fleet p50/p95/p99
  equal the percentiles of the unioned observations (property-tested).
  Exemplars ride along: a fleet p99 spike carries the trace ids of
  recent slow requests (``analysis/fleet_series.py`` joins them against
  flight-recorder dumps).
- **Partial-fleet tolerance.** Per-target timeouts; a dead target marks
  its series stale (excluded from rollups, flagged in the view) and
  NEVER blocks the tick. ``dps_fleet_scrape_errors_total{target}`` is
  minted lazily per target and removed when a discovered target drains
  — the same series-lifecycle discipline as ``dps_replica_lag_*``
  (ps/sharding.py).
- **Fleet SLO burn.** The multi-window burn-rate recipe (telemetry/slo)
  re-evaluated over the MERGED series — a latency breach that only
  shows up in the union (each shard individually under threshold, the
  fleet over it) is visible here and nowhere else.

Runs as a standalone ``cli observe`` process — off every hot path, and
it survives primary restarts because it holds no connection state, just
URLs it re-scrapes each tick. ``start_fleet_server`` exposes ``GET
/fleet`` (the full view), plus ``/metrics`` for the collector's own
instruments. ``cli top`` renders the view live; docs/OBSERVABILITY.md
("Fleet observatory") documents the payload schema and the rollup
semantics table pinned to :data:`FLEET_ROLLUP_FIELDS`.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import LATENCY_BUCKETS_S, MetricsRegistry, get_registry
from .slo import SloEvaluator, default_objectives
from .stats import histogram_quantile, merge_histograms

__all__ = [
    "FLEET_ROLLUP_FIELDS",
    "FleetCollector",
    "parse_prometheus_text",
    "start_fleet_server",
]

#: Rollup-field catalog: every field a ``/fleet`` rollup entry may carry,
#: with its merge semantics. Pure literal — dpslint's ``doc-drift`` pass
#: (tools/dpslint/catalog_drift.py, check ``fleet-rollup-fields``) pins
#: this table to the "Rollup semantics" section of docs/OBSERVABILITY.md
#: in both directions.
FLEET_ROLLUP_FIELDS = {
    "sum": "counters/gauges/histograms: values summed over fresh targets",
    "rate_per_s": "counters: ring-delta rates summed over fresh targets",
    "min": "gauges: minimum latest value across fresh targets",
    "max": "gauges: maximum latest value across fresh targets",
    "mean": "gauges: mean of latest values across fresh targets",
    "targets": "number of fresh targets contributing to the rollup",
    "le": "histograms: pinned bucket upper bounds (identical fleet-wide)",
    "counts": "histograms: exact per-bucket union counts (non-cumulative)",
    "count": "histograms: total observations in the union",
    "p50_ms": "histograms: union median from the merged buckets",
    "p95_ms": "histograms: union p95 from the merged buckets",
    "p99_ms": "histograms: union p99 from the merged buckets",
    "exemplars": "histograms: newest exemplar per bucket across the fleet",
}

#: Counter families whose fleet-wide rate sum defines "fleet QPS".
_QPS_FAMILIES = ("dps_rpc_server_calls_total", "dps_replica_fetches_total")


def _parse_label_block(block: str) -> dict:
    """``k="v",k2="v2"`` -> dict (no escape handling: our renderer never
    emits quotes or commas inside values)."""
    labels: dict[str, str] = {}
    for part in block.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        labels[k.strip()] = v.strip().strip('"')
    return labels


def _label_key(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return "{" + inner + "}"


def parse_prometheus_text(text: str) -> dict:
    """Prometheus text exposition -> registry-snapshot shape.

    The degradation path when a target serves only ``/metrics`` (older
    build without ``/metrics.json``): reconstructs NON-cumulative bucket
    counts from the cumulative ``_bucket{le=...}`` series using the
    ``# TYPE`` directives, yielding the same ``{"counters", "gauges",
    "histograms"}`` dict ``MetricsRegistry.snapshot()`` produces —
    minus exemplars, which the text format does not carry.
    """
    kinds: dict[str, str] = {}
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    hists: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        metric, _, value_s = line.rpartition(" ")
        metric = metric.strip()
        if "{" in metric:
            name, _, rest = metric.partition("{")
            labels = _parse_label_block(rest.rstrip("}"))
        else:
            name, labels = metric, {}
        try:
            value = float(value_s)
        except ValueError:
            continue
        base = name
        suffix = ""
        for s in ("_bucket", "_sum", "_count"):
            if name.endswith(s) and kinds.get(name[:-len(s)]) == "histogram":
                base, suffix = name[:-len(s)], s
                break
        kind = kinds.get(base)
        if kind == "histogram":
            le = labels.pop("le", None)
            key = base + _label_key(labels)
            h = hists.setdefault(key, {"cum": [], "sum": 0.0, "count": 0})
            if suffix == "_bucket" and le is not None:
                edge = float("inf") if le == "+Inf" else float(le)
                h["cum"].append((edge, int(value)))
            elif suffix == "_sum":
                h["sum"] = value
            elif suffix == "_count":
                h["count"] = int(value)
        elif kind == "gauge":
            out["gauges"][base + _label_key(labels)] = value
        else:  # counter, or untyped (counted as counter-like)
            out["counters"][base + _label_key(labels)] = value
    for key, h in hists.items():
        cum = sorted(h["cum"])
        edges = [e for e, _ in cum if e != float("inf")]
        counts: list[int] = []
        prev = 0
        for _, c in cum:
            counts.append(max(0, c - prev))
            prev = c
        if len(counts) == len(edges):  # no +Inf line: empty overflow
            counts.append(0)
        out["histograms"][key] = {"le": edges, "counts": counts,
                                  "sum": h["sum"], "count": h["count"]}
    return out


def _normalize_target(t: str) -> str:
    t = t.strip().rstrip("/")
    if t.startswith(("http://", "https://")):
        return t
    return "http://" + t


class _TargetState:
    """Everything the collector remembers about one scrape target."""

    def __init__(self, target: str, explicit: bool, ring_depth: int,
                 discovered_from: str | None = None):
        self.target = target
        self.explicit = explicit
        self.discovered_from = discovered_from
        self.ring_depth = ring_depth
        self.rings: dict[str, deque] = {}     # series key -> (ts, value)
        self.hist_latest: dict[str, dict] = {}  # series key -> snapshot
        self.cluster: dict | None = None
        self.ok = False
        self.consecutive_failures = 0
        self.last_scrape_ts = 0.0
        self.last_error: str | None = None
        self.role: str | None = None
        self.pid: int | None = None

    @property
    def stale(self) -> bool:
        return not self.ok

    def record(self, now: float, snap: dict, cluster: dict | None) -> None:
        for kind in ("counters", "gauges"):
            for key, val in snap.get(kind, {}).items():
                ring = self.rings.get(kind + ":" + key)
                if ring is None:
                    ring = deque(maxlen=self.ring_depth)
                    self.rings[kind + ":" + key] = ring
                ring.append((now, float(val)))
        self.hist_latest = dict(snap.get("histograms", {}))
        if cluster is not None:
            self.cluster = cluster
            self.role = cluster.get("role")
            self.pid = cluster.get("pid")
        self.ok = True
        self.consecutive_failures = 0
        self.last_scrape_ts = now
        self.last_error = None

    def fail(self, now: float, err: str) -> None:
        self.ok = False
        self.consecutive_failures += 1
        self.last_error = err

    def latest(self, kind: str) -> dict:
        """Latest value per series of one kind ('counters'/'gauges')."""
        prefix = kind + ":"
        return {k[len(prefix):]: ring[-1][1]
                for k, ring in self.rings.items()
                if k.startswith(prefix) and ring}

    def rate(self, key: str, now: float, window_s: float) -> float | None:
        """Ring-delta rate for one counter: newest vs the oldest sample
        inside the window (None with <2 samples). Clamped at 0 so a
        counter reset (process restart) reads as a rate dip, not a
        negative spike."""
        ring = self.rings.get("counters:" + key)
        if not ring or len(ring) < 2:
            return None
        newest_ts, newest_v = ring[-1]
        base_ts, base_v = ring[0]
        for ts, v in ring:
            if ts >= now - window_s:
                base_ts, base_v = ts, v
                break
        if newest_ts <= base_ts:
            return None
        return max(0.0, newest_v - base_v) / (newest_ts - base_ts)

    def to_row(self) -> dict:
        row = {
            "target": self.target,
            "explicit": self.explicit,
            "ok": self.ok,
            "stale": self.stale,
            "consecutive_failures": self.consecutive_failures,
            "last_scrape_ts": round(self.last_scrape_ts, 3),
            "last_error": self.last_error,
        }
        if self.role is not None:
            row["role"] = self.role
        if self.pid is not None:
            row["pid"] = self.pid
        if self.discovered_from is not None:
            row["discovered_from"] = self.discovered_from
        return row


class FleetCollector:
    """Scrape loop + ring TSDB + rollup engine (see module docstring).

    ``tick()`` is re-entrant-safe but meant to be driven by one loop
    (``run_forever`` or a test calling it directly with a fake clock);
    ``view()`` may be called concurrently from the HTTP surface.
    """

    def __init__(self, targets: list, interval_s: float = 2.0,
                 timeout_s: float = 1.5, ring_depth: int = 120,
                 rate_window_s: float = 30.0,
                 registry: MetricsRegistry | None = None,
                 objectives: list | None = None,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0,
                 journal=None, incidents=None,
                 clock=time.time):
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.ring_depth = int(ring_depth)
        self.rate_window_s = float(rate_window_s)
        #: Optional JournalWriter: every tick appends one ``fleet_tick``
        #: record (the merged view minus its history rings) plus
        #: ``slo_burn`` edge records — the replay/forensics feed.
        self.journal = journal
        #: Optional IncidentCapture fed each tick's view (observer-side
        #: critical alert / SLO-burn capture).
        self.incidents = incidents
        self.clock = clock
        self.registry = registry if registry is not None else get_registry()
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        self._slo_windows = SloEvaluator(
            self.objectives, registry=MetricsRegistry(),
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s).windows
        self._lock = threading.Lock()
        # guarded by: self._lock
        self._states: dict[str, _TargetState] = {}
        for t in targets:
            t = _normalize_target(t)
            self._states[t] = _TargetState(t, explicit=True,
                                           ring_depth=self.ring_depth)
        self._ticks = 0                     # guarded by: self._lock
        self._last_scrape_ms = 0.0          # guarded by: self._lock
        # (ts, {objective: (total, bad)}) — guarded by: self._lock
        self._slo_samples: deque = deque()
        self._slo_breaches: list = []       # guarded by: self._lock
        self._history: dict[str, deque] = {  # guarded by: self._lock
            "fleet_qps": deque(maxlen=self.ring_depth),
            "p99_ms": deque(maxlen=self.ring_depth),
            "scrape_ms": deque(maxlen=self.ring_depth),
        }
        # SLO breach identities already journaled as ``slo_burn`` edges.
        self._journaled_breaches = set()  # guarded by: self._lock
        # Collector's own instruments (scraping the observer works too).
        self._tm_ticks = self.registry.counter("dps_fleet_ticks_total")
        self._tm_targets = self.registry.gauge("dps_fleet_targets")
        self._tm_series = self.registry.gauge("dps_fleet_series")
        self._tm_scrape = self.registry.histogram(
            "dps_fleet_scrape_seconds", buckets=LATENCY_BUCKETS_S)
        self._tm_err: dict[str, object] = {}  # guarded by: self._lock

    # -- scraping -------------------------------------------------------------

    def _http_json(self, base: str, path: str):
        with urllib.request.urlopen(base + path,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _scrape_one(self, base: str) -> tuple[dict, dict | None]:
        """(metrics snapshot, cluster view or None). Prefers the exact
        ``/metrics.json`` snapshot; falls back to parsing the Prometheus
        text; a missing ``/cluster`` (404: no monitor in that process,
        e.g. a replica) is NOT an error."""
        try:
            snap = self._http_json(base, "/metrics.json")
        except urllib.error.HTTPError:
            # Target answers HTTP but has no /metrics.json (older
            # build): degrade to parsing the text exposition. Dead
            # targets (refused/timeout) skip the fallback — one bounded
            # failure, not two.
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=self.timeout_s) as r:
                snap = parse_prometheus_text(r.read().decode())
        cluster = None
        try:
            cluster = self._http_json(base, "/cluster")
        except Exception:  # noqa: BLE001 — replicas have no monitor
            pass
        return snap, cluster

    def _err_counter_locked(self, target: str):
        """Lazy-mint ``dps_fleet_scrape_errors_total{target}`` — the
        dynamic-member series-lifecycle idiom (ps/sharding.py): minted
        on first error, removed from the registry when the discovered
        target drains."""
        c = self._tm_err.get(target)
        if c is None:
            c = self.registry.counter("dps_fleet_scrape_errors_total",
                                      target=target)
            self._tm_err[target] = c
        return c

    def tick(self) -> dict:
        """One scrape round: concurrent per-target scrapes (each GET
        bounded by ``timeout_s``; a dead target marks its series stale
        and never blocks the others), discovery refresh, drain, SLO
        sample. Returns ``{"ok": n, "failed": n, "scrape_ms": ms}``."""
        t0 = time.perf_counter()
        now = self.clock()
        with self._lock:
            targets = list(self._states)
        results: dict[str, tuple] = {}
        errors: dict[str, str] = {}
        res_lock = threading.Lock()

        def scrape(base: str) -> None:
            try:
                out = self._scrape_one(base)
            except Exception as e:  # noqa: BLE001 — any failure = stale
                with res_lock:
                    errors[base] = repr(e)
                return
            with res_lock:
                results[base] = out

        threads = [threading.Thread(target=scrape, args=(t,), daemon=True,
                                    name=f"fleet-scrape-{t}")
                   for t in targets]
        for th in threads:
            th.start()
        # Each scrape makes at most 3 GETs, each socket-bounded by
        # timeout_s, so this join cannot hang the tick.
        for th in threads:
            th.join(timeout=3.0 * self.timeout_s + 1.0)
        with self._lock:
            for base in targets:
                st = self._states.get(base)
                if st is None:
                    continue
                if base in results:
                    snap, cluster = results[base]
                    try:
                        st.record(now, snap, cluster)
                    except Exception as e:  # noqa: BLE001 — bad payload
                        st.fail(now, f"bad payload: {e!r}")
                        self._err_counter_locked(base).inc()
                else:
                    st.fail(now, errors.get(base, "scrape timed out"))
                    self._err_counter_locked(base).inc()
            self._refresh_discovery_locked()
            self._sample_slo_locked(now)
            self._ticks += 1
            ms = (time.perf_counter() - t0) * 1e3
            self._last_scrape_ms = ms
            self._history["scrape_ms"].append(round(ms, 3))
            self._history["fleet_qps"].append(
                round(self._fleet_qps_locked(now), 3))
            self._history["p99_ms"].append(self._fleet_p99_ms_locked())
            self._tm_ticks.inc()
            self._tm_targets.set(len(self._states))
            self._tm_series.set(sum(
                len(s.rings) + len(s.hist_latest)
                for s in self._states.values()))
            self._tm_scrape.observe(ms / 1e3)
            ok = sum(1 for s in self._states.values() if s.ok)
            out = {"ok": ok, "failed": len(self._states) - ok,
                   "scrape_ms": round(ms, 3)}
        self._post_tick()
        return out

    def _post_tick(self) -> None:
        """Forensics fan-out, outside the collector lock: journal this
        tick's merged view (minus the history rings — replay rebuilds
        those from consecutive ticks) and new ``slo_burn`` edges, then
        feed the incident engine. All best-effort: a full disk or a
        capture failure must never stall the scrape loop."""
        if self.journal is None and self.incidents is None:
            return
        try:
            v = self.view()
        except Exception:  # noqa: BLE001 — forensics never stalls ticks
            return
        breaches = (v.get("slo") or {}).get("breaches") or []
        with self._lock:
            new = [b for b in breaches
                   if (b["rule"], b["objective"])
                   not in self._journaled_breaches]
            self._journaled_breaches = {(b["rule"], b["objective"])
                                        for b in breaches}
        if self.journal is not None:
            try:
                slim = {k: val for k, val in v.items() if k != "history"}
                if isinstance(slim.get("rollups"), dict):
                    slim["rollups"] = self._slim_rollups(slim["rollups"])
                self.journal.append("fleet_tick",
                                    {"ts": v["ts"], "view": slim})
                for b in new:
                    self.journal.append("slo_burn", dict(b))
            except Exception:  # noqa: BLE001 — disk full degrades
                pass
        if self.incidents is not None:
            try:
                self.incidents.on_fleet_view(v)
            except Exception:  # noqa: BLE001 — capture never stalls
                pass

    @staticmethod
    def _slim_rollups(roll: dict) -> dict:
        """The journaled copy of one tick's rollups, minus the
        zero-valued counter/histogram vocabulary (same rationale as
        ``SnapshotEmitter._journal_payload``: the pre-created
        alert/fault grids dominate the bytes, and replay reads an
        absent series exactly like a present zero). The live ``/fleet``
        response keeps its full-vocabulary rollups untouched."""
        out = dict(roll)
        ctr = roll.get("counters")
        if isinstance(ctr, dict):
            out["counters"] = {
                k: r for k, r in ctr.items()
                if not isinstance(r, dict)
                or r.get("sum") or r.get("rate_per_s")}
        gauges = roll.get("gauges")
        if isinstance(gauges, dict):
            out["gauges"] = {
                k: r for k, r in gauges.items()
                if not isinstance(r, dict)
                or r.get("min") or r.get("max")}
        hists = roll.get("histograms")
        if isinstance(hists, dict):
            out["histograms"] = {
                k: h for k, h in hists.items()
                if not isinstance(h, dict)
                or h.get("count") or "error" in h}
        return out

    def _refresh_discovery_locked(self) -> None:
        """Adopt replica metrics addresses announced via the primaries'
        ``/cluster`` sharding views; drain discovered targets no view
        mentions anymore (state dropped AND the per-target error series
        removed — same lifecycle as ``dps_replica_lag_*``)."""
        announced: dict[str, str] = {}
        for st in self._states.values():
            if not st.ok or not st.cluster:
                continue
            sharding = st.cluster.get("sharding") or {}
            for rep in sharding.get("replicas", []):
                maddr = rep.get("metrics")
                if maddr:
                    announced[_normalize_target(maddr)] = st.target
        for t, src in announced.items():
            if t not in self._states:
                self._states[t] = _TargetState(
                    t, explicit=False, ring_depth=self.ring_depth,
                    discovered_from=src)
        for t in [t for t, s in self._states.items()
                  if not s.explicit and t not in announced]:
            del self._states[t]
            self._tm_err.pop(t, None)
            self.registry.remove("dps_fleet_scrape_errors_total", target=t)

    # -- fleet SLO ------------------------------------------------------------

    def _merged_hist_locked(self, key: str) -> dict | None:
        snaps = [s.hist_latest[key] for s in self._states.values()
                 if s.ok and key in s.hist_latest]
        if not snaps:
            return None
        return merge_histograms(snaps)

    def _merged_counter_locked(self, key: str) -> float:
        return sum(s.latest("counters").get(key, 0.0)
                   for s in self._states.values() if s.ok)

    def _sample_slo_locked(self, now: float) -> None:
        sample: dict[str, tuple] = {}
        for obj in self.objectives:
            hkey = f"dps_rpc_server_latency_seconds{{method={obj.method}}}"
            ekey = f"dps_rpc_server_errors_total{{method={obj.method}}}"
            merged = self._merged_hist_locked(hkey)
            if merged is None:
                continue
            total = int(merged["count"])
            err = int(self._merged_counter_locked(ekey))
            if obj.threshold_s is None:
                bad = min(total, err)
            else:
                good, _ = SloEvaluator._good_upto(merged, obj.threshold_s)
                bad = min(total, (total - good) + err)
            sample[obj.name] = (total, bad)
        self._slo_samples.append((now, sample))
        horizon = now - self._slo_windows[-1].window_s * 1.5
        while len(self._slo_samples) > 1 \
                and self._slo_samples[0][0] < horizon:
            self._slo_samples.popleft()
        breaches = []
        samples = list(self._slo_samples)
        for win in self._slo_windows:
            for obj in self.objectives:
                d = SloEvaluator._window_delta(samples, obj.name, now,
                                               win.window_s)
                if d is None or d["total"] < win.min_events:
                    continue
                burn = SloEvaluator._burn(obj, d["bad"], d["total"])
                if burn >= win.burn_threshold:
                    breaches.append({
                        "rule": win.rule, "severity": win.severity,
                        "objective": obj.name, "window_s": win.window_s,
                        "burn": round(burn, 2),
                        "burn_threshold": win.burn_threshold,
                        "bad": d["bad"], "total": d["total"],
                        "scope": "fleet",
                    })
        self._slo_breaches = breaches

    def _fleet_qps_locked(self, now: float) -> float:
        qps = 0.0
        for st in self._states.values():
            if not st.ok:
                continue
            for key in st.latest("counters"):
                if key.split("{", 1)[0] in _QPS_FAMILIES:
                    r = st.rate(key, now, self.rate_window_s)
                    if r is not None:
                        qps += r
        return qps

    def _fleet_p99_ms_locked(self) -> float | None:
        merged = self._merged_hist_locked(
            "dps_rpc_server_latency_seconds{method=FetchParameters}")
        if merged is None:
            return None
        q = histogram_quantile(merged["le"], merged["counts"], 99)
        return None if q is None else round(q * 1e3, 3)

    # -- the /fleet view ------------------------------------------------------

    def _rollups_locked(self, now: float) -> dict:
        fresh = [s for s in self._states.values() if s.ok]
        counters: dict[str, dict] = {}
        gauges: dict[str, dict] = {}
        hists: dict[str, dict] = {}
        for st in fresh:
            for key, val in st.latest("counters").items():
                row = counters.setdefault(
                    key, {"sum": 0.0, "rate_per_s": 0.0, "targets": 0})
                row["sum"] += val
                r = st.rate(key, now, self.rate_window_s)
                if r is not None:
                    row["rate_per_s"] += r
                row["targets"] += 1
            for key, val in st.latest("gauges").items():
                row = gauges.get(key)
                if row is None:
                    gauges[key] = {"sum": val, "min": val, "max": val,
                                   "mean": val, "targets": 1}
                else:
                    row["sum"] += val
                    row["min"] = min(row["min"], val)
                    row["max"] = max(row["max"], val)
                    row["targets"] += 1
        for row in counters.values():
            row["sum"] = round(row["sum"], 6)
            row["rate_per_s"] = round(row["rate_per_s"], 6)
        for row in gauges.values():
            row["mean"] = round(row["sum"] / row["targets"], 6)
            row["sum"] = round(row["sum"], 6)
        hist_keys = {k for s in fresh for k in s.hist_latest}
        for key in sorted(hist_keys):
            snaps = [s.hist_latest[key] for s in fresh
                     if key in s.hist_latest]
            try:
                merged = merge_histograms(snaps)
            except ValueError as e:  # mismatched schemes: never merge
                hists[key] = {"error": str(e), "targets": len(snaps)}
                continue
            merged["targets"] = len(snaps)
            for pct, pkey in ((50, "p50_ms"), (95, "p95_ms"),
                              (99, "p99_ms")):
                q = histogram_quantile(merged["le"], merged["counts"], pct)
                merged[pkey] = None if q is None else round(q * 1e3, 3)
            hists[key] = merged
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def _tiers_locked(self) -> dict:
        primaries, replicas, workers = [], [], []
        jobs: dict[str, dict] = {}
        seen_reps: set[str] = set()
        prim_addrs: set[str] = set()
        for st in self._states.values():
            view = st.cluster
            if view is None:
                continue
            row = {"target": st.target, "ok": st.ok,
                   "role": view.get("role"), "pid": view.get("pid"),
                   "mode": view.get("mode"),
                   "global_step": view.get("global_step"),
                   "alerts": len(view.get("alerts", []))}
            sharding = view.get("sharding") or {}
            if sharding:
                row["shard_id"] = sharding.get("shard_id")
                row["map_version"] = sharding.get("map_version")
                prim_addrs.update(a for a in (sharding.get("primaries")
                                              or []) if a)
            primaries.append(row)
            for rep in sharding.get("replicas", []):
                addr = rep.get("address")
                if addr in seen_reps:
                    continue
                seen_reps.add(addr)
                replicas.append({**rep, "via": st.target})
            for w in view.get("workers", []):
                workers.append({**w, "via": st.target})
            for name, jrow in (view.get("jobs") or {}).items():
                jobs.setdefault(name, {**jrow, "via": st.target})
        # Fan-out-tree rollup (docs/SHARDING.md "Fan-out trees"): the
        # per-tier shape of the serve tree, merged across every shard.
        tiers: dict[str, dict] = {}
        for rep in replicas:
            key = str(max(1, int(rep.get("tier") or 1)))
            roll = tiers.setdefault(
                key, {"replicas": 0, "max_lag_steps": 0.0, "fetch_qps": 0.0})
            roll["replicas"] += 1
            roll["max_lag_steps"] = max(roll["max_lag_steps"],
                                        float(rep.get("lag_steps") or 0.0))
            roll["fetch_qps"] = round(
                roll["fetch_qps"] + float(rep.get("fetch_qps") or 0.0), 2)
        out = {"primaries": primaries, "replicas": replicas,
               "workers": workers, "jobs": jobs}
        if prim_addrs:
            # gRPC addresses of the shard primaries (scrape targets above
            # are metrics endpoints) — the tree renderer roots replica
            # rows whose ``parent`` is one of these.
            out["primary_addresses"] = sorted(prim_addrs)
        if tiers:
            out["replica_tiers"] = tiers
        return out

    def _slo_view_locked(self, now: float) -> dict:
        samples = list(self._slo_samples)
        breaches = list(self._slo_breaches)
        out_objs = []
        for obj in self.objectives:
            hkey = f"dps_rpc_server_latency_seconds{{method={obj.method}}}"
            merged = self._merged_hist_locked(hkey)
            entry = {
                "name": obj.name, "method": obj.method,
                "target": obj.target,
                "kind": ("latency" if obj.threshold_s is not None
                         else "availability"),
                "total": 0 if merged is None else int(merged["count"]),
            }
            if obj.threshold_s is not None:
                entry["threshold_ms"] = round(obj.threshold_s * 1e3, 3)
            if merged is not None:
                for pct, key in ((50, "p50_ms"), (95, "p95_ms"),
                                 (99, "p99_ms")):
                    q = histogram_quantile(merged["le"], merged["counts"],
                                           pct)
                    entry[key] = None if q is None else round(q * 1e3, 3)
            windows = {}
            for win in self._slo_windows:
                d = SloEvaluator._window_delta(samples, obj.name, now,
                                               win.window_s)
                if d is None:
                    d = {"total": 0, "bad": 0}
                burn = SloEvaluator._burn(obj, d["bad"], d["total"])
                windows[win.rule] = {
                    "window_s": win.window_s, "total": d["total"],
                    "bad": d["bad"], "burn": round(burn, 2),
                    "burn_threshold": win.burn_threshold,
                    "breaching": any(b["rule"] == win.rule
                                     and b["objective"] == obj.name
                                     for b in breaches),
                }
            entry["windows"] = windows
            out_objs.append(entry)
        return {"objectives": out_objs, "breaches": breaches,
                "scope": "fleet"}

    def view(self) -> dict:
        """The ``GET /fleet`` payload (schema: docs/OBSERVABILITY.md)."""
        now = self.clock()
        with self._lock:
            alerts = []
            for st in self._states.values():
                if st.cluster is None:
                    continue
                for a in st.cluster.get("alerts", []):
                    alerts.append({**a, "target": st.target})
            remediation_active = any(
                (st.cluster or {}).get("remediation", {}).get("active")
                and not (st.cluster or {}).get("remediation",
                                               {}).get("dry_run")
                for st in self._states.values())
            return {
                "ts": round(now, 3),
                "ticks": self._ticks,
                "interval_s": self.interval_s,
                "targets": [s.to_row()
                            for s in sorted(self._states.values(),
                                            key=lambda s: s.target)],
                "tiers": self._tiers_locked(),
                "rollups": self._rollups_locked(now),
                "slo": self._slo_view_locked(now),
                "alerts": alerts,
                "remediation_active": remediation_active,
                "fleet_qps": round(self._fleet_qps_locked(now), 3),
                "history": {k: list(v)
                            for k, v in self._history.items()},
                "series_count": sum(
                    len(s.rings) + len(s.hist_latest)
                    for s in self._states.values()),
                "scrape": {
                    "last_ms": round(self._last_scrape_ms, 3),
                    "targets_scraped": sum(
                        1 for s in self._states.values() if s.ok),
                },
            }

    def run_forever(self, stop: threading.Event | None = None) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass
            elapsed = time.perf_counter() - t0
            stop.wait(max(0.05, self.interval_s - elapsed))


def _since_param(query: str) -> int | None:
    """``since=<tick>`` from a raw query string; None when absent or
    unparseable (full payload — the pre-ISSUE-18 behaviour)."""
    for part in query.split("&"):
        if part.startswith("since="):
            try:
                return max(0, int(part[len("since="):]))
            except ValueError:
                return None
    return None


class _FleetHandler(BaseHTTPRequestHandler):
    collector: FleetCollector  # set by start_fleet_server

    def do_GET(self):  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        if path == "/fleet":
            try:
                view = self.collector.view()
                since = _since_param(query)
                if since is not None:
                    # Incremental poll (ISSUE 18): history entry i
                    # belongs to tick (ticks - len + 1 + i), so a client
                    # that saw tick N needs exactly the last
                    # (ticks - N) entries. ``history_since`` is the
                    # capability marker: an older server ignores the
                    # query entirely and the client detects the absence
                    # and degrades to full-ring replacement.
                    delta = max(0, view["ticks"] - since)
                    view["history"] = {
                        k: (rows[-delta:] if delta else [])
                        for k, rows in view["history"].items()}
                    view["history_since"] = since
                body = json.dumps(view).encode()
                status = 200
            except Exception as e:  # noqa: BLE001
                body = json.dumps({"error": repr(e)}).encode()
                status = 500
            ctype = "application/json"
        elif path == "/metrics":
            from .prometheus import render_prometheus
            body = render_prometheus(self.collector.registry).encode()
            status = 200
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body = json.dumps({"ok": True}).encode()
            status = 200
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrape/poll noise stays off stdout
        pass


def start_fleet_server(collector: FleetCollector, port: int = 0,
                       addr: str = "0.0.0.0"
                       ) -> tuple[ThreadingHTTPServer, int]:
    """Serve ``GET /fleet`` (+ ``/metrics`` for the collector's own
    instruments) on a daemon thread. Returns (server, bound_port);
    callers own shutdown."""
    handler = type("BoundFleetHandler", (_FleetHandler,),
                   {"collector": collector})
    server = ThreadingHTTPServer((addr, port), handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="fleet-http").start()
    return server, server.server_address[1]
