"""Span helpers: wall-time instrumentation for the hot paths.

A "span" here is deliberately minimal — a duration observed into a fixed-
bucket histogram plus an optional call counter — not a distributed-tracing
tree. The hot paths this framework cares about (train step, push/fetch RPC
client+handler, store aggregation) are flat and high-frequency; what the
adaptive-sync literature needs from them is *distributions over time*
(PAPERS.md: ACE-Sync consumes staleness/latency signals), which histograms
in the snapshot stream deliver at microsecond record cost.

Two usage shapes:

- ``with span(hist):`` for paths where a context manager's ~1 us overhead
  is irrelevant (RPC handlers, epoch loops);
- ``t0 = now(); ...; hist.observe(now() - t0)`` inlined where every
  nanosecond is on-budget (store push/fetch). ``now`` is re-exported
  ``time.perf_counter`` so call sites don't import ``time`` twice.

For deep profiler traces use utils/tracing.py (jax.profiler) — spans and
traces answer different questions (always-on time-series vs one-off
timeline).
"""

from __future__ import annotations

from contextlib import contextmanager
from time import perf_counter as now

from .registry import Counter, Histogram

__all__ = ["span", "now"]


@contextmanager
def span(hist: Histogram, counter: Counter | None = None):
    """Observe the block's wall time into ``hist`` (and bump ``counter``).

    The duration is recorded even when the body raises — a failing RPC
    still spent the wire time, and dropping error durations would bias the
    distribution toward the happy path.
    """
    t0 = now()
    try:
        yield
    finally:
        hist.observe(now() - t0)
        if counter is not None:
            counter.inc()
