"""Packed-nibble int4 tensor type: the wire's ``int4`` dtype carrier.

numpy has no packed 4-bit dtype, so int4 tensors travel as
:class:`PackedInt4` — a uint8 ndarray of packed nibbles (two signed 4-bit
values per byte, low nibble = even flat index) that remembers the LOGICAL
shape of the tensor it encodes. The wire codec (``comms/wire.py``) maps it
to/from the ``int4`` header dtype; the quantization math lives in
``ops/compression.py``.

This module is a dependency LEAF (numpy only): both the wire codec and the
compression layer import it, and neither package's ``__init__`` chain runs
underneath it — which is what keeps ``ops.compression`` ↔ ``comms``
acyclic.
"""

from __future__ import annotations

import math

import numpy as np


class PackedInt4(np.ndarray):
    """uint8 array of packed nibbles + the logical tensor shape it encodes.

    ``logical_shape`` is the shape of the dequantized tensor; the packed
    buffer is ``ceil(prod(shape)/2)`` bytes. Built via
    :func:`as_packed_int4`; survives the wire encode/decode round trip
    (decode re-wraps the zero-copy uint8 view)."""

    logical_shape: tuple = ()

    def __array_finalize__(self, obj):
        if obj is not None:
            self.logical_shape = getattr(obj, "logical_shape", ())


def packed_int4_nbytes(logical_shape) -> int:
    """Packed byte count for a logical element shape (two per byte)."""
    return (math.prod(logical_shape) + 1) // 2


def as_packed_int4(data, logical_shape) -> PackedInt4:
    """Wrap packed nibble bytes as :class:`PackedInt4`. ``data`` must hold
    exactly ``ceil(prod(logical_shape)/2)`` uint8s."""
    arr = np.asarray(data, np.uint8).reshape(-1).view(PackedInt4)
    shape = tuple(int(s) for s in logical_shape)
    if arr.nbytes != packed_int4_nbytes(shape):
        raise ValueError(
            f"packed int4 buffer holds {arr.nbytes} bytes; logical shape "
            f"{shape} needs {packed_int4_nbytes(shape)}")
    arr.logical_shape = shape
    return arr


def pack_nibbles(q: np.ndarray) -> np.ndarray:
    """Pack an int8 array of values in [-8, 7] into uint8 nibble pairs
    (flat, ceil(n/2) bytes; a trailing odd element rides the low nibble of
    the last byte)."""
    flat = np.asarray(q, np.int8).reshape(-1)
    if flat.size % 2:
        flat = np.concatenate([flat, np.zeros(1, np.int8)])
    lo = (flat[0::2].astype(np.uint8)) & 0x0F
    hi = ((flat[1::2].astype(np.uint8)) & 0x0F) << 4
    return (lo | hi).astype(np.uint8)


def unpack_nibbles(packed: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`pack_nibbles`: first ``n`` signed int8 values
    (sign-extended from the 4-bit two's-complement nibbles)."""
    p = np.asarray(packed, np.uint8).reshape(-1)
    out = np.empty(p.size * 2, np.int8)
    out[0::2] = (p & 0x0F).astype(np.int8)
    out[1::2] = ((p >> 4) & 0x0F).astype(np.int8)
    # Sign-extend: nibble values 8..15 are -8..-1.
    out[out > 7] -= 16
    return out[:n]
