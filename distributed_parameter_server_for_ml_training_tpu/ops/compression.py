"""Gradient compression.

The reference's "compression" is an fp32->fp16 cast before pickling
(src/workers/worker.py:264-268) and a cast back on the server
(src/parameter_server/server.py:232-237) — ~50% wire bytes, logged at
worker.py:292.

Two TPU-native forms of the same capability:

1. **Reduced-precision all-reduce** (sync path): cast gradients to
   bfloat16/float16 before ``lax.pmean`` so the ICI collective moves half the
   bytes, then restore fp32 for the optimizer. bfloat16 keeps fp32's exponent
   range, so — unlike the reference's fp16 cast — it cannot overflow large
   gradients. (Prior art for in-collective quantization: EQuARX; PAPERS.md.)

2. **Wire codecs** (async PS path): fp16 cast (bit-for-bit the reference
   semantics) and int8 per-tensor affine quantization (~75% bytes) for
   host<->store transfers. These operate on numpy arrays because the async
   store lives on the host CPU.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

_ALLREDUCE_DTYPES = {
    "none": None,
    "fp32": None,
    "bf16": jnp.bfloat16,
    "fp16": jnp.float16,
}


def compress_for_allreduce(grads: PyTree, mode: str = "bf16") -> PyTree:
    """Cast gradients for the wire (the collective). No-op for 'none'."""
    dtype = _ALLREDUCE_DTYPES[mode]
    if dtype is None:
        return grads
    return jax.tree_util.tree_map(lambda g: g.astype(dtype), grads)


def decompress_from_allreduce(grads: PyTree, mode: str = "bf16") -> PyTree:
    """Restore fp32 after the collective (server.py:232-237 analogue)."""
    if _ALLREDUCE_DTYPES[mode] is None:
        return grads
    return jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)


# ---------------------------------------------------------------------------
# Host-side wire codecs for the async parameter store.
# ---------------------------------------------------------------------------

def fp16_compress(tree: PyTree) -> PyTree:
    """fp32 -> fp16 cast, exactly the reference's compress_gradients
    (worker.py:264-268)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float32).astype(np.float16), tree)


def fp16_decompress(tree: PyTree) -> PyTree:
    """fp16 -> fp32, exactly decompress_gradients (server.py:232-237)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a).astype(np.float32), tree)


def bf16_compress(tree: PyTree) -> PyTree:
    """fp32 -> bfloat16 cast (round-to-nearest-even via ml_dtypes).

    The FETCH-side codec the reference never had: its dominant server cost
    was re-pickling ~45 MB of fp32 parameters per fetch (server.py:222,
    SURVEY §3.1). bf16 halves those bytes while keeping fp32's full
    exponent range — for PARAMETERS (which span many orders of magnitude
    across layers) that matters more than fp16's extra mantissa bits."""
    import ml_dtypes

    return jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float32).astype(ml_dtypes.bfloat16), tree)


def bf16_decompress(tree: PyTree) -> PyTree:
    """bfloat16 -> fp32 (exact: bf16 values are representable in fp32)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a).astype(np.float32), tree)


def int8_quantize(a: np.ndarray) -> tuple[np.ndarray, np.float32]:
    """Per-tensor symmetric int8 quantization: returns (q, scale).

    Non-finite inputs raise: quantizing inf/NaN would cast undefined
    int8 garbage the server then applies as plausible-looking gradients
    — the fp16 codec propagates the non-finite values visibly, and this
    codec must not silently corrupt where fp16 would surface the
    blow-up."""
    a = np.asarray(a, np.float32)
    amax = float(np.max(np.abs(a))) if a.size else 0.0
    if not np.isfinite(amax):
        raise ValueError("int8_quantize: non-finite values in input "
                         "(diverging gradients?)")
    scale = np.float32(amax / 127.0) if amax > 0 else np.float32(1.0)
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, scale


def int8_dequantize(q: np.ndarray, scale: np.float32) -> np.ndarray:
    return q.astype(np.float32) * np.float32(scale)


# int8 WIRE codec over named-tensor dicts: each fp32 tensor rides as int8
# values plus a scale entry under ``name + _SCALE_SUFFIX``. The suffix
# convention keeps the existing no-pickle wire format (comms/wire.py)
# unchanged — scales are just more named tensors.
_SCALE_SUFFIX = "::int8scale"


def int8_wire_compress(tensors: dict) -> dict:
    """{name: fp32 array} -> {name: int8 array, name::int8scale: fp32[1]}
    (~1/4 of fp32's wire bytes; half of the fp16 codec's)."""
    out: dict = {}
    for name, a in tensors.items():
        q, scale = int8_quantize(a)
        out[name] = q
        out[name + _SCALE_SUFFIX] = np.asarray([scale], np.float32)
    return out


def int8_wire_decompress(tensors: dict) -> dict:
    """Inverse of :func:`int8_wire_compress`; tolerates already-fp32
    entries (mixed payloads) by passing them through."""
    out: dict = {}
    for name, a in tensors.items():
        if name.endswith(_SCALE_SUFFIX):
            continue
        a = np.asarray(a)
        if a.dtype == np.int8:
            scale = tensors.get(name + _SCALE_SUFFIX)
            if scale is None:
                raise ValueError(f"int8 wire entry {name!r} missing its "
                                 f"{_SCALE_SUFFIX} companion")
            out[name] = int8_dequantize(a, np.float32(np.asarray(scale)[0]))
        else:
            out[name] = a.astype(np.float32)
    return out
