"""Gradient compression.

The reference's "compression" is an fp32->fp16 cast before pickling
(src/workers/worker.py:264-268) and a cast back on the server
(src/parameter_server/server.py:232-237) — ~50% wire bytes, logged at
worker.py:292.

Two TPU-native forms of the same capability:

1. **Reduced-precision all-reduce** (sync path): cast gradients to
   bfloat16/float16 before ``lax.pmean`` so the ICI collective moves half the
   bytes, then restore fp32 for the optimizer. bfloat16 keeps fp32's exponent
   range, so — unlike the reference's fp16 cast — it cannot overflow large
   gradients. (Prior art for in-collective quantization: EQuARX; PAPERS.md.)

2. **Wire codecs** (async PS path): fp16 cast (bit-for-bit the reference
   semantics) and int8 per-tensor affine quantization (~75% bytes) for
   host<->store transfers. These operate on numpy arrays because the async
   store lives on the host CPU.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .packed import PackedInt4, as_packed_int4, pack_nibbles, unpack_nibbles

PyTree = Any

#: The push/fetch wire-codec vocabulary (docs/WIRE_PROTOCOL.md's codec
#: table is drift-pinned to these keys by tests/test_docs_drift.py).
#: 'bf16' is fetch-side only; 'adaptive' is a worker-side per-layer
#: CHOICE among int8/int4/topk, not a wire form of its own.
CODEC_CATALOG = {
    "none": "fp32 tensors, reference parity",
    "fp16": "fp32->fp16 cast (the reference's push codec)",
    "bf16": "fp32->bfloat16 cast (fetch-side parameter codec)",
    "int8": "per-tensor symmetric int8 + ::int8scale companion",
    "int4": "packed-nibble int4 (wire dtype) + ::int4scale companion",
    "topk": "top-k sparsification: (indices, int8 values, scale) triple",
    "adaptive": "per-layer int8/int4/topk chosen from link pressure",
}

#: Push codecs whose payloads are quantized named-tensor dicts the server
#: can hold (and, in sync mode, accumulate) without decoding to fp32.
QUANTIZED_PUSH_CODECS = ("int8", "int4", "topk", "adaptive")

#: Every valid push codec (CODEC_CATALOG minus the fetch-only bf16) —
#: the store validates against THIS, so a catalog change propagates.
PUSH_CODECS = tuple(k for k in CODEC_CATALOG if k != "bf16")

_ALLREDUCE_DTYPES = {
    "none": None,
    "fp32": None,
    "bf16": jnp.bfloat16,
    "fp16": jnp.float16,
}


def compress_for_allreduce(grads: PyTree, mode: str = "bf16") -> PyTree:
    """Cast gradients for the wire (the collective). No-op for 'none'."""
    dtype = _ALLREDUCE_DTYPES[mode]
    if dtype is None:
        return grads
    return jax.tree_util.tree_map(lambda g: g.astype(dtype), grads)


def decompress_from_allreduce(grads: PyTree, mode: str = "bf16") -> PyTree:
    """Restore fp32 after the collective (server.py:232-237 analogue)."""
    if _ALLREDUCE_DTYPES[mode] is None:
        return grads
    return jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)


# ---------------------------------------------------------------------------
# Host-side wire codecs for the async parameter store.
# ---------------------------------------------------------------------------

def _stage_f32(a) -> np.ndarray:
    """Zero-copy fp32 staging for the cast codecs: an array that is
    already fp32 is returned AS ITSELF (``astype(copy=False)``), so the
    narrowing cast is the push's only allocation — the old
    ``np.asarray(a, np.float32)`` staging materialized an intermediate
    fp32 copy for device arrays and non-f32 inputs before the real cast.
    Pinned by tests/test_wire_zero_copy.py."""
    return np.asarray(a).astype(np.float32, copy=False)


# dpslint: hot-path
def fp16_compress(tree: PyTree) -> PyTree:
    """fp32 -> fp16 cast, exactly the reference's compress_gradients
    (worker.py:264-268)."""
    return jax.tree_util.tree_map(
        lambda a: _stage_f32(a).astype(np.float16, copy=False), tree)


def fp16_decompress(tree: PyTree) -> PyTree:
    """fp16 -> fp32, exactly decompress_gradients (server.py:232-237)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a).astype(np.float32), tree)


# dpslint: hot-path
def bf16_compress(tree: PyTree) -> PyTree:
    """fp32 -> bfloat16 cast (round-to-nearest-even via ml_dtypes).

    The FETCH-side codec the reference never had: its dominant server cost
    was re-pickling ~45 MB of fp32 parameters per fetch (server.py:222,
    SURVEY §3.1). bf16 halves those bytes while keeping fp32's full
    exponent range — for PARAMETERS (which span many orders of magnitude
    across layers) that matters more than fp16's extra mantissa bits."""
    import ml_dtypes

    return jax.tree_util.tree_map(
        lambda a: _stage_f32(a).astype(ml_dtypes.bfloat16, copy=False), tree)


def bf16_decompress(tree: PyTree) -> PyTree:
    """bfloat16 -> fp32 (exact: bf16 values are representable in fp32)."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a).astype(np.float32), tree)


def int8_quantize(a: np.ndarray) -> tuple[np.ndarray, np.float32]:
    """Per-tensor symmetric int8 quantization: returns (q, scale).

    Non-finite inputs raise: quantizing inf/NaN would cast undefined
    int8 garbage the server then applies as plausible-looking gradients
    — the fp16 codec propagates the non-finite values visibly, and this
    codec must not silently corrupt where fp16 would surface the
    blow-up."""
    a = np.asarray(a, np.float32)
    amax = float(np.max(np.abs(a))) if a.size else 0.0
    if not np.isfinite(amax):
        raise ValueError("int8_quantize: non-finite values in input "
                         "(diverging gradients?)")
    scale = np.float32(amax / 127.0) if amax > 0 else np.float32(1.0)
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, scale


def int8_dequantize(q: np.ndarray, scale: np.float32) -> np.ndarray:
    return q.astype(np.float32) * np.float32(scale)


# int8 WIRE codec over named-tensor dicts: each fp32 tensor rides as int8
# values plus a scale entry under ``name + _SCALE_SUFFIX``. The suffix
# convention keeps the existing no-pickle wire format (comms/wire.py)
# unchanged — scales are just more named tensors.
_SCALE_SUFFIX = "::int8scale"


def int8_wire_compress(tensors: dict) -> dict:
    """{name: fp32 array} -> {name: int8 array, name::int8scale: fp32[1]}
    (~1/4 of fp32's wire bytes; half of the fp16 codec's)."""
    out: dict = {}
    for name, a in tensors.items():
        q, scale = int8_quantize(a)
        out[name] = q
        out[name + _SCALE_SUFFIX] = np.asarray([scale], np.float32)
    return out


def int8_wire_decompress(tensors: dict) -> dict:
    """Inverse of :func:`int8_wire_compress`; tolerates already-fp32
    entries (mixed payloads) by passing them through WITHOUT copying
    (``astype(..., copy=False)`` — an unconditional ``astype`` re-copied
    the whole zero-copy wire view per push for nothing)."""
    out: dict = {}
    for name, a in tensors.items():
        if name.endswith(_SCALE_SUFFIX):
            continue
        a = np.asarray(a)
        if a.dtype == np.int8:
            scale = tensors.get(name + _SCALE_SUFFIX)
            if scale is None:
                raise ValueError(f"int8 wire entry {name!r} missing its "
                                 f"{_SCALE_SUFFIX} companion")
            out[name] = int8_dequantize(a, np.float32(np.asarray(scale)[0]))
        else:
            out[name] = a.astype(np.float32, copy=False)
    return out


# ---------------------------------------------------------------------------
# Compressed-domain push codecs (docs/WIRE_PROTOCOL.md):
#
#   int4  — packed-nibble symmetric quantization (the wire's "int4" dtype;
#           ~1/8 of fp32's bytes),
#   topk  — top-k sparsification, riding the named-tensor wire as an
#           (indices, int8 values, scale) triple per tensor,
#   shared-scale int8/int4 — quantize against the SERVER's per-layer scale
#           so the aggregator can sum payloads in the integer domain (THC,
#           PAPERS.md) and dequantize once per round,
#   ErrorFeedback — worker-side residual carry that makes the aggressive
#           codecs accuracy-safe,
#   homomorphic_mean — the server-side compressed-domain aggregation.
#
# All payloads stay self-describing named-tensor dicts: scales and sparse
# companions are just more named tensors under reserved suffixes, so the
# wire format (comms/wire.py) and the exactly-once/envelope machinery are
# untouched.
# ---------------------------------------------------------------------------

_INT4_SCALE_SUFFIX = "::int4scale"
_TOPK_IDX_SUFFIX = "::topk_idx"
_TOPK_VAL_SUFFIX = "::topk_val"
_TOPK_SCALE_SUFFIX = "::topk_scale"
_TOPK_SHAPE_SUFFIX = "::topk_shape"

_COMPANION_SUFFIXES = (
    _SCALE_SUFFIX, _INT4_SCALE_SUFFIX, _TOPK_IDX_SUFFIX, _TOPK_VAL_SUFFIX,
    _TOPK_SCALE_SUFFIX, _TOPK_SHAPE_SUFFIX,
)


def _require_finite(a: np.ndarray, who: str) -> None:
    """Every quantization path must surface NaN/Inf gradients instead of
    casting them to plausible-looking int garbage — and a NaN that slipped
    into an ErrorFeedback residual would poison every later push of that
    layer (same rationale as int8_quantize's guard)."""
    if a.size and not np.isfinite(float(np.max(np.abs(a)))):
        raise ValueError(f"{who}: non-finite values in input "
                         f"(diverging gradients?)")


def int8_quantize_with_scale(a: np.ndarray,
                             scale: float) -> np.ndarray:
    """Symmetric int8 quantization against a GIVEN scale (the shared-scale
    path): values beyond ±127·scale clip — error feedback carries the
    clipped mass into the next step."""
    a = np.asarray(a, np.float32)
    _require_finite(a, "int8_quantize_with_scale")
    return np.clip(np.rint(a / np.float32(scale)), -127, 127).astype(np.int8)


def int4_quantize(a: np.ndarray, scale: float | None = None
                  ) -> tuple[PackedInt4, np.float32]:
    """Per-tensor symmetric int4 quantization -> (packed nibbles, scale).

    Levels are [-7, 7] (the -8 code is unused so the scheme stays
    symmetric). Like :func:`int8_quantize`, non-finite inputs raise —
    with or without a caller-given shared scale."""
    a = np.asarray(a, np.float32)
    if scale is None:
        amax = float(np.max(np.abs(a))) if a.size else 0.0
        if not np.isfinite(amax):
            raise ValueError("int4_quantize: non-finite values in input "
                             "(diverging gradients?)")
        scale = np.float32(amax / 7.0) if amax > 0 else np.float32(1.0)
    else:
        _require_finite(a, "int4_quantize")
    scale = np.float32(scale)
    q = np.clip(np.rint(a / scale), -7, 7).astype(np.int8)
    return as_packed_int4(pack_nibbles(q), a.shape), scale


def int4_dequantize(packed: PackedInt4, scale) -> np.ndarray:
    shape = packed.logical_shape
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    q = unpack_nibbles(np.asarray(packed, np.uint8), n)
    return (q.astype(np.float32) * np.float32(scale)).reshape(shape)


def topk_compress_tensor(a: np.ndarray, frac: float = 0.01,
                         min_k: int = 1) -> dict:
    """One tensor -> its sparse wire triple (+ shape companion):
    ``{name::topk_idx: int32[k], name::topk_val: int8[k],
    name::topk_scale: fp32[1], name::topk_shape: int64[ndim]}`` — the
    largest-magnitude ``k = max(min_k, frac·n)`` entries, int8-quantized.
    Returns the dict of companion arrays WITHOUT the name prefixes; the
    caller attaches them."""
    a = np.asarray(a, np.float32)
    flat = a.reshape(-1)
    k = min(flat.size, max(min_k, int(round(frac * flat.size))))
    if not np.all(np.isfinite(flat)):
        raise ValueError("topk_compress_tensor: non-finite values in input")
    idx = np.argpartition(np.abs(flat), flat.size - k)[flat.size - k:]
    idx = np.sort(idx).astype(np.int32)
    vals = flat[idx]
    amax = float(np.max(np.abs(vals))) if k else 0.0
    scale = np.float32(amax / 127.0) if amax > 0 else np.float32(1.0)
    q = np.clip(np.rint(vals / scale), -127, 127).astype(np.int8)
    return {
        _TOPK_IDX_SUFFIX: idx,
        _TOPK_VAL_SUFFIX: q,
        _TOPK_SCALE_SUFFIX: np.asarray([scale], np.float32),
        _TOPK_SHAPE_SUFFIX: np.asarray(a.shape, np.int64),
    }


def topk_dense(idx: np.ndarray, q: np.ndarray, scale, shape) -> np.ndarray:
    """Scatter a sparse triple back to a dense fp32 tensor."""
    out = np.zeros(int(np.prod(shape, dtype=np.int64)), np.float32)
    out[np.asarray(idx, np.int64)] = \
        np.asarray(q, np.float32) * np.float32(scale)
    return out.reshape(tuple(int(s) for s in shape))


class ErrorFeedback:
    """Worker-side error-feedback residual (1-bit SGD / EF-SGD lineage;
    PAPERS.md "Utility of Gradient Compression"): the quantization error of
    each push is kept and added to the next step's gradient, so the
    compressed updates sum to the true gradient over time — the property
    that makes int4 and top-k sparsification accuracy-safe."""

    def __init__(self):
        self._residual: dict[str, np.ndarray] = {}

    def add_to(self, name: str, grad: np.ndarray) -> np.ndarray:
        r = self._residual.get(name)
        g = np.asarray(grad, np.float32)
        return g if r is None else g + r

    def store(self, name: str, total: np.ndarray,
              decoded: np.ndarray) -> None:
        self._residual[name] = np.asarray(total, np.float32) \
            - np.asarray(decoded, np.float32)

    def reset(self) -> None:
        self._residual.clear()


def compress_push(tensors: Mapping[str, np.ndarray],
                  plan: Mapping[str, str] | None = None,
                  scales: Mapping[str, float] | None = None,
                  ef: ErrorFeedback | None = None,
                  topk_frac: float = 0.01) -> dict:
    """Encode a push payload per-layer: ``plan[name]`` picks
    ``'int8' | 'int4' | 'topk' | 'none'`` (default int8). ``scales`` is the
    server-published per-layer ABSMAX table (shared-scale quantization —
    when present for a layer, int8/int4 quantize against it so the server
    can accumulate in the integer domain); ``ef`` threads the
    error-feedback residual through every quantized layer."""
    plan = plan or {}
    scales = scales or {}
    out: dict = {}
    for name, a in tensors.items():
        kind = plan.get(name, "int8")
        a32 = np.asarray(a, np.float32)
        if kind == "none":
            out[name] = a32
            continue
        total = ef.add_to(name, a32) if ef is not None else a32
        absmax = scales.get(name)
        if kind == "topk":
            triple = topk_compress_tensor(total, frac=topk_frac)
            for suffix, arr in triple.items():
                out[name + suffix] = arr
            if ef is not None:
                ef.store(name, total, topk_dense(
                    triple[_TOPK_IDX_SUFFIX], triple[_TOPK_VAL_SUFFIX],
                    triple[_TOPK_SCALE_SUFFIX][0], total.shape))
        elif kind == "int4":
            scale = np.float32(absmax / 7.0) \
                if absmax and absmax > 0 else None
            packed, scale = int4_quantize(total, scale)
            out[name] = packed
            out[name + _INT4_SCALE_SUFFIX] = \
                np.asarray([scale], np.float32)
            if ef is not None:
                ef.store(name, total, int4_dequantize(packed, scale))
        else:  # int8
            if absmax and absmax > 0:
                scale = np.float32(absmax / 127.0)
                q = int8_quantize_with_scale(total, scale)
            else:
                q, scale = int8_quantize(total)
            out[name] = q
            out[name + _SCALE_SUFFIX] = np.asarray([scale], np.float32)
            if ef is not None:
                ef.store(name, total, int8_dequantize(q, scale))
    return out


def _iter_logical(tensors: Mapping[str, np.ndarray]):
    """Yield ``(name, kind, payload)`` logical entries of a (possibly
    quantized) named-tensor payload. ``payload``: int8 -> (q, scale);
    int4 -> (packed, scale); topk -> (idx, q, scale, shape);
    dense -> the array."""
    for name, a in tensors.items():
        if any(name.endswith(s) for s in _COMPANION_SUFFIXES):
            if name.endswith(_TOPK_IDX_SUFFIX):
                base = name[:-len(_TOPK_IDX_SUFFIX)]
                scale = tensors.get(base + _TOPK_SCALE_SUFFIX)
                shape = tensors.get(base + _TOPK_SHAPE_SUFFIX)
                q = tensors.get(base + _TOPK_VAL_SUFFIX)
                if scale is None or shape is None or q is None:
                    raise ValueError(
                        f"topk entry {base!r} missing companions")
                idx = np.asarray(a)
                q = np.asarray(q)
                lshape = tuple(int(s) for s in np.asarray(shape))
                # Validate HERE, not at consumption time: a malformed
                # sparse push must be refused at the push that carried it
                # — an out-of-range index surfacing later, inside the
                # round-completing scatter, would fail a DIFFERENT
                # worker's RPC and throw away the whole round.
                n = int(np.prod(lshape, dtype=np.int64))
                if idx.size != q.size:
                    raise ValueError(
                        f"topk entry {base!r}: {idx.size} indices vs "
                        f"{q.size} values")
                if idx.size and not np.issubdtype(idx.dtype, np.integer):
                    raise ValueError(
                        f"topk entry {base!r}: non-integer indices "
                        f"({idx.dtype})")
                if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= n):
                    raise ValueError(
                        f"topk entry {base!r}: index out of range for "
                        f"shape {lshape}")
                yield base, "topk", (idx, q,
                                     np.float32(np.asarray(scale)[0]),
                                     lshape)
            continue
        if isinstance(a, PackedInt4):
            scale = tensors.get(name + _INT4_SCALE_SUFFIX)
            if scale is None:
                raise ValueError(f"int4 wire entry {name!r} missing its "
                                 f"{_INT4_SCALE_SUFFIX} companion")
            yield name, "int4", (a, np.float32(np.asarray(scale)[0]))
            continue
        a = np.asarray(a)
        if a.dtype == np.int8:
            scale = tensors.get(name + _SCALE_SUFFIX)
            if scale is None:
                raise ValueError(f"int8 wire entry {name!r} missing its "
                                 f"{_SCALE_SUFFIX} companion")
            yield name, "int8", (a, np.float32(np.asarray(scale)[0]))
            continue
        yield name, "dense", a


def is_quantized_payload(tensors: Mapping[str, np.ndarray]) -> bool:
    """True when the payload carries any quantized (int8/int4/topk)
    entries — cheap key/dtype scan, no decode."""
    for name, a in tensors.items():
        if any(name.endswith(s) for s in _COMPANION_SUFFIXES):
            return True
        if isinstance(a, PackedInt4):
            return True
        if isinstance(a, np.ndarray) and a.dtype == np.int8:
            return True
    return False


def payload_logical_shapes(tensors: Mapping[str, np.ndarray]
                           ) -> dict[str, tuple]:
    """Logical (dequantized) tensor shapes of a payload, WITHOUT decoding
    — the store's shape guard runs on these for quantized pushes."""
    return {name: (payload[0].logical_shape if kind == "int4"
                   else payload[3] if kind == "topk"
                   else np.asarray(payload[0] if kind == "int8"
                                   else payload).shape)
            for name, kind, payload in _iter_logical(tensors)}


def wire_decompress(tensors: Mapping[str, np.ndarray]) -> dict:
    """Decode ANY push payload to dense fp32: int8/int4/topk entries
    dequantize with their carried scales, fp16/bf16 cast up, fp32 passes
    through without copying. The async apply path uses this (one incoming
    tensor dict, dequantized at apply time with its carried scale)."""
    out: dict = {}
    for name, kind, payload in _iter_logical(tensors):
        if kind == "int8":
            out[name] = int8_dequantize(*payload)
        elif kind == "int4":
            out[name] = int4_dequantize(*payload)
        elif kind == "topk":
            out[name] = topk_dense(*payload)
        else:
            out[name] = np.asarray(payload).astype(np.float32, copy=False)
    return out


def homomorphic_mean(grad_dicts: list) -> dict:
    """Compressed-domain sync aggregation (THC-style; PAPERS.md
    arXiv:2302.08545): the per-worker mean of possibly-quantized payloads
    WITHOUT a per-push fp32 decode.

    int8 and int4 entries accumulate in per-layer **int32** accumulators,
    grouped by their carried scale (shared-scale pushes all land in one
    group — one dequantize per layer per ROUND); entries that don't share
    a scale, plus top-k and dense entries, fold into an fp32 side
    accumulator. Semantics mirror :func:`...ps.semantics.mean_gradients`:
    parameter names come from the first worker's push, each averaged over
    only the workers that supplied it."""
    if not grad_dicts:
        return {}
    parsed = []
    for d in grad_dicts:
        parsed.append({name: (kind, payload)
                       for name, kind, payload in _iter_logical(d)})
    out: dict = {}
    for name in parsed[0]:
        int_groups: dict[float, np.ndarray] = {}
        f32_acc = None
        shape = None
        valid = 0
        for p in parsed:
            entry = p.get(name)
            if entry is None:
                continue
            kind, payload = entry
            valid += 1
            if kind in ("int8", "int4"):
                if kind == "int8":
                    q, scale = payload
                    if shape is None:
                        shape = q.shape
                    q = q.reshape(-1)
                else:
                    packed, scale = payload
                    if shape is None:
                        shape = packed.logical_shape
                    q = unpack_nibbles(
                        np.asarray(packed, np.uint8),
                        int(np.prod(packed.logical_shape,
                                    dtype=np.int64)))
                key = float(scale)
                acc = int_groups.get(key)
                if acc is None:
                    int_groups[key] = q.astype(np.int32)
                else:
                    acc += q  # int8 adds into the int32 accumulator
            else:
                if kind == "topk":
                    dense = topk_dense(*payload)
                else:
                    dense = np.asarray(payload, np.float32)
                if shape is None:
                    shape = dense.shape
                f32_acc = dense.reshape(-1).astype(np.float32, copy=True) \
                    if f32_acc is None else f32_acc + dense.reshape(-1)
        if valid == 0:
            continue
        total = f32_acc
        for scale, acc in int_groups.items():
            part = acc.astype(np.float32) * np.float32(scale)
            total = part if total is None else total + part
        out[name] = (total / np.float32(valid)).reshape(shape)
    return out
