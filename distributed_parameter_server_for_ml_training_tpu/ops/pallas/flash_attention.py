"""Fused (flash) attention as Pallas TPU kernels, with a custom VJP.

Net-new TPU capability (round-2 VERDICT item 5): the reference has no
attention anywhere (its model layer is a CNN, SURVEY.md §2.6); this is the
fused core for the framework's transformer path — the same
``[B, T, H, D] x3 -> [B, T, H, D]`` contract as
parallel/ring_attention.dense_attention, so it drops into
models/vit.py:SelfAttention via ``attention_fn`` and serves as the per-hop
block kernel inside ring attention.

Design (standard flash attention, TPU-shaped):

- forward: grid over (batch*heads, T/BLOCK_Q); each program streams K/V
  through VMEM in BLOCK_K tiles, keeping the online-softmax running
  (max, sum, acc) in registers — the [T, T] score matrix never
  materializes. Saves the per-row logsumexp for the backward.
- backward: two kernels re-using the saved LSE (no softmax recompute
  ambiguity): dQ tiles over query blocks, dK/dV tiles over key blocks,
  each streaming the opposite operand. delta = rowsum(dO * O) is a cheap
  elementwise precompute.
- sequence lengths that aren't block multiples are zero-padded; padded KEY
  positions are masked to -inf in every kernel, padded QUERY rows fall out
  of the backward because their dO/delta are zero.

Off TPU the same math runs as a jnp fallback (exact dense formulation with
identical masking), which is what the CPU test suite exercises; kernel-vs-
fallback parity on real hardware is asserted by tests/test_flash_attention.py
when a TPU is attached (and by experiments/ on-chip runs).

VMEM sizing: each program holds full K and V for one (batch, head) — at
D=64 fp32 that bounds T at ~8k per chip; beyond that, shard the sequence
with ring attention (parallel/ring_attention.py), which calls this kernel
per hop on T/N-sized blocks.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30
# Measured on-chip (experiments/measure_mfu.py block sweep): 512-wide tiles
# nearly halve the backward at T>=2048 vs 128 (bigger serial-loop bodies
# keep the MXU fed); short sequences clamp down so padding stays small.
MAX_BLOCK = 512

# Fallback when no measured crossover has been recorded (conservative:
# well above the short-sequence regime where dense decisively wins; the
# measured file usually records a smaller value — 512 on the round-4
# chip).
DEFAULT_CROSSOVER_T = 2048
_CROSSOVER_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "attn_crossover.json")

# Run the Pallas kernels in interpreter mode (CPU emulation of the exact
# kernel code, loop bounds and SMEM scalars included). Tests flip this to
# exercise the kernel-side logic without a chip; never set on TPU.
INTERPRET = False


def _on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


@lru_cache(maxsize=1)
def _crossover_record() -> dict:
    try:
        with open(_CROSSOVER_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def flash_crossover() -> int:
    """Measured dense->flash crossover sequence length.

    Read from ``attn_crossover.json`` next to this module — REGENERATED (not
    hand-coded) by ``experiments/measure_mfu.py``, which times dense vs
    Pallas fwd+bwd across sequence lengths on the attached chip and records
    the smallest T from which flash sustains >= 0.95x dense (statistical
    ties break to flash: same wall clock, O(T) memory). Falls back to
    ``DEFAULT_CROSSOVER_T`` when the file is absent.
    """
    try:
        return int(_crossover_record()["crossover_t"])
    except (KeyError, ValueError, TypeError):
        return DEFAULT_CROSSOVER_T


# The tie threshold shared by the MEASUREMENT side (experiments/
# measure_mfu.py derives crossover_t as "sustains >= this x dense") and
# the DISPATCH side (flash_preferred compares the padding-taxed speedup
# against it). One constant so the two can't drift.
FLASH_TIE_THRESHOLD = 0.95


def _measured_speedup(tp: int) -> float:
    """Flash fwd+bwd speedup vs dense at PADDED length ``tp``, piecewise-
    linearly interpolated over the recorded bench table (clamped to its
    edge values); 1.0 when no table was recorded (or it is malformed —
    same conservative fallback class as ``flash_crossover``)."""
    table = _crossover_record().get("measured_speedups_fwd_bwd") or {}
    try:
        pts = sorted((int(k), float(v)) for k, v in table.items())
    except (ValueError, TypeError):
        pts = []
    if not pts:
        return 1.0
    if tp <= pts[0][0]:
        return pts[0][1]
    if tp >= pts[-1][0]:
        return pts[-1][1]
    for (t0, s0), (t1, s1) in zip(pts, pts[1:]):
        if t0 <= tp <= t1:
            w = (tp - t0) / (t1 - t0)
            return s0 + w * (s1 - s0)
    return 1.0


def flash_preferred(t: int) -> bool:
    """True when the Pallas flash path is expected to BEAT dense attention
    at sequence length ``t`` on the attached backend.

    This is the dispatch predicate ``flash_attention`` (``use_pallas=None``)
    and ``train.model_parallel.SPTrainer`` consult, closing the round-3 gap
    where flash was auto-selected below its measured crossover and LOST to
    dense (ViT-B/16 @224px, 197 tokens: 28.4% vs 43.8% MFU).

    Non-128-multiple lengths pay a PADDING TAX the crossover table (which
    is measured at clean multiples) doesn't see: the kernel computes the
    padded length's FLOPs, so its effective speedup is the table value at
    the padded length times (t/t_padded)^2. Measured reality check
    (on-chip): T=576 pads to 640 -> flash 0.89x dense despite
    576 >= crossover 512. The predicate applies that tax and keeps the
    same >= 0.95 tie-break threshold.
    """
    if not _on_tpu() or t < flash_crossover():
        return False
    tp = -(-t // 128) * 128
    return (_measured_speedup(tp) * (t / tp) ** 2
            >= FLASH_TIE_THRESHOLD)


# -- forward ------------------------------------------------------------------

def _k_loop_hi(pos_ref, n_k: int, block_q: int, block_k: int, kv_len: int,
               causal: bool):
    """Upper bound (exclusive) of the K-block loop for the current query
    block: fully-padded K blocks (beyond ``kv_len``, static) are skipped
    outright, and under causal masking so are blocks entirely in the
    future of this query block's last GLOBAL row (dynamic — depends on
    the SMEM (q_offset, k_offset) scalars and the grid position)."""
    import jax.experimental.pallas as pl

    hi = min(n_k, -(-kv_len // block_k))           # static: skip padding
    if not causal:
        return hi
    row_max = pos_ref[0, 0] + (pl.program_id(1) + 1) * block_q - 1
    dyn = jnp.floor_divide(row_max - pos_ref[0, 1], block_k) + 1
    return jnp.clip(dyn, 0, hi)


def _fwd_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, block_q: int, block_k: int, kv_len: int,
                causal: bool):
    import jax.experimental.pallas as pl  # noqa: F401 (pl.ds below)

    q = q_ref[0]                                   # [BQ, D]
    bq = q.shape[0]
    n_k = k_ref.shape[1] // block_k
    # program_id is read OUTSIDE the loop body: the interpret-mode lowering
    # can't substitute it inside fori_loop sub-jaxprs (and hoisting is free
    # on the TPU path).
    pid_q = pl.program_id(1)

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :]      # [BK, D]
        vb = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [BQ, BK]
        col = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
        if causal:
            # Global positions: pos_ref holds (q_offset, k_offset) —
            # nonzero when this call is one hop of a sharded ring.
            row_g = pos_ref[0, 0] + pid_q * block_q \
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(pos_ref[0, 1] + col <= row_g, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                             # [BQ, BK]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m, l, acc = jax.lax.fori_loop(
        0, _k_loop_hi(pos_ref, n_k, block_q, block_k, kv_len, causal),
        body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _bwd_dq_kernel(pos_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref,
                   *, scale: float, block_q: int, block_k: int, kv_len: int,
                   causal: bool):
    import jax.experimental.pallas as pl  # noqa: F401

    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                       # [BQ, 1]
    delta = delta_ref[0]
    n_k = k_ref.shape[1] // block_k
    pid_q = pl.program_id(1)       # hoisted: see _fwd_kernel

    def body(i, dq):
        kb = k_ref[0, pl.ds(i * block_k, block_k), :]
        vb = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = col < kv_len
        if causal:
            row_g = pos_ref[0, 0] + pid_q * block_q \
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            keep = keep & (pos_ref[0, 1] + col <= row_g)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)          # [BQ, BK]
        dp = jax.lax.dot_general(
            do.astype(vb.dtype), vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq = jax.lax.fori_loop(
        0, _k_loop_hi(pos_ref, n_k, block_q, block_k, kv_len, causal),
        body, jnp.zeros(q.shape[:1] + (q.shape[1],), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(pos_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *,
                    scale: float, block_q: int, kv_len: int, q_len: int,
                    causal: bool):
    import jax.experimental.pallas as pl

    kb = k_ref[0]                                          # [BK, D]
    vb = v_ref[0]
    bk = kb.shape[0]
    col = pl.program_id(1) * bk + jax.lax.broadcasted_iota(
        jnp.int32, (1, bk), 1)                             # [1, BK] global
    n_q = q_ref.shape[1] // block_q
    # Padded QUERY blocks (beyond q_len) have zero dO/delta — skip them
    # (static); under causal masking also skip query blocks entirely
    # BEFORE this K block's first global column (dynamic).
    hi_q = min(n_q, -(-q_len // block_q))
    if causal:
        col0 = pos_ref[0, 1] + pl.program_id(1) * bk
        lo_q = jnp.clip(jnp.floor_divide(col0 - pos_ref[0, 0], block_q),
                        0, hi_q)
    else:
        lo_q = 0

    def body(j, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(j * block_q, block_q), :]      # [BQ, D]
        dob = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(j * block_q, block_q), :]   # [BQ, 1]
        delta = delta_ref[0, pl.ds(j * block_q, block_q), :]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [BQ, BK]
        keep = col < kv_len
        if causal:
            row_g = pos_ref[0, 0] + j * block_q \
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            keep = keep & (pos_ref[0, 1] + col <= row_g)
        p = jnp.where(keep, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BK, D]
        dp = jax.lax.dot_general(
            dob.astype(vb.dtype), vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BQ, BK]
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [BK, D]
        return dk, dv

    zero = jnp.zeros((bk, kb.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo_q, hi_q, body, (zero, zero))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# -- jnp fallback (identical masked math, dense) ------------------------------

def _position_mask(tq, tk, kv_len, causal, q_offset, k_offset):
    """[Tq, Tk] keep-mask combining the kv_len bound with (optionally) the
    causal constraint in GLOBAL positions (offsets are nonzero when the
    call is one hop of a sharded ring)."""
    keep = (jnp.arange(tk) < kv_len)[None, :]
    if causal:
        rows = q_offset + jnp.arange(tq)
        cols = k_offset + jnp.arange(tk)
        keep = keep & (cols[None, :] <= rows[:, None])
    return keep


def _dense_fwd(q, k, v, kv_len, scale, out_dtype=None,
               causal=False, q_offset=0, k_offset=0):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = _position_mask(q.shape[1], k.shape[1], kv_len, causal,
                          q_offset, k_offset)
    s = jnp.where(mask[None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bqk,bkd->bqd", p / l, v.astype(jnp.float32))
    lse = m + jnp.log(l)           # [BH, T, 1]
    return o.astype(out_dtype or q.dtype), lse


def pick_block(t: int) -> int:
    """Largest 128-multiple <= MAX_BLOCK dividing ``t`` (kernel grids
    floor-divide, so the block must divide the length exactly)."""
    if t % 128:
        raise ValueError(
            f"sequence block length {t} must be a multiple of 128 (TPU "
            f"tile); pad the sequence or pick a shard count that divides "
            f"it into 128-multiples")
    return max(b for b in range(128, MAX_BLOCK + 1, 128) if t % b == 0)


# -- core op on [BH, T_pad, D] with custom VJP --------------------------------

def _pos_scalars(q_offset, k_offset):
    """(1, 2) int32 SMEM payload carrying the global (q, k) offsets."""
    return jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)]).reshape(1, 2)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, kv_len, block_q, block_k, use_pallas, causal):
    o, _ = _flash_fwd_impl(q, k, v, kv_len, block_q, block_k, use_pallas,
                           causal=causal)
    return o


def _flash_fwd_impl(q, k, v, kv_len, block_q, block_k, use_pallas,
                    out_dtype=None, causal=False, q_offset=0, k_offset=0):
    bh, tp, d = q.shape
    scale = 1.0 / np.sqrt(d)
    if not use_pallas:
        # out_dtype reaches the FINAL cast — an intermediate round-trip
        # through q.dtype would quantize the fp32 partials the ring merge
        # depends on.
        return _dense_fwd(q, k, v, kv_len, scale, out_dtype,
                          causal, q_offset, k_offset)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_q = tp // block_q
    blk_pos = pl.BlockSpec(memory_space=pltpu.SMEM)
    blk_q = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    blk_full = pl.BlockSpec((1, tp, d), lambda b, i: (b, 0, 0),
                            memory_space=pltpu.VMEM)
    # LSE rides as [BH, T, 1]: a (1, BLOCK_Q, 1) block keeps the last
    # two dims tileable ((BLOCK_Q, 1): sublanes % 8 == 0, lane dim == array).
    blk_lse = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    o, lse = pl.pallas_call(
        partial(_fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
                kv_len=kv_len, causal=causal),
        grid=(bh, n_q),
        in_specs=[blk_pos, blk_q, blk_full, blk_full],
        out_specs=(blk_q, blk_lse),
        out_shape=(jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
                   jax.ShapeDtypeStruct((bh, tp, 1), jnp.float32)),
        interpret=INTERPRET,
    )(_pos_scalars(q_offset, k_offset), q, k, v)
    return o, lse


def _flash_core_fwd(q, k, v, kv_len, block_q, block_k, use_pallas, causal):
    o, lse = _flash_fwd_impl(q, k, v, kv_len, block_q, block_k, use_pallas,
                             causal=causal)
    return o, (q, k, v, o, lse)


def _flash_bwd_impl(q, k, v, do, lse, delta, kv_len, block_q, block_k,
                    use_pallas, out_dtype=None,
                    causal=False, q_offset=0, k_offset=0, q_len=None):
    """Flash backward given EXTERNAL (lse, delta) — shared by the custom
    VJP below and by ring attention's per-hop backward
    (parallel/ring_attention.py), where lse/delta come from the MERGED
    softmax over the whole ring. ``out_dtype`` overrides the gradient
    dtype (the ring accumulates partials in fp32). ``q_len`` is the
    UNPADDED query length (padded query rows carry zero dO/delta, so the
    dK/dV kernel skips those blocks); defaults to the padded length,
    i.e. no skipping."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    q_len = tq if q_len is None else q_len
    scale = 1.0 / np.sqrt(d)
    dts = [out_dtype or x.dtype for x in (q, k, v)]
    if not use_pallas:
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        dof = do.astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
        mask = _position_mask(tq, tk, kv_len, causal, q_offset, k_offset)
        p = jnp.where(mask[None], jnp.exp(s - lse), 0.0)
        dv = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
        ds = p * (dp - delta)
        dq = jnp.einsum("bqk,bkd->bqd", ds, kf) * scale
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
        return (dq.astype(dts[0]), dk.astype(dts[1]), dv.astype(dts[2]))

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blk_q = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    blk_k = pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    blk_qfull = pl.BlockSpec((1, tq, d), lambda b, i: (b, 0, 0),
                             memory_space=pltpu.VMEM)
    blk_kfull = pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0),
                             memory_space=pltpu.VMEM)
    blk_row_q = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    blk_row_qfull = pl.BlockSpec((1, tq, 1), lambda b, i: (b, 0, 0),
                                 memory_space=pltpu.VMEM)

    blk_pos = pl.BlockSpec(memory_space=pltpu.SMEM)
    pos = _pos_scalars(q_offset, k_offset)

    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                block_k=block_k, kv_len=kv_len, causal=causal),
        grid=(bh, tq // block_q),
        in_specs=[blk_pos, blk_q, blk_kfull, blk_kfull, blk_q, blk_row_q,
                  blk_row_q],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct(q.shape, dts[0]),
        interpret=INTERPRET,
    )(pos, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                kv_len=kv_len, q_len=q_len, causal=causal),
        grid=(bh, tk // block_k),
        in_specs=[blk_pos, blk_qfull, blk_k, blk_k, blk_qfull,
                  blk_row_qfull, blk_row_qfull],
        out_specs=(blk_k, blk_k),
        out_shape=(jax.ShapeDtypeStruct(k.shape, dts[1]),
                   jax.ShapeDtypeStruct(v.shape, dts[2])),
        interpret=INTERPRET,
    )(pos, q, k, v, do, lse, delta)
    return dq, dk, dv


def _flash_core_bwd(kv_len, block_q, block_k, use_pallas, causal, res, do):
    q, k, v, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # [BH, T, 1]
    # Self-attention: q and k share the unpadded length, so q_len=kv_len.
    return _flash_bwd_impl(q, k, v, do, lse, delta, kv_len, block_q,
                           block_k, use_pallas, causal=causal,
                           q_len=kv_len)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# -- public op ----------------------------------------------------------------

def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    use_pallas: bool | None = None) -> jax.Array:
    """Fused attention over ``[B, T, H, D]`` q/k/v (causal optional).

    Same contract as parallel/ring_attention.dense_attention — plug into
    models/vit.py:SelfAttention via ``attention_fn=flash_attention`` (or
    partial(...) to pin block sizes). Differentiable (custom VJP, flash
    backward). T is padded to a block multiple internally; default block
    sizes adapt to T (128-tile-rounded, capped at MAX_BLOCK).

    ``use_pallas=None`` (the default) dispatches on the MEASURED
    dense/flash crossover (``flash_preferred``): below it the dispatch
    returns the PLAIN dense formulation under native XLA autodiff —
    short sequences are dominated by the padding + fusion-barrier
    overhead of a custom kernel, and even the custom-VJP fallback costs
    ~7% vs letting XLA fuse the backward itself (measured, ViT-B/16
    @224: 762 vs 822 img/s). Explicit True/False overrides force the
    Pallas kernels / the custom-VJP fallback (the CPU tests exercise
    the latter's kernel-identical math).
    """
    b, t, h, d = q.shape
    for name, blk in (("block_q", block_q), ("block_k", block_k)):
        if blk is not None and (blk <= 0 or blk % 128):
            raise ValueError(
                f"{name}={blk} must be a positive multiple of 128 (TPU "
                f"tile constraint; defaults via pick_block satisfy it)")
    if use_pallas is None:
        if not flash_preferred(t):
            # THE shared dense core (ops/attention.dense_core) — the same
            # function models/vit.py:SelfAttention runs with no
            # attention_fn, so below the crossover
            # ``attention_fn=flash_attention`` compiles to the identical
            # program (asserted bitwise by tests). Upcasting (fp32 logits
            # or fp32 q/k/v) costs 7-10% of the ViT-B/16 @224 step: the
            # fp32 cotangents push the backward matmuls off the bf16 MXU
            # rate (measured 740-753 vs 813-823 img/s).
            from ..attention import dense_core
            return dense_core(q, k, v, causal=causal)
        use_pallas = True
    # Default blocks: the largest 128-multiple <= MAX_BLOCK that DIVIDES the
    # 128-rounded sequence length — a bare min() would pad e.g. T=768 up to
    # 1024 (1.78x the attention FLOPs); 384 divides it exactly.
    tp128 = -(-t // 128) * 128
    if block_q is None:
        block_q = pick_block(tp128)
    if block_k is None:
        block_k = pick_block(tp128)
    # Pad to a multiple of BOTH block sizes — the kernels floor-divide the
    # padded length by each, so a non-divisible combination would silently
    # skip trailing blocks.
    block = np.lcm(block_q, block_k)
    tp = -(-t // block) * block

    def to3(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)
        return jnp.pad(x, ((0, 0), (0, tp - t), (0, 0))) if tp != t else x

    o3 = _flash_core(to3(q), to3(k), to3(v), t, block_q, block_k,
                     bool(use_pallas), bool(causal))
    o = o3[:, :t].reshape(b, h, t, d)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)
