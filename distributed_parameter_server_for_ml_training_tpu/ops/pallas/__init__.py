"""Pallas TPU kernels for the framework's hot numerics ops."""

from .quantize import (
    dequantize_int8,
    quantize_dequantize_int8,
    quantize_int8,
)

__all__ = ["quantize_int8", "dequantize_int8", "quantize_dequantize_int8"]
