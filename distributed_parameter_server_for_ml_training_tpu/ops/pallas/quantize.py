"""Block-wise int8 gradient quantization as Pallas TPU kernels.

The reference's gradient compression is an fp16 cast (worker.py:264-268,
~50% bytes). This is the stronger TPU-native analogue: symmetric int8 with a
per-block scale (~75% fewer bytes than fp32), quantized/dequantized on device
so only int8 + scales cross HBM/ICI/host boundaries. Used by

- the ``compression='int8'`` sync all-reduce mode (parallel/sync_dp.py):
  int8 payloads on every hop of a reduce-scatter + all-gather ring
  (EQuARX-style quantized collective; PAPERS.md prior art),
- the async wire path (ops/compression.py int8 tree codec is the host-side
  equivalent for store payloads).

Kernel layout: input is flattened and viewed as [rows, 128] (VPU lanes),
grid over row-blocks of BLOCK_ROWS; each block gets one fp32 scale computed
from its abs-max. On TPU, stochastic rounding uses the on-core PRNG
(pltpu.prng_random_bits); round-to-nearest is the deterministic default.
Both kernels fall back to identical-math jnp implementations off-TPU (and
power the unit tests via interpret-free CPU execution).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128
BLOCK_ROWS = 256  # 256x128 fp32 = 128 KiB per block in VMEM


def block_rows_for(rows_padded: int) -> int:
    """Quantization block height for a [rows_padded, 128] view.

    Large inputs tile in BLOCK_ROWS blocks; inputs at or below one block
    are a SINGLE block of their own (32-row-aligned: the int8 native TPU
    tile is (32, 128)) — padding a 1/N-sized ring chunk up to 32768
    elements would otherwise dominate the wire bytes for small models
    (parallel/sync_dp.py int8 ring). Both quantize and dequantize derive
    the layout from this rule, so the pair stays consistent without
    shipping the block size. Empty inputs (rows_padded == 0) get the
    minimum 32-row block so callers' ``rows // br`` stays well-defined
    (0 blocks) instead of dividing by zero."""
    if rows_padded == 0:
        return 32
    return rows_padded if rows_padded <= BLOCK_ROWS else BLOCK_ROWS


def _pad_to_blocks(x: jax.Array) -> tuple[jax.Array, int, int]:
    """Flatten to [rows, 128]; rows 32-aligned (single block) for small
    inputs, a BLOCK_ROWS multiple otherwise."""
    n = x.size
    rows = -(-n // LANES)
    if rows <= BLOCK_ROWS:
        rows_padded = -(-rows // 32) * 32
    else:
        rows_padded = -(-rows // BLOCK_ROWS) * BLOCK_ROWS
    flat = jnp.zeros((rows_padded * LANES,), jnp.float32)
    flat = flat.at[:n].set(x.reshape(-1).astype(jnp.float32))
    return flat.reshape(rows_padded, LANES), n, rows_padded


def _on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


# -- kernels ------------------------------------------------------------------

def _quantize_kernel(x_ref, values_ref, scales_ref, *, stochastic: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = x_ref[:]
    abs_max = jnp.max(jnp.abs(block))
    scale = jnp.where(abs_max > 0, abs_max / 127.0, 1.0)
    # scales live whole in SMEM (scalar-per-block outputs can't be tiled);
    # each grid step writes its own slot.
    scales_ref[pl.program_id(0), 0] = scale
    scaled = block / scale
    if stochastic:
        # floor(x + u), u ~ U[0,1): rounds k+f up with probability f —
        # unbiased. (pltpu.stochastic_round targets only bf16/fp8 dtypes in
        # this JAX, so int8 needs the manual form.)
        # Mosaic can't cast uint32->f32; go via int32 with a mask to keep
        # the value in [0, 2^24).
        random_bits = pltpu.bitcast(
            pltpu.prng_random_bits(scaled.shape), jnp.int32)
        u = ((random_bits >> 8) & 0x00FFFFFF).astype(jnp.float32) \
            * (1.0 / (1 << 24))
        values_ref[:] = jnp.clip(jnp.floor(scaled + u),
                                 -127, 127).astype(jnp.int8)
    else:
        values_ref[:] = jnp.clip(jnp.rint(scaled), -127, 127).astype(jnp.int8)


def _quantize_seed_kernel(seed_ref, x_ref, values_ref, scales_ref):
    from jax.experimental.pallas import tpu as pltpu

    pltpu.prng_seed(seed_ref[0])
    _quantize_kernel(x_ref, values_ref, scales_ref, stochastic=True)


def _dequantize_kernel(values_ref, scales_ref, out_ref):
    import jax.experimental.pallas as pl

    out_ref[:] = (values_ref[:].astype(jnp.float32)
                  * scales_ref[pl.program_id(0), 0])


# -- public ops ---------------------------------------------------------------

@partial(jax.jit, static_argnames=("stochastic", "use_pallas"))
def quantize_int8(x: jax.Array, seed: jax.Array | int = 0, *,
                  stochastic: bool = False,
                  use_pallas: bool | None = None):
    """x (any shape) -> (values int8 [rows,128], scales fp32 [blocks]).

    The caller keeps ``x.shape`` to reconstruct (dequantize_int8 takes it
    statically).
    """
    if x.size == 0:  # empty gradients quantize to empty wire payloads
        return (jnp.zeros((0, LANES), jnp.int8), jnp.zeros((0,), jnp.float32))
    xb, n, rows = _pad_to_blocks(x)
    br = block_rows_for(rows)
    n_blocks = rows // br
    if use_pallas is None:
        use_pallas = _on_tpu()

    if use_pallas:
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        out_shapes = (
            jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
            jax.ShapeDtypeStruct((n_blocks, 1), jnp.float32),
        )
        block_in = pl.BlockSpec((br, LANES), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)
        block_vals = pl.BlockSpec((br, LANES), lambda i: (i, 0),
                                  memory_space=pltpu.VMEM)
        # whole scales array in SMEM for every step (untiled scalar slots)
        block_scale = pl.BlockSpec((n_blocks, 1), lambda i: (0, 0),
                                   memory_space=pltpu.SMEM)
        if stochastic:
            values, scales = pl.pallas_call(
                _quantize_seed_kernel,
                grid=(n_blocks,),
                in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), block_in],
                out_specs=(block_vals, block_scale),
                out_shape=out_shapes,
            )(jnp.atleast_1d(jnp.asarray(seed, jnp.int32)), xb)
        else:
            values, scales = pl.pallas_call(
                partial(_quantize_kernel, stochastic=False),
                grid=(n_blocks,),
                in_specs=[block_in],
                out_specs=(block_vals, block_scale),
                out_shape=out_shapes,
            )(xb)
        return values, scales.reshape(n_blocks)

    # jnp fallback: identical deterministic math (stochastic ignored).
    blocks = xb.reshape(n_blocks, br * LANES)
    abs_max = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(abs_max > 0, abs_max / 127.0, 1.0)
    q = jnp.clip(jnp.rint(blocks / scales[:, None]), -127, 127)
    return q.astype(jnp.int8).reshape(rows, LANES), scales


@partial(jax.jit, static_argnames=("shape", "use_pallas"))
def dequantize_int8(values: jax.Array, scales: jax.Array,
                    shape: tuple, *, use_pallas: bool | None = None):
    """Inverse of :func:`quantize_int8`; ``shape`` is the original
    (static) array shape."""
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if n == 0:
        return jnp.zeros(shape, jnp.float32)
    rows = values.shape[0]
    br = block_rows_for(rows)
    n_blocks = rows // br
    if use_pallas is None:
        use_pallas = _on_tpu()

    if use_pallas:
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        out = pl.pallas_call(
            _dequantize_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((br, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_blocks, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec((br, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        )(values, scales.reshape(n_blocks, 1))
    else:
        blocks = values.reshape(n_blocks, br * LANES)
        out = (blocks.astype(jnp.float32)
               * scales.reshape(n_blocks, 1)).reshape(rows, LANES)

    flat = out.reshape(-1)[:n]
    return flat.reshape(shape)


def quantize_dequantize_int8(x: jax.Array, *, stochastic: bool = False,
                             seed: int = 0,
                             use_pallas: bool | None = None) -> jax.Array:
    """Round-trip (the quantization error a gradient would incur)."""
    v, s = quantize_int8(x, seed, stochastic=stochastic,
                         use_pallas=use_pallas)
    return dequantize_int8(v, s, tuple(x.shape), use_pallas=use_pallas)


# -- fused wire-codec kernels (device-resident push codec) --------------------
#
# The wire codec family (ops/compression.py int8/int4/topk) is the NumPy
# host reference: every quantized push starts with a full fp32 device_get
# BEFORE the bytes shrink. These kernels run the SAME math on device, bit
# identical to the reference (true division — never a reciprocal multiply,
# which double-rounds; jnp.rint == np.rint round-half-even; identical clip
# bounds), so only the already-quantized wire buffers cross the link. Tree
# orchestration (host-computed scales, error feedback, the single packed
# bytes pull) lives in ops/device_codec.py; these are the per-tensor
# primitives it traces into its phase programs. Only the quantize runs as
# a Pallas kernel — the nibble pack and top-k select stay jnp inside the
# same jit program (XLA fuses them; Mosaic has no win for lane-pair bit
# twiddling), which also serves as the CPU tier-1 fallback.

# Below ~64k elements the pallas_call launch costs more than the fused XLA
# elementwise it replaces; small tensors stay on the jnp path even on TPU.
PALLAS_WIRE_MIN_SIZE = 65536


def _wire_quantize_kernel(scale_ref, x_ref, values_ref, *, levels: int):
    # One fp32 block / one shared SMEM scale -> int8 codes in [-levels,
    # levels]. The divide must stay a true divide for bit-identity with
    # the NumPy reference codec.
    values_ref[:] = jnp.clip(jnp.rint(x_ref[:] / scale_ref[0]),
                             -levels, levels).astype(jnp.int8)


def wire_quantize_flat(x2d: jax.Array, scale: jax.Array, levels: int,
                       use_pallas: bool) -> jax.Array:  # dpslint: hot-path device
    """[rows,128] fp32 + scalar scale -> [rows,128] int8 codes.

    Traced inside the device codec's phase programs (and the jitted
    :func:`wire_quantize` wrapper) — not jitted itself. ``levels`` is 127
    for int8 wire codes, 7 for int4 nibble codes.
    """
    rows = x2d.shape[0]
    scale = jnp.asarray(scale, jnp.float32)
    if use_pallas and rows:
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        br = block_rows_for(rows)
        return pl.pallas_call(
            partial(_wire_quantize_kernel, levels=levels),
            grid=(rows // br,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec((br, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((br, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        )(scale.reshape(1), x2d)
    return jnp.clip(jnp.rint(x2d / scale), -levels, levels).astype(jnp.int8)


def pack_nibbles_device(q: jax.Array) -> jax.Array:  # dpslint: hot-path device
    """int8 codes in [-8, 7] (any shape) -> packed uint8, flat ceil(n/2).

    Bit-identical to ops/packed.py:pack_nibbles: low nibble = even flat
    index, odd length padded with a zero code. Traced (not jitted) so the
    device codec fuses it into the quantize program.
    """
    flat = q.reshape(-1)
    if flat.size % 2:
        flat = jnp.concatenate([flat, jnp.zeros((1,), jnp.int8)])
    pairs = flat.reshape(-1, 2)
    lo = pairs[:, 0].astype(jnp.uint8) & 0x0F
    hi = (pairs[:, 1].astype(jnp.uint8) & 0x0F) << 4
    return lo | hi


def topk_select_flat(x: jax.Array, k: int):  # dpslint: hot-path device
    """Flat top-k by |value|: (sorted int32 indices, fp32 values).

    jax.lax.top_k + ascending index sort — identical to the NumPy
    reference's argpartition+sort selection whenever the k-th magnitude
    is unique (equal-magnitude ties at the boundary tie-break by index
    here, unspecified there; continuous gradients don't tie). Traced,
    not jitted.
    """
    flat = x.reshape(-1).astype(jnp.float32)
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    idx = jnp.sort(idx).astype(jnp.int32)
    return idx, jnp.take(flat, idx)


@partial(jax.jit, static_argnames=("levels", "use_pallas"))
def wire_quantize(x: jax.Array, scale, *, levels: int = 127,
                  use_pallas: bool | None = None) -> jax.Array:
    """Tensor + scalar scale -> int8 wire codes with the tensor's shape.

    Jitted per-tensor convenience surface over :func:`wire_quantize_flat`
    (tests, microbench). The device codec uses the flat form directly so
    a whole gradient tree compiles as one program.
    """
    if use_pallas is None:
        use_pallas = _on_tpu() and x.size >= PALLAS_WIRE_MIN_SIZE
    if x.size == 0:
        return jnp.zeros(x.shape, jnp.int8)
    xb, n, _ = _pad_to_blocks(x)
    q = wire_quantize_flat(xb, scale, levels, use_pallas)
    return q.reshape(-1)[:n].reshape(x.shape)
