"""The framework's ONE dense attention core (input-dtype, MXU-native).

``dense_core`` is the softmax-attention formulation every dense path
shares: logits in the INPUT dtype (bf16 matmuls stay on the fast MXU
path — fp32 upcasts cost a measured 7-10% of a ViT-B/16 @224 step),
softmax in fp32, probabilities cast back. Users:

- models/vit.py:SelfAttention (the default core when no ``attention_fn``),
- ops/pallas/flash_attention.flash_attention's below-crossover dispatch
  (so ``attention_fn=flash_attention`` compiles to the IDENTICAL program
  below the crossover — asserted bitwise by tests/test_flash_attention),
- experiments/measure_mfu.py's crossover bench dense arm (the baseline
  the Pallas kernel must beat is the core the dispatch actually runs,
  not the fp32-upcast test reference in parallel/ring_attention).

Kept dependency-free (jnp only) so models, ops and experiments can all
import it without cycles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def dense_core(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool = False) -> jax.Array:
    """[B, T, H, D] x3 -> [B, T, H, D] softmax attention in the input
    dtype (fp32 softmax)."""
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)
