"""Numerics ops: gradient compression and quantization."""

from .compression import (
    compress_for_allreduce,
    decompress_from_allreduce,
    fp16_compress,
    fp16_decompress,
    int8_quantize,
    int8_dequantize,
)

__all__ = [
    "compress_for_allreduce",
    "decompress_from_allreduce",
    "fp16_compress",
    "fp16_decompress",
    "int8_quantize",
    "int8_dequantize",
]
