"""Device-resident push codec: quantize + pack on the accelerator.

The NumPy codec family (ops/compression.py) is the host reference: every
quantized push starts with a full fp32 ``jax.device_get`` of the gradient
tree — ~45 MB across the link for ResNet-18 — and then single-core NumPy
quantize/pack arithmetic, all on the push critical path. This module keeps
the codec ON the device: the error-feedback residual carry, the quantize,
the int4 nibble pack, and the top-k select all run as jit-compiled device
programs (Pallas kernels for the quantize on TPU, identical-math jnp
elsewhere — ops/pallas/quantize.py), and the only bulk device->host
transfer is the final WIRE buffers (int4: ceil(n/2) bytes, 1/8 of the
fp32 pull; int8: 1/4; topk: ~frac of it).

Bit-identity contract (property-tested by tests/test_quantize.py): the
payload :meth:`DeviceCodec.encode` produces is byte-for-byte what
:func:`..ops.compression.compress_push` produces for the same gradients,
plan, shared-scale table, error-feedback history, and ``topk_frac`` — so
the server side (NumPy decode, homomorphic aggregation, the negotiation
matrix) is provably unaffected by which codec a worker runs. What makes
that hold:

- scales are computed ON THE HOST from device-reduced absmax scalars with
  the reference's exact expression (``np.float32(float(amax) / 127.0)``:
  a float64 divide then one fp32 round — a direct fp32 divide on device
  would double-round differently for ~1 in 2^29 amax values);
- quantization is a true division (never a reciprocal multiply) + fp32
  round-half-even (``jnp.rint`` == ``np.rint``) + the same clip bounds;
- nibble packing matches ops/packed.py bit for bit (low nibble = even
  flat index, odd length zero-padded);
- top-k selection (``jax.lax.top_k`` + ascending index sort) matches the
  NumPy argpartition+sort selection whenever the k-th magnitude is unique
  (boundary ties tie-break by index here, unspecified there; continuous
  gradient values don't tie);
- error-feedback residuals are ``total - decoded`` in fp32 on device —
  the same two arithmetic ops the NumPy ``ErrorFeedback.store`` runs.

Encode is two async dispatches (stats, then quantize+pack) around one
small host pull of the per-tensor absmax scalars; ``encode()`` also
starts ``copy_to_host_async()`` on every wire buffer, so by the time
``finalize()`` (typically the comms pipeline thread) assembles the NumPy
wire dict, the packed bytes are usually already on the host and the
training thread never blocked on any of it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .compression import (
    _INT4_SCALE_SUFFIX,
    _SCALE_SUFFIX,
    _TOPK_IDX_SUFFIX,
    _TOPK_SCALE_SUFFIX,
    _TOPK_SHAPE_SUFFIX,
    _TOPK_VAL_SUFFIX,
)
from .packed import as_packed_int4
from .pallas.quantize import (
    PALLAS_WIRE_MIN_SIZE,
    _on_tpu,
    _pad_to_blocks,
    pack_nibbles_device,
    topk_select_flat,
    wire_quantize_flat,
)

__all__ = ["DeviceCodec", "DevicePayload", "is_device_tree"]


def is_device_tree(tree: Any) -> bool:
    """True when every leaf is a jax.Array — the precondition for running
    the device codec without first paying the host pull it exists to
    avoid. NumPy-leaf trees take the negotiated NumPy fallback."""
    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves) and all(isinstance(a, jax.Array) for a in leaves)


# -- phase programs -----------------------------------------------------------
#
# Whole-tree jits (cached by tree structure + static plan) rather than
# per-tensor jitted calls: a ResNet-sized model would otherwise pay ~60
# tiny compilations per process. Plan/ks arrive as hashable tuples so a
# changed bitwidth plan retraces exactly once.

@partial(jax.jit, static_argnames=("plan", "ks", "use_ef"))
def _phase_stats(flat, residuals, plan, ks, use_ef):  # dpslint: hot-path device
    """Dispatch 1: EF-carried totals, per-tensor absmax, top-k selects."""
    ks = dict(ks)
    totals, amax, topk = {}, {}, {}
    for name, kind in plan:
        g = flat[name].astype(jnp.float32)
        r = residuals.get(name) if use_ef else None
        t = g if (r is None or kind == "none") else g + r
        totals[name] = t
        if kind == "none":
            continue
        # whole-tensor absmax doubles as the finite guard: NaN propagates
        # through max, inf survives it — isfinite(amax) on the host is
        # exactly the reference's _require_finite / np.all(isfinite).
        amax[name] = jnp.max(jnp.abs(t)) if t.size else jnp.zeros((), jnp.float32)
        if kind == "topk":
            topk[name] = topk_select_flat(t, ks[name])
    return totals, amax, topk


@partial(jax.jit, static_argnames=("plan", "use_pallas", "use_ef"))
def _phase_encode(totals, topk, scales, plan, use_pallas, use_ef):  # dpslint: hot-path device
    """Dispatch 2: quantize + pack against host-computed scales; emit the
    wire buffers (and, under EF, the DECODED dequantizations), all on
    device. The residual subtraction ``total - decoded`` runs in a
    separate program (:func:`_phase_residual`): fused into this one, XLA
    contracts the dequantize multiply and the subtract into a single
    rounded FMA — ~1 ulp off the NumPy reference's two roundings, enough
    to break the bit-identity contract (``lax.optimization_barrier`` and
    bitcast tricks do not survive the LLVM-level contraction)."""
    wire, decoded = {}, {}
    for name, kind in plan:
        t = totals[name]
        if kind == "none":
            wire[name] = t
            continue
        s = scales[name]
        if kind == "topk":
            idx, vals = topk[name]
            q = jnp.clip(jnp.rint(vals / s), -127, 127).astype(jnp.int8)
            wire[name + _TOPK_IDX_SUFFIX] = idx
            wire[name + _TOPK_VAL_SUFFIX] = q
            if use_ef:
                decoded[name] = jnp.zeros((t.size,), jnp.float32) \
                    .at[idx].set(q.astype(jnp.float32) * s).reshape(t.shape)
            continue
        levels = 7 if kind == "int4" else 127
        xb, n, _ = _pad_to_blocks(t)
        q = wire_quantize_flat(
            xb, s, levels,
            use_pallas and n >= PALLAS_WIRE_MIN_SIZE).reshape(-1)[:n]
        if kind == "int4":
            wire[name] = pack_nibbles_device(q)
        else:
            wire[name] = q.reshape(t.shape)
        if use_ef:
            decoded[name] = (q.astype(jnp.float32) * s).reshape(t.shape)
    return wire, decoded


@jax.jit
def _phase_residual(totals, decoded):  # dpslint: hot-path device
    """Dispatch 3 (EF only): next residuals = total - decoded, with the
    decoded values already materialized by the previous program so the
    subtraction rounds separately, exactly like ``ErrorFeedback.store``."""
    return {name: totals[name] - d for name, d in decoded.items()}


# -- host orchestration -------------------------------------------------------

@dataclass
class DevicePayload:
    """An in-flight device-encoded push.

    ``device_entries`` are wire buffers still on device (their
    ``copy_to_host_async`` is already running); ``host_entries`` are the
    tiny host-built companions (fp32 scales, int64 shapes). ``order`` is
    the exact wire-dict key order the NumPy reference would emit —
    frame bytes depend on it."""
    order: list
    device_entries: dict
    host_entries: dict
    int4_shapes: dict
    pre_bytes: int
    encode_seconds: float
    copy_started_at: float = field(default_factory=time.perf_counter)


class DeviceCodec:
    """Stateful device-side equivalent of ``compress_push`` + its
    ``ErrorFeedback`` — residuals live as device arrays between pushes."""

    def __init__(self, *, error_feedback: bool = True,
                 topk_frac: float = 0.01,
                 use_pallas: bool | None = None):
        self.error_feedback = bool(error_feedback)
        self.topk_frac = float(topk_frac)
        self.use_pallas = use_pallas
        self._residual: dict[str, jax.Array] = {}

    def reset(self) -> None:
        """Drop EF residuals (quarantine directive parity with
        ``ErrorFeedback.reset``)."""
        self._residual.clear()

    # The reference's top-k sizing, verbatim (Python round half-even).
    @staticmethod
    def _topk_k(n: int, frac: float, min_k: int = 1) -> int:
        return min(n, max(min_k, int(round(frac * n))))

    def encode(self, flat: Mapping[str, jax.Array],
               plan: Mapping[str, str] | None = None,
               scales: Mapping[str, float] | None = None) -> DevicePayload:
        """Dispatch the device encode for one push; returns immediately
        with the transfers in flight. Argument semantics (plan kinds,
        shared-scale table, non-finite ValueError) match
        :func:`..ops.compression.compress_push`."""
        t0 = time.perf_counter()
        plan = plan or {}
        scales = scales or {}
        plan_t = tuple((name, plan.get(name, "int8")) for name in flat)
        ks = tuple(sorted(
            (name, self._topk_k(int(a.size), self.topk_frac))
            for name, a in flat.items()
            if plan.get(name, "int8") == "topk"))
        use_pallas = self.use_pallas if self.use_pallas is not None \
            else _on_tpu()

        totals, amax_dev, topk = _phase_stats(
            dict(flat), dict(self._residual), plan_t, ks,
            self.error_feedback)
        amax = jax.device_get(amax_dev)  # small scalars: the one sync point

        scale_host: dict[str, np.float32] = {}
        for name, kind in plan_t:
            if kind == "none":
                continue
            a = float(amax[name])
            if not np.isfinite(a):
                raise ValueError(f"device codec [{kind}] '{name}': "
                                 "non-finite values in input "
                                 "(diverging gradients?)")
            absmax = scales.get(name)
            if kind == "topk":
                continue  # scale comes from the SELECTED values, below
            if kind == "int4":
                scale_host[name] = np.float32(absmax / 7.0) \
                    if absmax and absmax > 0 \
                    else (np.float32(a / 7.0) if a > 0 else np.float32(1.0))
            else:
                scale_host[name] = np.float32(absmax / 127.0) \
                    if absmax and absmax > 0 \
                    else (np.float32(a / 127.0) if a > 0 else np.float32(1.0))
        if topk:
            # top-k scales need the selected values' absmax — one more
            # small pull (k entries per topk layer, ~1% of the tensor).
            vals_host = jax.device_get({n: v for n, (_, v) in topk.items()})
            for name, vals in vals_host.items():
                amax_v = float(np.max(np.abs(vals))) if vals.size else 0.0
                scale_host[name] = np.float32(amax_v / 127.0) \
                    if amax_v > 0 else np.float32(1.0)

        wire_dev, decoded = _phase_encode(
            totals, topk, scale_host, plan_t, use_pallas,
            self.error_feedback)
        if self.error_feedback:
            self._residual = dict(_phase_residual(totals, decoded))

        order, host_entries, int4_shapes = [], {}, {}
        for name, kind in plan_t:
            shape = tuple(flat[name].shape)
            if kind == "none":
                order.append(name)
                continue
            if kind == "topk":
                order += [name + _TOPK_IDX_SUFFIX, name + _TOPK_VAL_SUFFIX,
                          name + _TOPK_SCALE_SUFFIX, name + _TOPK_SHAPE_SUFFIX]
                host_entries[name + _TOPK_SCALE_SUFFIX] = \
                    np.asarray([scale_host[name]], np.float32)
                host_entries[name + _TOPK_SHAPE_SUFFIX] = \
                    np.asarray(shape, np.int64)
                continue
            suffix = _INT4_SCALE_SUFFIX if kind == "int4" else _SCALE_SUFFIX
            order += [name, name + suffix]
            host_entries[name + suffix] = \
                np.asarray([scale_host[name]], np.float32)
            if kind == "int4":
                int4_shapes[name] = shape

        for arr in wire_dev.values():
            if hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        pre_bytes = sum(4 * int(a.size) for a in flat.values())
        return DevicePayload(
            order=order,
            device_entries=dict(wire_dev),
            host_entries=host_entries,
            int4_shapes=int4_shapes,
            pre_bytes=pre_bytes,
            encode_seconds=time.perf_counter() - t0)

    def finalize(self, payload: DevicePayload) -> dict:
        """Assemble the NumPy wire dict from an in-flight payload. The
        device_get here is the ONLY bulk transfer of the push — already
        overlapped when the async copies had a head start."""
        host = jax.device_get(payload.device_entries)
        out: dict = {}
        for name in payload.order:
            if name in payload.host_entries:
                out[name] = payload.host_entries[name]
            elif name in payload.int4_shapes:
                out[name] = as_packed_int4(
                    np.ascontiguousarray(host[name]),
                    payload.int4_shapes[name])
            else:
                out[name] = host[name]
        return out

    def encode_now(self, flat: Mapping[str, jax.Array],
                   plan: Mapping[str, str] | None = None,
                   scales: Mapping[str, float] | None = None) -> dict:
        """Blocking encode (serial push path / tests / microbench)."""
        return self.finalize(self.encode(flat, plan=plan, scales=scales))
