"""Checkpoint/resume — a first-class gap-fill (SURVEY.md §5.4).

The reference holds parameters only in server RAM (server.py:96) and lists
"checkpointing to S3" as future work (DEPLOYMENT.md:309). Here both canonical
state holders checkpoint natively:

- device train states (params + optimizer state + BN stats + step) via Orbax,
- the async ParameterStore via a simple npz + JSON snapshot.
"""

from .manager import (
    CheckpointManager,
    PeriodicStoreCheckpointer,
    STORE_SNAPSHOT_VERSION,
    check_job_identity,
    check_shard_identity,
    load_store_record,
    restore_server_state,
    restore_store,
    save_store,
)

__all__ = ["CheckpointManager", "PeriodicStoreCheckpointer",
           "STORE_SNAPSHOT_VERSION", "check_job_identity",
           "check_shard_identity", "load_store_record",
           "restore_server_state", "restore_store", "save_store"]
