"""Checkpoint managers: Orbax for train states, npz for the host store."""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

import jax
import numpy as np

from ..ps.store import ParameterStore
from ..telemetry.journal import journal_event


class CheckpointManager:
    """Orbax-backed checkpointing of :class:`~..train.train_state.TrainState`.

    Saves params / opt_state / batch_stats / step; keeps the newest
    ``max_to_keep`` checkpoints. Restore returns a state built on the caller's
    template (so apply_fn/tx survive).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, state, step: int | None = None, wait: bool = True) -> int:
        import orbax.checkpoint as ocp

        step = int(state.step) if step is None else int(step)
        payload = {
            "params": jax.device_get(state.params),
            "opt_state": jax.device_get(state.opt_state),
            "batch_stats": jax.device_get(state.batch_stats),
            "step": step,
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        if wait:
            self._mgr.wait_until_finished()
        return step

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template_state, step: int | None = None):
        """Restore into a state template (returns a new TrainState)."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        target = {
            "params": jax.device_get(template_state.params),
            "opt_state": jax.device_get(template_state.opt_state),
            "batch_stats": jax.device_get(template_state.batch_stats),
            "step": 0,
        }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target))
        return template_state.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            batch_stats=restored["batch_stats"],
            step=restored["step"],
        )

    def close(self):
        self._mgr.close()


# -- async store snapshots ----------------------------------------------------

#: Store-snapshot record format. v1 was (npz, {global_step,...} json); v2
#: adds the aggregation-config block and the push-token journal that make a
#: server restart transparent to retrying clients (docs/ROBUSTNESS.md); v3
#: adds the npz CRC-32 integrity stamp (torn/corrupt snapshots detected at
#: restore, falling back to the previous valid record) and the in-flight
#: migration ledger block (docs/ROBUSTNESS.md "Migration failure matrix");
#: v4 adds the ``job`` identity (docs/TENANCY.md) so a restore into the
#: wrong job's namespace is refused like a cross-shard restore — pre-v4
#: records count as the ``default`` job. Restore accepts all four.
STORE_SNAPSHOT_VERSION = 4


def save_store(store: ParameterStore, directory: str,
               journal_fn=None, migration_fn=None) -> str:
    """Atomic, versioned snapshot of a parameter store: params npz +
    metadata JSON (format v2: global step, aggregation-mode config, and —
    via ``journal_fn``, typically ``ParameterService.journal_snapshot`` —
    the bounded journal of recent push-token outcomes, so a restarted
    server still dedupes pre-crash push retries).

    Works for every store backend through the uniform ``snapshot()`` surface:
    host-numpy ParameterStore (copy under param_lock), HBM-resident
    DeviceParameterStore (immutable refs pulled to host), and the C++
    NativeParameterStore (seqlock-consistent arena fetch). Enables the <30 s
    recovery the reference targeted but never built
    (baseline_summary.json distributed_system_targets; SURVEY.md §4).
    """
    os.makedirs(directory, exist_ok=True)
    # Journal BEFORE params: steps are monotonic, so every journaled
    # outcome's apply is at a step <= the snapshot step and therefore
    # INCLUDED in the saved params — a restored server can never answer
    # "duplicate, accepted" for a gradient its restored params lack (the
    # silent-loss failure). The reverse ordering would allow exactly
    # that. The residual window (a push applying between the two
    # captures is in params but not the journal, so its retry re-applies
    # after a crash) is microseconds wide and errs toward an extra
    # down-weighted gradient rather than a lost-but-claimed one.
    journal = list(journal_fn()) if journal_fn is not None else []
    arrays, step = store.snapshot()
    cfg = store.config
    meta = {
        "format_version": STORE_SNAPSHOT_VERSION,
        "global_step": step,
        "mode": cfg.mode,
        "total_workers": cfg.total_workers,
        "learning_rate": cfg.learning_rate,
        "staleness_bound": cfg.staleness_bound,
        "aggregation": {
            "mode": cfg.mode,
            "learning_rate": cfg.learning_rate,
            "staleness_bound": cfg.staleness_bound,
            "total_workers": cfg.total_workers,
            "strict_rounds": bool(getattr(cfg, "strict_rounds", False)),
            "elastic": bool(getattr(cfg, "elastic", False)),
            "push_codec": getattr(store, "push_codec", None),
            "fetch_codec": getattr(store, "fetch_codec", "none"),
        },
        "push_journal": journal,
        # Shard identity (docs/SHARDING.md): each shard primary runs its
        # own checkpointer over its own key subset, so a snapshot is only
        # valid for the SAME slot of the SAME partition — restore refuses
        # anything else. Absent in pre-sharding records (== 0-of-1).
        "shard": {
            "shard_index": int(getattr(cfg, "shard_index", 0)),
            "shard_count": int(getattr(cfg, "shard_count", 1)),
        },
        # Job identity (v4, docs/TENANCY.md): each job's checkpointer
        # writes its own lineage directory, and a snapshot is only valid
        # for the SAME job — restore refuses cross-job exactly like the
        # shard block above refuses cross-shard. Absent pre-v4
        # (== "default").
        "job": str(getattr(cfg, "job_id", "default")),
        "saved_at": time.time(),
    }
    # In-flight migration ledger (docs/ROBUSTNESS.md "Migration failure
    # matrix"): a primary that crashes mid-reshard restores its ledger
    # record with the params, so `cli reshard --resume` can read the
    # crash point and the donor's lease keeps its original deadline.
    if migration_fn is not None:
        mig = migration_fn()
        if mig is not None:
            meta["migration"] = mig
    # Unique temp names per call: concurrent snapshots (periodic thread +
    # final snapshot) must never interleave writes into one file. Publish
    # order is json THEN npz: restore discovers records by .npz, so a
    # crash between the two renames leaves either a harmless orphan json
    # or nothing — never a visible npz without its metadata.
    suffix = f"{os.getpid()}-{threading.get_ident()}"
    tmp_npz = os.path.join(directory, f".tmp-{suffix}.npz")
    tmp_json = os.path.join(directory, f".tmp-{suffix}.json")
    np.savez(tmp_npz, **arrays)
    # CRC the STAGED npz bytes (v3): restore re-hashes the published
    # file against this stamp, so a torn write, a crash mid-rename, or
    # later on-disk damage is detected and restore falls back to the
    # previous valid snapshot instead of silently loading garbage.
    crc, size = 0, 0
    with open(tmp_npz, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    meta["npz_crc32"] = crc
    meta["npz_size"] = size
    with open(tmp_json, "w") as f:
        json.dump(meta, f)
    final = os.path.join(directory, f"store_{step:08d}.npz")
    os.replace(tmp_json, os.path.join(directory, f"store_{step:08d}.json"))
    os.replace(tmp_npz, final)
    journal_event("checkpoint", step=int(step), path=final,
                  bytes=size)
    return final


def _read_record(directory: str, name: str
                 ) -> tuple[dict[str, np.ndarray], dict]:
    """Read and fully validate ONE snapshot record (npz + json). Raises
    on any damage: unreadable metadata, an ``npz_crc32`` mismatch (v3
    stamp), or an npz numpy cannot decode (the only integrity signal a
    pre-v3 record offers). Arrays are materialized here — np.load is
    lazy, and a torn zip often only fails when a member is read."""
    npz_path = os.path.join(directory, name)
    with open(os.path.join(directory,
                           name.replace(".npz", ".json"))) as f:
        meta = json.load(f)
    want = meta.get("npz_crc32")
    if want is not None:
        crc = 0
        with open(npz_path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        if crc != int(want):
            raise ValueError(
                f"npz checksum mismatch (torn or corrupt write): "
                f"crc {crc:#010x} != recorded {int(want):#010x}")
    data = np.load(npz_path)
    params = {k: np.array(data[k], np.float32) for k in data.files}
    return params, meta


def load_store_record(directory: str, step: int | None = None
                      ) -> tuple[dict[str, np.ndarray], dict]:
    """Read the newest (or given-step) snapshot -> (params, meta dict).
    v1 records (no ``format_version``) load with an empty journal and no
    aggregation block.

    Newest-pick mode walks newest -> oldest past torn or corrupt
    records (CRC-verified for v3, decode-verified for older), logging
    one ``CHECKPOINT_FALLBACK`` line per skip — a crash mid-snapshot
    must cost one checkpoint interval of progress, not the restore. An
    EXPLICIT ``step`` is load-bearing: damage there is an error, never
    a silent substitution of some other step."""
    snaps = sorted(f for f in os.listdir(directory)
                   if f.startswith("store_") and f.endswith(".npz"))
    if not snaps:
        raise FileNotFoundError(f"no store snapshots in {directory}")
    if step is not None:
        name = f"store_{step:08d}.npz"
        if name not in snaps:
            raise FileNotFoundError(name)
        return _read_record(directory, name)
    errors = []
    for name in reversed(snaps):
        try:
            return _read_record(directory, name)
        except Exception as e:  # noqa: BLE001 — any damage means fall back
            errors.append(f"{name}: {e}")
            print(f"CHECKPOINT_FALLBACK {name} unreadable ({e}); "
                  f"trying previous snapshot", flush=True)
    raise FileNotFoundError(
        f"no valid store snapshot in {directory}: " + "; ".join(errors))


def restore_store(store: ParameterStore, directory: str,
                  step: int | None = None) -> int:
    """Load the newest (or given-step) snapshot into the store. Returns the
    restored global step (also published as the ``dps_store_restore_step``
    gauge, so telemetry streams show where a restarted server resumed)."""
    params, meta = load_store_record(directory, step)
    check_shard_identity(store, meta)
    check_job_identity(store, meta)
    store.load_snapshot(params, int(meta["global_step"]))
    from ..telemetry import get_registry
    get_registry().gauge(
        "dps_store_restore_step",
        backend=getattr(store, "store_backend", "python"),
    ).set(store.global_step)
    return store.global_step


def check_shard_identity(store: ParameterStore, meta: dict) -> None:
    """Refuse restoring a snapshot into the wrong shard slot or into a
    differently-partitioned topology (docs/SHARDING.md): each shard's
    checkpoint holds only its own key subset, so a mismatched restore
    would silently serve another shard's tensors — or a partial model as
    the whole one. Pre-sharding records carry no block and count as
    shard 0 of 1."""
    rec = meta.get("shard") or {}
    rec_idx = int(rec.get("shard_index", 0))
    rec_cnt = int(rec.get("shard_count", 1))
    cfg = store.config
    cur_idx = int(getattr(cfg, "shard_index", 0))
    cur_cnt = int(getattr(cfg, "shard_count", 1))
    if (rec_idx, rec_cnt) != (cur_idx, cur_cnt):
        raise ValueError(
            f"snapshot belongs to shard {rec_idx}/{rec_cnt} but this "
            f"server is shard {cur_idx}/{cur_cnt} — refusing a "
            f"cross-shard restore")


def check_job_identity(store: ParameterStore, meta: dict) -> None:
    """Refuse restoring a snapshot into a different job's namespace
    (docs/TENANCY.md): each job owns its own parameters, step, and push
    journal, so a cross-job restore would silently replace one tenant's
    model with another's — the tenancy analogue of the cross-shard
    refusal above. Pre-v4 records carry no ``job`` and count as the
    ``default`` job."""
    rec_job = str(meta.get("job") or "default")
    cur_job = str(getattr(store.config, "job_id", "default"))
    if rec_job != cur_job:
        raise ValueError(
            f"snapshot belongs to job {rec_job!r} but this store is job "
            f"{cur_job!r} — refusing a cross-job restore")


def restore_server_state(store: ParameterStore, service, directory: str,
                         step: int | None = None,
                         record: tuple | None = None) -> tuple[int, int]:
    """Full server-side restore: params + step into the store, push-token
    journal into the service's dedupe table. Returns (restored_step,
    journal_entries_loaded). The one-call recovery path ``cli serve
    --restore`` uses. ``record`` accepts an already-loaded
    ``(params, meta)`` pair so a caller that inspected the snapshot first
    (config adoption) restores the SAME record it read — re-listing the
    directory could pick up a newer snapshot published in between."""
    params, meta = record if record is not None \
        else load_store_record(directory, step)
    check_shard_identity(store, meta)
    check_job_identity(store, meta)
    store.load_snapshot(params, int(meta["global_step"]))
    from ..telemetry import get_registry
    get_registry().gauge(
        "dps_store_restore_step",
        backend=getattr(store, "store_backend", "python"),
    ).set(store.global_step)
    loaded = 0
    if service is not None:
        loaded = service.load_journal(meta.get("push_journal", []))
        # Re-install any in-flight migration ledger record (v3): a
        # donor that died mid-export comes back FROZEN under its
        # original lease deadline, so the coordinator's --resume (or
        # lease expiry) decides the outcome, not the crash.
        mig_load = getattr(service, "load_migration", None)
        if mig_load is not None:
            mig_load(meta.get("migration"))
    return store.global_step, loaded


class PeriodicStoreCheckpointer(threading.Thread):
    """Background thread snapshotting the store every ``interval`` seconds.

    A failed periodic snapshot (disk full, permissions) is logged and
    retried at the next tick rather than silently killing the thread — one
    transient failure must not permanently disable the <30 s recovery path.
    The most recent failure (cleared by any later success) is kept in
    ``last_error`` and returned by ``stop()``.
    """

    def __init__(self, store: ParameterStore, directory: str,
                 interval: float = 30.0, journal_fn=None,
                 migration_fn=None):
        super().__init__(daemon=True)
        self.store = store
        self.directory = directory
        self.interval = interval
        #: Optional push-token journal source (typically
        #: ``ParameterService.journal_snapshot``), persisted into every
        #: snapshot so a restart keeps deduping pre-crash push retries.
        self.journal_fn = journal_fn
        #: Optional migration-ledger source (typically
        #: ``ParameterService.migration_snapshot``) — persisted so a
        #: primary that crashes mid-reshard restores its crash point.
        self.migration_fn = migration_fn
        self.last_error: Exception | None = None
        # NB: must not be named _stop — that would shadow
        # threading.Thread._stop(), which join() calls internally.
        self._stop_event = threading.Event()

    def run(self):
        while not self._stop_event.wait(self.interval):
            try:
                save_store(self.store, self.directory,
                           journal_fn=self.journal_fn,
                           migration_fn=self.migration_fn)
                self.last_error = None
            except Exception as e:  # noqa: BLE001 — keep snapshotting
                self.last_error = e
                print(f"periodic store snapshot failed (will retry in "
                      f"{self.interval:.0f}s): {e!r}")

    def flush_now(self) -> None:
        """One immediate snapshot, independent of the tick — registered as
        a telemetry shutdown flush (``add_shutdown_flush``) so SIGTERM
        drains the store's end state through the same path that dumps the
        flight recorder. Exceptions propagate to the shutdown runner,
        which swallows them (a failed final snapshot must not mask the
        shutdown itself); the periodic ``last_error`` is left for the
        next tick's bookkeeping."""
        save_store(self.store, self.directory, journal_fn=self.journal_fn,
                   migration_fn=self.migration_fn)

    def stop(self, final_snapshot: bool = True) -> Exception | None:
        """Stop the thread; returns the last unrecovered periodic failure
        (None if the latest snapshot attempt succeeded)."""
        self._stop_event.set()
        if self.is_alive():
            self.join()  # let an in-flight periodic snapshot finish first
        if final_snapshot:
            # The final snapshot still raises on failure: unlike a periodic
            # tick there is no later retry, and the caller must know the
            # run's end state was not persisted.
            save_store(self.store, self.directory,
                       journal_fn=self.journal_fn,
                       migration_fn=self.migration_fn)
            self.last_error = None
        return self.last_error
