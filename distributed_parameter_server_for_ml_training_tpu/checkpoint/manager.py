"""Checkpoint managers: Orbax for train states, npz for the host store."""

from __future__ import annotations

import json
import os
import threading

import jax
import numpy as np

from ..ps.store import ParameterStore


class CheckpointManager:
    """Orbax-backed checkpointing of :class:`~..train.train_state.TrainState`.

    Saves params / opt_state / batch_stats / step; keeps the newest
    ``max_to_keep`` checkpoints. Restore returns a state built on the caller's
    template (so apply_fn/tx survive).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, state, step: int | None = None, wait: bool = True) -> int:
        import orbax.checkpoint as ocp

        step = int(state.step) if step is None else int(step)
        payload = {
            "params": jax.device_get(state.params),
            "opt_state": jax.device_get(state.opt_state),
            "batch_stats": jax.device_get(state.batch_stats),
            "step": step,
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        if wait:
            self._mgr.wait_until_finished()
        return step

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template_state, step: int | None = None):
        """Restore into a state template (returns a new TrainState)."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        target = {
            "params": jax.device_get(template_state.params),
            "opt_state": jax.device_get(template_state.opt_state),
            "batch_stats": jax.device_get(template_state.batch_stats),
            "step": 0,
        }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target))
        return template_state.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            batch_stats=restored["batch_stats"],
            step=restored["step"],
        )

    def close(self):
        self._mgr.close()


# -- async store snapshots ----------------------------------------------------

def save_store(store: ParameterStore, directory: str) -> str:
    """Atomic snapshot of a parameter store: params npz + metadata JSON.

    Works for every store backend through the uniform ``snapshot()`` surface:
    host-numpy ParameterStore (copy under param_lock), HBM-resident
    DeviceParameterStore (immutable refs pulled to host), and the C++
    NativeParameterStore (seqlock-consistent arena fetch). Enables the <30 s
    recovery the reference targeted but never built
    (baseline_summary.json distributed_system_targets; SURVEY.md §4).
    """
    os.makedirs(directory, exist_ok=True)
    arrays, step = store.snapshot()
    # Unique temp name per call: concurrent snapshots (periodic thread +
    # final snapshot) must never interleave writes into one file.
    tmp = os.path.join(directory,
                       f".tmp-{os.getpid()}-{threading.get_ident()}.npz")
    np.savez(tmp, **arrays)
    final = os.path.join(directory, f"store_{step:08d}.npz")
    os.replace(tmp, final)
    meta = {
        "global_step": step,
        "mode": store.config.mode,
        "total_workers": store.config.total_workers,
        "learning_rate": store.config.learning_rate,
        "staleness_bound": store.config.staleness_bound,
    }
    with open(os.path.join(directory, f"store_{step:08d}.json"), "w") as f:
        json.dump(meta, f)
    return final


def restore_store(store: ParameterStore, directory: str,
                  step: int | None = None) -> int:
    """Load the newest (or given-step) snapshot into the store. Returns the
    restored global step."""
    snaps = sorted(f for f in os.listdir(directory)
                   if f.startswith("store_") and f.endswith(".npz"))
    if not snaps:
        raise FileNotFoundError(f"no store snapshots in {directory}")
    if step is not None:
        name = f"store_{step:08d}.npz"
        if name not in snaps:
            raise FileNotFoundError(name)
    else:
        name = snaps[-1]
    data = np.load(os.path.join(directory, name))
    with open(os.path.join(directory,
                           name.replace(".npz", ".json"))) as f:
        meta = json.load(f)
    params = {k: np.array(data[k], np.float32) for k in data.files}
    store.load_snapshot(params, int(meta["global_step"]))
    return store.global_step


class PeriodicStoreCheckpointer(threading.Thread):
    """Background thread snapshotting the store every ``interval`` seconds.

    A failed periodic snapshot (disk full, permissions) is logged and
    retried at the next tick rather than silently killing the thread — one
    transient failure must not permanently disable the <30 s recovery path.
    The most recent failure (cleared by any later success) is kept in
    ``last_error`` and returned by ``stop()``.
    """

    def __init__(self, store: ParameterStore, directory: str,
                 interval: float = 30.0):
        super().__init__(daemon=True)
        self.store = store
        self.directory = directory
        self.interval = interval
        self.last_error: Exception | None = None
        # NB: must not be named _stop — that would shadow
        # threading.Thread._stop(), which join() calls internally.
        self._stop_event = threading.Event()

    def run(self):
        while not self._stop_event.wait(self.interval):
            try:
                save_store(self.store, self.directory)
                self.last_error = None
            except Exception as e:  # noqa: BLE001 — keep snapshotting
                self.last_error = e
                print(f"periodic store snapshot failed (will retry in "
                      f"{self.interval:.0f}s): {e!r}")

    def stop(self, final_snapshot: bool = True) -> Exception | None:
        """Stop the thread; returns the last unrecovered periodic failure
        (None if the latest snapshot attempt succeeded)."""
        self._stop_event.set()
        if self.is_alive():
            self.join()  # let an in-flight periodic snapshot finish first
        if final_snapshot:
            # The final snapshot still raises on failure: unlike a periodic
            # tick there is no later retry, and the caller must know the
            # run's end state was not persisted.
            save_store(self.store, self.directory)
            self.last_error = None
        return self.last_error
