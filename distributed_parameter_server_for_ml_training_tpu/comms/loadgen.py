"""Fetch-path load generator (docs/SHARDING.md "Serve-path load").

Drives ``FetchParameters`` at open-throttle concurrency against one or
more targets (shard primaries and/or replicas) and reports aggregate
QPS plus client-observed latency percentiles — the measurement tool
behind the recorded ≥10× serve-path claim (experiments/run_shard_scale.py)
and the ``fetch_qps`` field bench.py records.

Deliberately NOT built on RemoteStore: the generator unpacks only the
reply envelope and never decodes tensors, so the client side stays far
from saturation and the measured ceiling is the SERVER's. Each worker
thread owns its own channel (no client-side multiplexing bottleneck)
and round-robins over the target list by thread index.

Modes:
- ``full``  — every fetch ships the whole model (the production read
  workload: parameter consumers arriving cold).
- ``delta`` — fetches carry ``have_step`` at the target's current step,
  so an idle server answers header-only NOT_MODIFIED (the replica-
  refresh / heartbeat workload).
- ``infer`` — the inference-serving workload against a canary-enabled
  replica tier (docs/SHARDING.md "Serve tier"): each request carries
  ``infer`` and piggybacks a quality score for the PREVIOUS response
  (``quality_fn(serving_step)``), and the result breaks fetch counts,
  latency, and mean quality out per serving arm — the canary split is
  directly visible in the numbers.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time

import grpc

from ..telemetry.registry import LATENCY_BUCKETS, Histogram
from ..telemetry.stats import histogram_quantile, merge_histograms
from ..telemetry.stats import latency_summary as _latency_summary
from .service import GRPC_OPTIONS, SERVICE_NAME, pack_msg, unpack_msg

__all__ = ["loadgen_child_argv", "merge_loadgen_reports",
           "parse_loadgen_json", "run_loadgen", "run_loadgen_scaled"]

#: The machine-readable line ``cli loadgen`` prints (and the scale-out
#: parent greps from each child's stdout).
LOADGEN_JSON_PREFIX = "LOADGEN_JSON "


def _latency_hist(lat_s: list) -> dict:
    """Client-observed latencies on the pinned SLO bucket scheme — the
    LOADGEN_JSON field that makes reports MERGEABLE: percentiles of
    percentiles are not percentiles, but pinned-scheme histograms merge
    exactly (telemetry/stats.merge_histograms)."""
    h = Histogram("loadgen_latency", buckets=LATENCY_BUCKETS)
    for v in lat_s:
        h.observe(v)
    return h.snapshot()


def merge_loadgen_reports(reports: list) -> dict:
    """Merge LOADGEN_JSON reports into one honest aggregate report.

    The building block for distributed load generation (N generator
    processes hammering one fleet): counts/bytes sum, QPS sums (the
    generators ran concurrently), duration takes the max, targets union
    — and the latency percentiles come from merging each report's
    ``latency_hist`` on the pinned bucket scheme, so the merged
    p50/p95/p99 are the union percentiles, not an average of
    per-report percentiles. Raises on reports without ``latency_hist``
    (pre-merge-era records cannot be merged honestly).
    """
    if not reports:
        raise ValueError("merge_loadgen_reports needs at least one report")
    for i, r in enumerate(reports):
        if "latency_hist" not in r:
            raise ValueError(
                f"report {i} has no latency_hist — re-run the generator "
                f"(pre-fleet reports cannot be merged honestly)")
    merged_hist = merge_histograms([r["latency_hist"] for r in reports])
    targets: list = []
    for r in reports:
        for t in r.get("targets", []):
            if t not in targets:
                targets.append(t)
    latency_ms = {"samples": int(merged_hist["count"])}
    for pct, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        q = histogram_quantile(merged_hist["le"], merged_hist["counts"],
                               pct)
        latency_ms[key] = None if q is None else round(q * 1e3, 3)
    total_bytes = sum(r.get("bytes_in", 0) for r in reports)
    return {
        "targets": targets,
        "reports": len(reports),
        "modes": sorted({r.get("mode", "?") for r in reports}),
        "concurrency": sum(int(r.get("concurrency", 0)) for r in reports),
        "duration_s": round(max(float(r.get("duration_s", 0.0))
                                for r in reports), 3),
        "fetches_ok": sum(int(r.get("fetches_ok", 0)) for r in reports),
        "fetches_err": sum(int(r.get("fetches_err", 0)) for r in reports),
        "not_modified": sum(int(r.get("not_modified", 0))
                            for r in reports),
        "bytes_in": total_bytes,
        "qps": round(sum(float(r.get("qps", 0.0)) for r in reports), 1),
        "mb_per_s": round(sum(float(r.get("mb_per_s", 0.0))
                              for r in reports), 2),
        "latency_ms": latency_ms,
        "latency_hist": merged_hist,
    }


def _fetch_stub(channel):
    ident = lambda b: b  # noqa: E731
    return channel.unary_unary(f"/{SERVICE_NAME}/FetchParameters",
                               request_serializer=ident,
                               response_deserializer=ident)


def run_loadgen(targets, duration_s: float = 5.0, concurrency: int = 4,
                mode: str = "full", rpc_timeout: float = 10.0,
                quality_fn=None, job=None) -> dict:
    """Hammer ``targets`` with fetches for ``duration_s`` using
    ``concurrency`` threads; returns the aggregate result dict (also the
    ``LOADGEN_JSON`` schema ``cli loadgen`` emits). In ``infer`` mode
    ``quality_fn(serving_step) -> float`` scores each served response
    (default: constant 1.0); the score rides the NEXT request as canary
    feedback. ``job`` (a name or comma-separated list) stamps each
    request's envelope with a job id — threads round-robin over the
    list, so a two-job spec drives both tenants at once and the result
    gains a per-job ``"jobs"`` breakdown (docs/TENANCY.md)."""
    if isinstance(targets, str):
        targets = [t for t in targets.split(",") if t]
    if not targets:
        raise ValueError("loadgen needs at least one target")
    if mode not in ("full", "delta", "infer"):
        raise ValueError(f"mode must be full|delta|infer, got {mode!r}")
    jobs = ([j.strip() for j in str(job).split(",") if j.strip()]
            if job else [])

    lock = threading.Lock()
    per_target = {t: {"ok": 0, "err": 0, "bytes_in": 0,
                      "not_modified": 0} for t in targets}
    per_job = {j: {"ok": 0, "err": 0, "latency_s": []}
               for j in jobs}  # guarded by: lock
    latencies: list[float] = []  # guarded by: lock
    # Per-arm accounting (infer mode; guarded by: lock). Literal arm
    # names: these ARE the wire values a canary replica stamps replies
    # with.
    arms = {a: {"ok": 0, "quality_sum": 0.0, "quality_n": 0,
                "latency_s": [], "steps": set()}
            for a in ("stable", "canary")}
    stop = threading.Event()

    def worker(idx: int) -> None:
        target = targets[idx % len(targets)]
        myjob = jobs[idx % len(jobs)] if jobs else None
        # Stamp every envelope this thread sends; merged into each meta
        # dict built below (send-side only — the generator still never
        # decodes tensors).
        jmeta = {"job": myjob} if myjob else {}
        channel = grpc.insecure_channel(target, options=GRPC_OPTIONS)
        stub = _fetch_stub(channel)
        ok = err = nbytes = nm = 0
        lat: list[float] = []
        arm_local = {a: {"ok": 0, "quality_sum": 0.0, "quality_n": 0,
                         "latency_s": [], "steps": set()}
                     for a in ("stable", "canary")}
        have = None
        if mode == "delta":
            # Learn the target's current step once, then poll at it so
            # the steady state is all NOT_MODIFIED replies.
            try:
                meta, _ = unpack_msg(stub(pack_msg(dict(jmeta)),
                                          timeout=rpc_timeout))
                have = int(meta["global_step"])
            except Exception:  # noqa: BLE001 — count as errors below
                have = 0
        if mode == "infer":
            request = pack_msg({"infer": True, **jmeta})
        else:
            request = pack_msg(dict(jmeta) if have is None
                               else {"have_step": have, **jmeta})
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                reply = stub(request, timeout=rpc_timeout)
            except Exception:  # noqa: BLE001 — grpc errors only
                err += 1
                continue
            dt = time.perf_counter() - t0
            ok += 1
            nbytes += len(reply)
            lat.append(dt)
            if mode == "delta":
                rmeta, _ = unpack_msg(reply)
                if rmeta.get("not_modified"):
                    nm += 1
                else:
                    # The target advanced: re-arm at the new step so the
                    # loop keeps measuring the NM path, not full ships.
                    have = int(rmeta["global_step"])
                    request = pack_msg({"have_step": have, **jmeta})
            elif mode == "infer":
                rmeta, _ = unpack_msg(reply)
                arm = str(rmeta.get("arm") or "stable")
                if arm not in arm_local:
                    arm = "stable"
                step = rmeta.get("serving_step")
                row = arm_local[arm]
                row["ok"] += 1
                row["latency_s"].append(dt)
                meta: dict = {"infer": True, **jmeta}
                if step is not None:
                    row["steps"].add(int(step))
                    try:
                        q = (1.0 if quality_fn is None
                             else float(quality_fn(int(step))))
                    except Exception:  # noqa: BLE001 — scorer bug only
                        q = None       # costs one feedback sample
                    if q is not None:
                        row["quality_sum"] += q
                        row["quality_n"] += 1
                        # Feedback rides the NEXT request: arm + step
                        # identify which window the score lands in.
                        meta["quality"] = {"arm": arm,
                                           "step": int(step),
                                           "value": q}
                request = pack_msg(meta)
        channel.close()
        with lock:
            row = per_target[target]
            row["ok"] += ok
            row["err"] += err
            row["bytes_in"] += nbytes
            row["not_modified"] += nm
            latencies.extend(lat)
            if myjob is not None:
                jrow = per_job[myjob]
                jrow["ok"] += ok
                jrow["err"] += err
                jrow["latency_s"].extend(lat)
            for a, src in arm_local.items():
                dst = arms[a]
                dst["ok"] += src["ok"]
                dst["quality_sum"] += src["quality_sum"]
                dst["quality_n"] += src["quality_n"]
                dst["latency_s"].extend(src["latency_s"])
                dst["steps"] |= src["steps"]

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(int(concurrency))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(float(duration_s))
    stop.set()
    for t in threads:
        t.join(timeout=max(10.0, rpc_timeout * 2))
    elapsed = time.monotonic() - t0
    total_ok = sum(r["ok"] for r in per_target.values())
    total_err = sum(r["err"] for r in per_target.values())
    total_bytes = sum(r["bytes_in"] for r in per_target.values())
    result = {
        "targets": list(targets),
        "mode": mode,
        "concurrency": int(concurrency),
        "duration_s": round(elapsed, 3),
        "fetches_ok": total_ok,
        "fetches_err": total_err,
        "not_modified": sum(r["not_modified"]
                            for r in per_target.values()),
        "bytes_in": total_bytes,
        "qps": round(total_ok / elapsed, 1) if elapsed > 0 else 0.0,
        "mb_per_s": round(total_bytes / elapsed / 1e6, 2)
        if elapsed > 0 else 0.0,
        "latency_ms": _latency_summary(latencies),
        "latency_hist": _latency_hist(latencies),
        "errors_by_target": {t: r["err"] for t, r in per_target.items()},
        "per_target": per_target,
    }
    if jobs:
        result["jobs"] = {
            j: {"ok": r["ok"], "err": r["err"],
                "qps": (round(r["ok"] / elapsed, 1)
                        if elapsed > 0 else 0.0),
                "latency_ms": _latency_summary(r["latency_s"])}
            for j, r in per_job.items()}
    if mode == "infer":
        result["arms"] = {
            a: {"ok": r["ok"],
                "quality_mean": (round(r["quality_sum"] / r["quality_n"], 4)
                                 if r["quality_n"] else None),
                "latency_ms": _latency_summary(r["latency_s"]),
                "serving_steps": sorted(r["steps"])}
            for a, r in arms.items()}
    return result


def loadgen_child_argv(targets, duration_s: float, concurrency: int,
                       mode: str, job=None,
                       python: str | None = None) -> list[str]:
    """One scale-out child's command line: a plain ``cli loadgen``
    invocation (no ``--scale-out`` — children never recurse). Pure, so
    tests pin the fan-out contract without spawning anything."""
    if isinstance(targets, str):
        targets = [t for t in targets.split(",") if t]
    pkg = __name__.rsplit(".", 2)[0]
    argv = [python or sys.executable, "-m", f"{pkg}.cli", "loadgen",
            "--targets", ",".join(targets),
            "--duration", str(float(duration_s)),
            "--concurrency", str(int(concurrency)),
            "--fetch-mode", str(mode)]
    if job:
        argv += ["--job", str(job)]
    return argv


def parse_loadgen_json(text: str) -> dict | None:
    """Extract the LOADGEN_JSON report from one generator's stdout
    (last match wins — logs may precede it). None when absent or
    garbled: the scale-out parent drops that child from the merge and
    says so, instead of averaging in junk."""
    found = None
    for line in str(text).splitlines():
        if line.startswith(LOADGEN_JSON_PREFIX):
            try:
                found = json.loads(line[len(LOADGEN_JSON_PREFIX):])
            except ValueError:
                continue
        # tolerate prefixed wrapping (e.g. a supervisor log line)
        elif LOADGEN_JSON_PREFIX in line:
            try:
                found = json.loads(
                    line.split(LOADGEN_JSON_PREFIX, 1)[1])
            except ValueError:
                continue
    return found if isinstance(found, dict) else None


def run_loadgen_scaled(targets, duration_s: float = 5.0,
                       concurrency: int = 4, mode: str = "full",
                       job=None, scale_out: int = 2,
                       rpc_timeout: float = 10.0,
                       python: str | None = None, spawn=None) -> dict:
    """Distributed load generation (docs/SHARDING.md "Fan-out trees"):
    launch ``scale_out`` coordinated generator PROCESSES (each a plain
    ``cli loadgen`` with ``concurrency`` threads), then merge their
    LOADGEN_JSON reports through :func:`merge_loadgen_reports` — the
    merged percentiles come from the bucket-exact histogram union, never
    from averaging per-process percentiles. One process behaves exactly
    like :func:`run_loadgen` plus the subprocess overhead; the fan-out
    exists so a single GIL-bound generator stops being the thing the
    measurement saturates. ``spawn(argv) -> Popen-like`` is injectable
    for tests. Raises ``RuntimeError`` when no child produced a report.
    """
    n = max(1, int(scale_out))
    argv = loadgen_child_argv(targets, duration_s, concurrency, mode,
                              job=job, python=python)
    if spawn is None:
        def spawn(a):  # pragma: no cover — exercised by the slow drill
            return subprocess.Popen(a, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
    procs = [spawn(list(argv)) for _ in range(n)]
    reports, failed = [], 0
    deadline = time.monotonic() + float(duration_s) + 8 * rpc_timeout
    for p in procs:
        try:
            out, _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        report = parse_loadgen_json(out or "")
        if report is None:
            failed += 1
        else:
            reports.append(report)
    if not reports:
        raise RuntimeError(
            f"scale-out loadgen: none of the {n} generator processes "
            f"produced a LOADGEN_JSON report")
    merged = merge_loadgen_reports(reports)
    merged["scale_out"] = n
    merged["generators_failed"] = failed
    merged["per_process_qps"] = [round(float(r.get("qps", 0.0)), 1)
                                 for r in reports]
    return merged
