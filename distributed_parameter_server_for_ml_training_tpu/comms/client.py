"""gRPC client: a remote ParameterStore with the in-process interface.

`RemoteStore` duck-types :class:`~..ps.store.ParameterStore`'s worker-facing
API (register_worker / fetch / push / job_finished), so
:class:`~..ps.worker.PSWorker` runs unchanged against a server on another
host — the reference's worker/server split (worker.py:199-231) without
Fargate.

Reference parity: registration retries 5x with exponential backoff
(worker.py:215-229); fp16 push compression happens client-side
(worker.py:264-268) when the server's codec asks for it; channel options
match worker.py:203-209.

Beyond the reference: the HOT RPCs (Fetch/Push/JobFinished) carry a
deadline and bounded retry on transient failures (round-4 VERDICT item 7).
The reference's worker dies on any mid-epoch RPC blip (worker.py:270-311
has no retry); this framework has elastic membership and heartbeats, so
surviving blips completes that story — a worker that retries through a
flicker keeps its slot, and membership updates keep flowing via the
piggybacked Fetch replies (reshard happens at the next epoch boundary).
Retried pushes are exactly-once: every push carries a unique ``push_token``
(the request bytes — token included — are packed once and retried
verbatim), and the server replays the recorded outcome for a token it has
already seen instead of re-applying the gradient
(comms/service.py:push_gradrients). Without the token a reply lost AFTER a
sync round completed would re-stash that gradient into the next round as a
stale duplicate (round-4 ADVICE finding).
"""

from __future__ import annotations

import json
import time

import grpc
import numpy as np

from ..telemetry import current_wire_trace, now as _tnow, trace_span

from .service import (GRPC_OPTIONS, SERVICE_NAME, RawJSON, pack_msg,
                      unpack_msg)

#: Transient codes worth retrying; anything else (e.g. INVALID_ARGUMENT,
#: UNIMPLEMENTED) indicates a real protocol problem and raises immediately.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})


class SessionLostError(ConnectionError):
    """Transient failures outlived the retry budget: the server is most
    likely down or restarting. This replaces the old terminal behavior
    (the last ``grpc.RpcError`` escaping and killing the worker): it is a
    distinct, catchable signal the worker's reconnect state machine
    (`ps/worker.py:PSWorker._recover_session`) acts on — re-register for a
    fresh id, re-fetch at the restored server step, reconcile the
    in-flight gradient (docs/ROBUSTNESS.md). The last wire error rides as
    ``__cause__``."""


class _RemoteConfig:
    """Server-side StoreConfig facts the client learns at registration.
    PSWorker duck-types ``store.config`` for the elastic flag
    (ps/worker.py:_compute_shard); this is the remote half of that
    contract."""

    def __init__(self):
        self.elastic = False
        self.mode = "sync"
        self.learning_rate = 0.1
        # Advertised at registration; the reconnect reconciliation uses it
        # to decide discard-vs-repush for an in-flight gradient without a
        # wasted round trip (docs/ROBUSTNESS.md).
        self.staleness_bound = 5


class RemoteStore:
    """Client-side stand-in for ParameterStore over gRPC."""

    #: fetch() returns fp32 regardless of the server's fetch codec — the
    #: decompress happens HERE (client side); PSWorker._fetch_params checks
    #: this to avoid a second full-parameter cast per fetch.
    decompresses_fetches = True

    def __init__(self, address: str = "localhost:8000",
                 register_retries: int = 5,
                 rpc_timeout: float = 60.0,
                 rpc_retries: int = 3,
                 rpc_backoff: float = 0.5,
                 faults=None,
                 job: str | None = None):
        self.address = address
        #: Tenancy (docs/TENANCY.md): the job this client asks to join at
        #: registration. None joins the server's default job. The value
        #: is re-adopted from the registration reply's echo (the server
        #: may degrade an unknown/garbled id to the default job), and
        #: attached to every push/fetch envelope ONLY once the server
        #: advertises the ``jobs`` capability — a legacy server never
        #: sees the key (the delta_fetch gating discipline).
        self.job = job
        self.supports_jobs = False
        self.register_retries = register_retries
        self.rpc_timeout = rpc_timeout
        self.rpc_retries = rpc_retries
        self.rpc_backoff = rpc_backoff
        # Deterministic client-side fault injection (comms/faults.py):
        # a spec string (or prebuilt FaultInjector) interposes between the
        # retry layer and the channel, so injected faults exercise the
        # real backoff/reconnect machinery. Env DPS_FAULTS_CLIENT applies
        # fleet-wide without code changes (chaos drills).
        import os as _os
        if faults is None:
            faults = _os.environ.get("DPS_FAULTS_CLIENT") or None
        if faults is not None and isinstance(faults, str):
            from .faults import FaultInjector
            faults = FaultInjector(faults, side="client")
        self.faults = faults
        self._channel = None
        self._build_channel()
        # The most recent push's (token, payload, fetched_step): after a
        # session loss the reconnect path re-sends it VERBATIM except for
        # the worker id (repush_last) — same token means a push the
        # crashed server already applied and journaled replays as a
        # duplicate instead of double-applying.
        self._last_push: tuple[str, bytes, int] | None = None
        #: filled in at registration from the server's config; PSWorker reads
        #: these to apply the fp16 cast client-side before push
        #: (worker.py:264-268) and decompress after fetch.
        self.push_codec = "none"
        self.fetch_codec = "none"
        #: True once the server advertises the delta-fetch capability at
        #: registration; fetch(have_step=...) is only sent when set (an old
        #: server would silently ignore the field and ship the full model,
        #: which is correct but wasteful — gating keeps intent explicit).
        self.supports_delta_fetch = False
        #: True once the server advertises trace-context propagation at
        #: registration (same gating discipline as delta fetch,
        #: docs/WIRE_PROTOCOL.md): the trace field is only attached to
        #: push frames / fetch meta when the peer said it understands it.
        self.supports_trace_context = False
        #: True once the server advertises the health-report capability at
        #: registration (it runs a cluster monitor; docs/OBSERVABILITY.md).
        self.supports_health_report = False
        #: True once the server advertises compressed-domain aggregation
        #: (docs/WIRE_PROTOCOL.md): it accepts quantized payloads
        #: (int8/int4/topk) without decoding and publishes per-layer
        #: gradient scales. Same gating discipline as delta_fetch.
        self.supports_compressed_domain = False
        #: True once the server advertises the directive channel
        #: (docs/ROBUSTNESS.md "Self-healing"): its fetch/push reply meta
        #: may carry server->worker control directives. This client
        #: advertises the capability in its register request; either side
        #: missing it degrades to a directive-less wire.
        self.supports_directives = False
        #: True once the server advertises CRC verification on push
        #: frames (docs/WIRE_PROTOCOL.md "Checksum trailer"): pushes are
        #: then encoded with the 4-byte CRC-32 trailer and a corrupt
        #: frame is REFUSED server-side instead of silently applying.
        #: Gated because a legacy server would mistake the trailer for
        #: buffer slack — same degradation discipline as delta_fetch.
        self.supports_checksum = False
        #: Directives received but not yet taken by the worker loop, plus
        #: the highest seq seen (the dedupe/ack watermark — the server
        #: re-attaches outstanding directives every reply until acked).
        self._pending_directives: list[dict] = []  # guarded by: self._wire_lock
        self._directive_last_seq = 0  # guarded by: self._wire_lock
        #: Server-published per-layer gradient ABSMAX table + version,
        #: cached from the registration reply and refreshed off fetch
        #: reply meta (the client sends its version as ``have_qscales``;
        #: the server attaches the table only when newer).
        self._qscales: dict[str, float] = {}  # guarded by: self._wire_lock
        self._qscale_step = 0  # guarded by: self._wire_lock
        #: Zero-arg callable returning the worker's current health report
        #: (a small JSON-able dict) or None. PSWorker installs its own
        #: snapshot builder here after registration; when set AND the
        #: server advertised the capability, every fetch (incl. heartbeat
        #: pings) and push carries the report in the envelope meta. Legacy
        #: combinations — no provider, or a server that never advertised —
        #: attach nothing, so heartbeats degrade to plain pings.
        self.health_provider = None
        #: Optional zero-arg callable returning a monotonic REVISION for
        #: the provider's current report. When installed (PSWorker bumps
        #: it on every report mutation), the JSON encode of the report is
        #: cached per revision and spliced into the envelope as a
        #: pre-encoded fragment (RawJSON) — heartbeat pings at replica-
        #: refresh cadence were re-serializing an unchanged report per
        #: RPC. Without it every attach re-encodes (legacy behavior).
        self.health_revision = None
        # (revision, RawJSON) — the heartbeat thread's pings and the
        # comms thread's pushes both consult/refresh this cache.
        self._health_enc: tuple | None = None  # guarded by: self._wire_lock
        #: Server-published shard map (docs/SHARDING.md), adopted from the
        #: registration reply (its presence IS the capability) and
        #: refreshed off fetch reply meta delta-gated on the version the
        #: client sends back as ``have_shard_map``. None against an
        #: unsharded server — the wire stays single-server.
        self.shard_map = None
        self._shard_map_version = 0
        #: Keys the last push reply reported DISOWNED (docs/SHARDING.md
        #: "Migration protocol"): the primary's map moved while this
        #: client pushed on a cached one, so that slice never applied
        #: there. The fan-out store re-routes it to the current owner
        #: under a fresh token; a plain RemoteStore caller may re-push or
        #: drop (one async gradient slice, same cost as a staleness
        #: reject).
        self.last_disowned: list[str] = []
        self.config = _RemoteConfig()
        # Last membership seen on the wire (elastic servers piggyback it on
        # Register/Fetch replies). Workers fetch at least once per K-step
        # window, so by an epoch boundary this reflects recent churn.
        self._membership: list[int] = []
        # Wire accounting (the reference logged pickled payload sizes at
        # the server; here the client counts the payloads of SUCCESSFUL
        # RPCs — experiments/run_wire_matrix.py turns these into MB/s).
        # Lock: the heartbeat thread's fetch races the training thread's
        # push (gRPC releases the GIL), and lost read-modify-writes would
        # silently undercount.
        import threading

        self._wire_lock = threading.Lock()
        self.wire_bytes_out = 0  # guarded by: self._wire_lock
        self.wire_bytes_in = 0  # guarded by: self._wire_lock
        self.rpc_counts: dict[str, int] = {}  # guarded by: self._wire_lock
        # Push-dedupe token source: a per-client nonce + counter makes every
        # push's token unique across client restarts too (a replacement
        # worker reusing an elastic slot must not collide with its
        # predecessor's last token).
        import uuid

        self._push_nonce = uuid.uuid4().hex[:12]
        self._push_count = 0
        # Live telemetry (telemetry/): per-RPC latency spans + wire byte
        # counters into the process registry, alongside the run-local wire
        # accounting above (wire_stats feeds METRICS_JSON exit rows; the
        # registry feeds the live snapshot stream / Prometheus endpoint).
        from ..telemetry import get_registry
        reg = self._telemetry = get_registry()
        self._tm_rpc: dict[str, tuple] = {}
        for name in ["RegisterWorker", "PushGradrients", "FetchParameters",
                     "JobFinished", "Reshard", "SubmitJob"]:
            self._tm_rpc[name] = (
                reg.histogram("dps_rpc_client_seconds", rpc=name),
                reg.counter("dps_rpc_client_bytes_total", rpc=name,
                            direction="out"),
                reg.counter("dps_rpc_client_bytes_total", rpc=name,
                            direction="in"),
                reg.counter("dps_rpc_client_calls_total", rpc=name,
                            outcome="ok"),
                reg.counter("dps_rpc_client_calls_total", rpc=name,
                            outcome="retry"),
                reg.counter("dps_rpc_client_calls_total", rpc=name,
                            outcome="error"),
            )
        # Delta-fetch replies answered NOT_MODIFIED (header-only) — the
        # client-side twin of dps_store_fetch_not_modified_total.
        self._tm_fetch_nm = reg.counter(
            "dps_rpc_client_fetch_not_modified_total")

    def _invoke(self, name: str, request: bytes):
        """Call RPC ``name`` with a deadline, retrying transient failures
        (RETRYABLE_CODES) up to ``rpc_retries`` times with exponential
        backoff. Non-transient codes raise immediately."""
        hist, b_out, b_in, c_ok, c_retry, c_err = self._tm_rpc[name]
        delay = self.rpc_backoff
        for attempt in range(self.rpc_retries + 1):
            t0 = _tnow()
            # One trace span per ATTEMPT (not per logical call): a retried
            # RPC's trace tree shows each wire round trip, and the error
            # attr on a failed attempt marks exactly where time went.
            with trace_span("rpc.client", rpc=name, attempt=attempt) as sp:
                try:
                    reply = self._call[name](request,
                                             timeout=self.rpc_timeout)
                except grpc.RpcError as e:
                    # Failed attempts record their latency too — a
                    # deadline expiry spent real wall time, and dropping
                    # it would bias the distribution toward the happy
                    # path.
                    hist.observe(_tnow() - t0)
                    code = e.code() if callable(getattr(e, "code", None)) \
                        else None
                    # Mark the span even when the retry path SWALLOWS the
                    # exception (the span exits cleanly then, so the
                    # automatic error attr would not fire) — a retry
                    # storm's post-mortem must show which attempts burned
                    # the time.
                    sp.attrs["error"] = (code.name if code is not None
                                         else type(e).__name__)
                    if code not in RETRYABLE_CODES:
                        c_err.inc()
                        raise
                    if attempt >= self.rpc_retries:
                        # Transient failures outlived the budget: the
                        # server is down or restarting. Escalate as the
                        # catchable session-loss signal (the worker's
                        # reconnect state machine takes it from here)
                        # rather than a bare RpcError the caller can only
                        # die on.
                        c_err.inc()
                        raise SessionLostError(
                            f"{name} failed with {code.name} after "
                            f"{attempt + 1} attempts against "
                            f"{self.address}") from e
                    c_retry.inc()
                else:
                    hist.observe(_tnow() - t0)
                    with self._wire_lock:
                        self.wire_bytes_out += len(request)
                        self.wire_bytes_in += len(reply)
                        self.rpc_counts[name] = \
                            self.rpc_counts.get(name, 0) + 1
                    b_out.inc(len(request))
                    b_in.inc(len(reply))
                    c_ok.inc()
                    return reply
            time.sleep(delay)
            delay *= 2

    def _build_channel(self) -> None:
        """(Re)build the channel + method stubs + fault wrappers — the ONE
        place the method list and channel options are wired, shared by
        construction and ``reset_channel`` so the two can never drift."""
        self._channel = grpc.insecure_channel(self.address,
                                              options=GRPC_OPTIONS)
        ident = lambda b: b  # noqa: E731
        self._call = {
            name: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=ident, response_deserializer=ident)
            for name in ["RegisterWorker", "PushGradrients",
                         "FetchParameters", "JobFinished", "Reshard",
                         "SubmitJob"]
        }
        if self.faults is not None:
            from .faults import install_client_faults
            install_client_faults(self, self.faults)

    def reset_channel(self) -> None:
        """Tear down and rebuild the gRPC channel + method stubs.

        A channel that was connected to a server process that DIED can
        stay wedged in connect-failure backoff even after a replacement
        is listening on the same port (observed: every attempt fails
        'Timeout occurred: FD Shutdown' against a live listener, while a
        fresh channel connects instantly). The worker's reconnect state
        machine calls this before each re-registration attempt. Client-
        side fault injection survives the reset (same injector, same
        schedule state, re-installed over the fresh stubs); ad-hoc test
        wrappers around the old stubs do not — by the time a reset
        happens their work (killing a server at call N) is done.

        Closes the abandoned channel BEFORE building its replacement:
        close() releases the old channel's sockets/fds synchronously, so
        a worker that reconnects many times (flapping network, chaos
        drills) holds at most one channel at a time. The old order —
        build first, close after — left a window per reset where two
        channels were live, and an exception from _build_channel leaked
        the old one entirely (tests/test_recovery.py pins the no-growth
        invariant)."""
        old, self._channel = self._channel, None
        try:
            old.close()
        except Exception:  # noqa: BLE001 — a dead channel may complain
            pass
        self._build_channel()

    def wire_stats(self) -> dict:
        """Cumulative client-side wire accounting (bytes + per-RPC counts
        of successful calls); PSWorker merges this into its METRICS_JSON
        row."""
        with self._wire_lock:
            return {"wire_bytes_out": self.wire_bytes_out,
                    "wire_bytes_in": self.wire_bytes_in,
                    "rpc_counts": dict(self.rpc_counts)}

    def _note_membership(self, reply_meta: dict) -> None:
        m = reply_meta.get("active_workers")
        if m is not None:
            self._membership = [int(w) for w in m]

    def _note_directives(self, reply_meta: dict) -> None:
        """Collect piggybacked server->worker directives off a reply
        (capability-gated; docs/ROBUSTNESS.md). Dedupe by seq — the
        server re-attaches outstanding directives until acked, so the
        same directive may arrive on several replies. Malformed entries
        are dropped; directives must never fail the RPC that carried
        them."""
        if not self.supports_directives:
            # Never negotiated: a directive-shaped key from a confused
            # peer must not steer this worker (cap-gate discipline).
            return
        ds = reply_meta.get("directives")
        if not isinstance(ds, list):
            return
        with self._wire_lock:
            for d in ds:
                if not isinstance(d, dict):
                    continue
                try:
                    seq = int(d["seq"])
                except (KeyError, TypeError, ValueError):
                    continue
                if seq <= self._directive_last_seq \
                        or not isinstance(d.get("action"), str):
                    continue
                self._directive_last_seq = seq
                self._pending_directives.append(dict(d))

    def take_directives(self) -> list[dict]:
        """Drain the pending directives (worker loop, step boundaries)."""
        with self._wire_lock:
            out, self._pending_directives = self._pending_directives, []
            return out

    def _attach_directive_ack(self, meta: dict) -> None:
        if self.supports_directives:
            # Under the lock: the heartbeat thread's fetch replies may
            # advance the watermark concurrently with a push's attach.
            with self._wire_lock:
                meta["directives_ack"] = self._directive_last_seq

    def _note_qscales(self, reply_meta: dict) -> None:
        """Adopt a piggybacked shared-scale table (register/fetch reply
        meta). A malformed table degrades to the cached one — scales are
        an optimization hint, never worth failing an RPC over."""
        if not self.supports_compressed_domain:
            # Scales only exist under compressed-domain aggregation; an
            # ungated adopt would cache a table nothing consumes.
            return
        qs = reply_meta.get("qscales")
        if not isinstance(qs, dict):
            return
        try:
            table = {str(k): float(v) for k, v in qs.items()}
            step = int(reply_meta.get("qscale_step", 0))
        except (TypeError, ValueError):
            return
        # One lock write for the PAIR: the heartbeat thread's ping can
        # adopt a refresh while the training thread quantizes against
        # gradient_scales(); without the lock the reader could pair the
        # new table with the old version stamp (or vice versa) and
        # desync from the server's dequant scales.
        with self._wire_lock:
            self._qscales = table
            self._qscale_step = step

    def gradient_scales(self) -> tuple[dict[str, float], int]:
        """Client-side cache of the server's per-layer gradient absmax
        table (PSWorker quantizes against it; docs/WIRE_PROTOCOL.md)."""
        with self._wire_lock:
            return dict(self._qscales), self._qscale_step

    def _note_shard_map(self, reply_meta: dict) -> None:
        """Adopt a piggybacked shard map (register/fetch reply meta).
        Validated before adoption; a garbled or older map degrades to the
        cached one — routing must never regress off a bad refresh."""
        m = reply_meta.get("shard_map")
        if m is None:
            return
        from ..ps.sharding import validate_shard_map
        try:
            norm = validate_shard_map(m)
        except ValueError:
            return
        if self.shard_map is None \
                or norm["version"] >= self._shard_map_version:
            self.shard_map = norm
            self._shard_map_version = norm["version"]

    def membership_snapshot(self) -> list[int]:
        """Client-side view of the server's live membership (sorted ids),
        as of the most recent Register/Fetch reply. Empty until the first
        reply from an elastic server."""
        return list(self._membership)

    def register_worker(self, worker_name: str = "",
                        retries: int | None = None) -> tuple[int, int]:
        """Retry x5 with exponential backoff (worker.py:215-229).
        ``retries`` overrides the constructor budget — the reconnect state
        machine passes 1 and paces its own backoff against the overall
        reconnect window instead."""
        hist, b_out, b_in, c_ok, c_retry, c_err = \
            self._tm_rpc["RegisterWorker"]
        delay = 1.0
        last_err = None
        register_retries = (self.register_retries if retries is None
                            else max(1, int(retries)))
        for attempt in range(register_retries):
            t0 = _tnow()
            try:
                # ``capabilities`` advertises what THIS client can act on
                # (directives flow server->worker); an old server ignores
                # the field (docs/ROBUSTNESS.md). The requested job rides
                # the same envelope: a pre-tenancy server ignores it and
                # the worker lands in the only job there is.
                req_meta = {"worker_name": worker_name,
                            "capabilities": ["directives"]}
                if self.job is not None:
                    req_meta["job"] = str(self.job)
                request = pack_msg(req_meta)
                # Deadline like the hot RPCs: an undeadlined registration
                # against a half-up server would hang the worker (and the
                # reconnect state machine) indefinitely.
                raw = self._call["RegisterWorker"](request,
                                                   timeout=self.rpc_timeout)
                hist.observe(_tnow() - t0)
                b_out.inc(len(request))
                b_in.inc(len(raw))
                c_ok.inc()
                reply, _ = unpack_msg(raw)
                self.push_codec = reply.get("push_codec", "none")
                self.fetch_codec = reply.get("fetch_codec", "none")
                self.supports_delta_fetch = bool(
                    reply.get("delta_fetch", False))
                self.supports_trace_context = bool(
                    reply.get("trace_context", False))
                self.supports_health_report = bool(
                    reply.get("health_report", False))
                self.supports_compressed_domain = bool(
                    reply.get("compressed_domain", False))
                self.supports_directives = bool(
                    reply.get("directives", False))
                self.supports_checksum = bool(
                    reply.get("checksum", False))
                # Tenancy handshake (docs/TENANCY.md): adopt the job the
                # server placed us in — it may differ from the request
                # (garbled/unknown ids degrade to the default job), and
                # every subsequent envelope must carry the SERVER's
                # answer, not our wish.
                self.supports_jobs = bool(reply.get("jobs", False))
                if self.supports_jobs:
                    self.job = reply.get("job") or self.job
                # A fresh registration (incl. session resume against a
                # restarted server) starts a fresh directive stream: the
                # new server's seqs restart from 1, so a stale watermark
                # would suppress every delivery.
                # Registration is the negotiation point: drop any cached
                # scale table before adopting the reply's. A crash-
                # RESTORED server restarts its scale versions from 0 — a
                # stale higher version kept across session resume would
                # make have_qscales suppress every refresh until the new
                # server's version caught up.
                with self._wire_lock:
                    self._pending_directives = []
                    self._directive_last_seq = 0
                    self._qscales, self._qscale_step = {}, 0
                self._note_qscales(reply)
                # Same discipline for the shard map: a restarted primary's
                # map versions restart from 1, so the cached version must
                # not suppress the fresh map's adoption.
                self.shard_map, self._shard_map_version = None, 0
                self._note_shard_map(reply)
                self.config.elastic = bool(reply.get("elastic", False))
                self.config.mode = reply.get("mode", "sync")
                self.config.learning_rate = float(
                    reply.get("learning_rate", 0.1))
                self.config.staleness_bound = int(
                    reply.get("staleness_bound", 5))
                self._note_membership(reply)
                return int(reply["worker_id"]), int(reply["total_workers"])
            except grpc.RpcError as e:
                hist.observe(_tnow() - t0)
                # The LAST failed attempt is an error (the caller sees
                # ConnectionError), not a retry — dashboards alert on it.
                if attempt == register_retries - 1:
                    c_err.inc()
                else:
                    c_retry.inc()
                    time.sleep(delay)
                    delay *= 2
                last_err = e
        raise ConnectionError(
            f"registration failed after {register_retries} attempts: "
            f"{last_err}")

    def _attach_job(self, meta: dict) -> None:
        """Label an outbound envelope with this client's job
        (capability-gated: only after the server advertised ``jobs`` at
        registration — a legacy server never sees the key, the
        delta_fetch discipline; docs/TENANCY.md)."""
        if self.supports_jobs and self.job:
            meta["job"] = str(self.job)

    def _attach_health(self, meta: dict) -> None:
        """Piggyback the worker's current health report on an outbound
        fetch/push envelope (capability-gated; docs/OBSERVABILITY.md).
        A provider failure degrades to a report-less message — the health
        layer must never fail the RPC that would have carried it."""
        if not self.supports_health_report or self.health_provider is None:
            return
        rev = None
        if self.health_revision is not None:
            try:
                rev = self.health_revision()
            except Exception:  # noqa: BLE001
                rev = None
        if rev is not None:
            with self._wire_lock:
                cached = self._health_enc
            if cached is not None and cached[0] == rev:
                meta["health"] = cached[1]
                return
        try:
            report = self.health_provider()
        except Exception:  # noqa: BLE001
            return
        if isinstance(report, dict) and report:
            if rev is None:
                meta["health"] = report
                return
            enc = RawJSON(json.dumps(report))
            with self._wire_lock:
                self._health_enc = (rev, enc)
            meta["health"] = enc

    def fetch(self, worker_id: int | None = None,
              have_step: int | None = None
              ) -> tuple[dict[str, np.ndarray], int]:
        """Fetch params (+ step). With ``have_step`` (and a server that
        advertised ``delta_fetch``), a server whose step hasn't advanced
        replies NOT_MODIFIED — returned as ``({}, step)`` with
        ``step == have_step`` — and the caller keeps its current params;
        the round trip costs a header instead of the full model."""
        from .wire import decode_tensor_dict
        meta = {} if worker_id is None else {"worker_id": worker_id}
        self._attach_job(meta)
        if worker_id is not None:
            self._attach_health(meta)
            self._attach_directive_ack(meta)
        if have_step is not None and self.supports_delta_fetch:
            meta["have_step"] = int(have_step)
        if self.supports_compressed_domain:
            # Scale-table delta handshake: the server attaches qscales to
            # the reply only when its version is newer than this.
            with self._wire_lock:
                meta["have_qscales"] = self._qscale_step
        if self.shard_map is not None:
            # Shard-map delta handshake (docs/SHARDING.md): the server
            # attaches a map only when its version is newer than this.
            meta["have_shard_map"] = self._shard_map_version
        if self.supports_trace_context:
            # A fetch request carries no tensor frame, so the trace
            # context rides the envelope meta (docs/WIRE_PROTOCOL.md);
            # None (tracing off / no open span) attaches nothing.
            wt = current_wire_trace()
            if wt is not None:
                meta["trace"] = wt
        reply = self._invoke("FetchParameters", pack_msg(meta))
        rmeta, payload = unpack_msg(reply)
        self._note_membership(rmeta)
        self._note_qscales(rmeta)
        self._note_directives(rmeta)
        self._note_shard_map(rmeta)
        if rmeta.get("not_modified"):
            self._tm_fetch_nm.inc()
            return {}, int(rmeta["global_step"])
        with trace_span("worker.codec", stage="decode"):
            params = decode_tensor_dict(payload)
            if self.fetch_codec == "fp16":
                # serve --fetch-codec: the server halves the params-in
                # wire term (the reference's dominant cost,
                # server.py:222); restore fp32 here so callers never see
                # compressed dtypes. Wire accounting above already
                # counted the COMPRESSED reply. (PSWorker sees
                # decompresses_fetches and does NOT cast again.)
                from ..ops.compression import fp16_decompress
                params = fp16_decompress(params)
            elif self.fetch_codec == "bf16":
                from ..ops.compression import bf16_decompress
                params = bf16_decompress(params)
        return params, int(rmeta["global_step"])

    def push(self, worker_id: int, gradients: dict, fetched_step: int) -> bool:
        """Encode and send as-is: the caller (PSWorker._push) applies the
        codec, so compressed bytes hit the wire exactly once."""
        from .wire import encode_tensor_dict
        self._push_count += 1
        # Trace context rides the v2 FRAME header (capability-gated): the
        # request bytes are packed once — token and trace included — and
        # retried verbatim, so every retry carries the same span identity.
        # The same object is duplicated into the envelope meta so the
        # server's wrapper reads it without re-parsing the frame header
        # (docs/WIRE_PROTOCOL.md); the frame field remains the wire
        # contract for peers that only speak frames.
        wt = current_wire_trace() if self.supports_trace_context else None
        token = f"{self._push_nonce}:{self._push_count}"
        meta = {"worker_id": worker_id, "fetched_step": fetched_step,
                "push_token": token}
        self._attach_job(meta)
        if wt is not None:
            meta["trace"] = wt
        self._attach_health(meta)
        self._attach_directive_ack(meta)
        payload = encode_tensor_dict(gradients, trace=wt,
                                     checksum=self.supports_checksum)
        # Recorded BEFORE the send: a push that dies mid-RPC is exactly
        # the one the reconnect path must be able to re-send verbatim.
        self._last_push = (token, payload, int(fetched_step))
        reply = self._invoke("PushGradrients", pack_msg(meta, payload))
        rmeta, _ = unpack_msg(reply)
        self._note_directives(rmeta)
        # A push that raced a live migration (docs/SHARDING.md "Migration
        # protocol") comes back with the PRIMARY'S fresh map plus the list
        # of keys it disowned rather than applied. Adopt the map first so
        # any re-route below already targets the new owner.
        self._note_shard_map(rmeta)
        if self.shard_map is not None:
            d = rmeta.get("disowned")
            self.last_disowned = \
                [str(k) for k in d] if isinstance(d, list) else []
        return bool(rmeta["accepted"])

    def reshard_op(self, op: str, payload: bytes = b"",
                   **fields) -> tuple[dict, bytes]:
        """Admin-plane Reshard RPC (docs/SHARDING.md "Migration
        protocol"): ``export`` / ``import`` / ``apply_ranges`` /
        ``commit`` against ONE primary. Returns the raw reply
        ``(meta, payload)`` — the coordinator (``cli reshard``) owns the
        protocol ordering and interprets the fields; this client only
        carries the envelope. Extra keyword fields (``slot_lo``,
        ``slot_hi``, ``ranges``, ``map_version``, ``journal``) pass
        through to the request meta verbatim."""
        request = pack_msg({"op": op, **fields}, payload)
        reply = self._invoke("Reshard", request)
        return unpack_msg(reply)

    def submit_job(self, spec: str) -> dict:
        """Admin-plane SubmitJob RPC (docs/TENANCY.md): declare a new
        job from a one-entry ``--jobs``-grammar spec string. Returns the
        reply meta ({"submitted", "index", "jobs"}). Single-job servers
        answer FAILED_PRECONDITION."""
        reply = self._invoke("SubmitJob", pack_msg({"job_spec": str(spec)}))
        meta, _ = unpack_msg(reply)
        return meta

    def drain_job(self, name: str) -> dict:
        """Admin-plane job drain (docs/TENANCY.md): remove a drained
        job and its per-job metric series server-side."""
        reply = self._invoke("SubmitJob", pack_msg({"drain_job": str(name)}))
        meta, _ = unpack_msg(reply)
        return meta

    def repush_last(self, worker_id: int) -> bool | None:
        """Re-send the most recent push — same token, same payload, same
        ``fetched_step`` — under (possibly) a new worker id. The session-
        resume reconciliation path: the server's dedupe table is keyed by
        the token's NONCE, not the worker id, so if the pre-crash server
        applied this push and journaled it, the replay answers
        ``duplicate`` from the journal instead of applying twice; if the
        apply was lost with the crash, it applies now. Returns the
        accepted outcome, or None when there is nothing to re-send."""
        if self._last_push is None:
            return None
        token, payload, fetched_step = self._last_push
        meta = {"worker_id": worker_id, "fetched_step": fetched_step,
                "push_token": token}
        self._attach_job(meta)
        reply = self._invoke("PushGradrients", pack_msg(meta, payload))
        rmeta, _ = unpack_msg(reply)
        return bool(rmeta["accepted"])

    def job_finished(self, worker_id: int) -> None:
        self._invoke("JobFinished", pack_msg({"worker_id": worker_id}))

    def close(self) -> None:
        self._channel.close()
