"""Delta-fed read replica: the wide cheap tier of the sharded topology.

A :class:`ReplicaServer` speaks the same four RPCs as a shard primary
(docs/WIRE_PROTOCOL.md) but holds no store at all — it subscribes to its
primary over the delta-fetch protocol (a background loop polls
``FetchParameters`` with ``have_step``; an idle primary answers with the
cached header-only NOT_MODIFIED reply, so an up-to-date replica costs the
primary a few bytes per poll) and serves fetch traffic from **cached
bytes**:

- the primary's tensor payload is kept VERBATIM — never decoded — and the
  full fetch reply is pre-encoded once per step, so serving a fetch is a
  dict lookup plus a socket write (this, times N replicas, is the ≥10×
  aggregate fetch-QPS lever the recorded experiment pins);
- ``have_step`` fetches at the replica's current step get the pre-encoded
  NOT_MODIFIED reply — the delta protocol composes through the tier.

Writes don't belong here: RegisterWorker / PushGradrients / JobFinished
answer a ``redirect`` to the primary (docs/SHARDING.md "Routing rules").

**Staleness contract**: every successful poll (including NOT_MODIFIED —
the primary confirming "your step is current" is freshness) stamps
``last_sync``; once that stamp ages past ``staleness_bound_s`` the
replica REFUSES fetches (UNAVAILABLE, redirect in the detail) instead of
serving arbitrarily old params. A replica can be behind by at most one
poll interval of real data, and a partitioned replica fails loud.

Each poll announces ``replica: {shard_id, address, parent, tier, ...}``
in the fetch meta; the primary's ShardInfo (ps/sharding.py) turns that
plus ``have_step`` into the published replica membership and the
``dps_replica_lag_*`` gauges.

**Fan-out trees** (docs/SHARDING.md "Fan-out trees"): a replica can
subscribe to ANOTHER replica instead of the primary (``parent=``), so
the serve tier forms a tree — the primary feeds a few interior nodes,
each interior node re-serves the same delta protocol to its children.
Three mechanisms make the tree honest:

- **tiers**: each node learns its tier from its parent's reply head
  (primary replies are tier 0's, a parent replica stamps ``tier`` in
  its re-packed head), and the default staleness bound scales with it
  (``tier_staleness_bound``) — edge tiers tolerate proportionally more
  lag, while an explicit ``staleness_bound_s`` stays a per-node
  override. Child announces are cached and forwarded UPSTREAM as
  ``descendants``, so the whole subtree reaches the primary's shard
  view; the primary's topology flows DOWNSTREAM as a delta-gated
  ``topology`` attachment (``have_topology`` versioning, same
  discipline as the shard map).
- **coalescing**: identical delta polls (``have_step == current``)
  arriving while an upstream refresh is in flight park on a
  single-flight latch and are all answered from the one refreshed
  payload — the same pre-encoded bytes, zero extra encodes
  (``dps_replica_coalesced_total`` / ``dps_coalesce_ratio``).
- **re-parenting**: after ``reparent_after`` consecutive refresh
  failures the node picks a new subscribe source from its cached
  topology (prefer the dead parent's tier, i.e. own tier minus one;
  fall back to the primary), guarded by a ``reparent_cooldown_s``
  hysteresis window so a flapping parent cannot make children ricochet
  around the tree. Writes always redirect to the PRIMARY regardless of
  who feeds the subscription.

**Inference serving (canary-gated)**: with ``canary=True`` the replica
keeps a short HISTORY of per-step reply bytes instead of only the
latest, and splits ``infer`` fetches across two pinned steps — the
STABLE step serves ~95% of requests, the newest candidate (CANARY)
~5%. Clients report a quality score for responses they served
(``quality`` request meta); once both arms have enough samples the
:class:`CanaryController` either PROMOTES the candidate (its quality is
within tolerance of stable's) or ROLLS IT BACK (marks the step bad, so
it is never offered again). Training-path fetches are untouched — they
always serve the newest synced step (docs/SHARDING.md "Serve tier").
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent import futures

import grpc

from ..telemetry.journal import journal_event
from .service import GRPC_OPTIONS, SERVICE_NAME, pack_msg, unpack_msg

__all__ = ["CanaryController", "ReplicaServer", "tier_staleness_bound"]

#: Base staleness bound (tier 1 — a direct child of the primary keeps
#: the pre-tree default of 5 s).
DEFAULT_STALENESS_BOUND_S = 5.0

#: A child that stops polling is dropped from the forwarded
#: ``descendants`` after this long — same horizon as the primary's
#: ShardInfo replica expiry, so the two views age out together.
CHILD_EXPIRE_S = 30.0


def tier_staleness_bound(tier: int,
                         base: float = DEFAULT_STALENESS_BOUND_S) -> float:
    """Default staleness bound for a node at ``tier`` (docs/SHARDING.md
    "Fan-out trees"): bound = base × tier. Every hop adds at most one
    poll interval of real data lag plus one refresh of clock skew, so
    the tolerated announce age must grow linearly with depth — an edge
    node rejecting fetches because its *grandparent* was one base-bound
    late would make deep trees fail exactly when they are healthy."""
    return float(base) * max(1, int(tier))


class CanaryController:
    """Promote/rollback state machine over (stable_step, canary_step).

    Pure decision logic — no wire, no locks (the owner serializes calls
    under its own lock). Steps flow in via :meth:`offer` (each newer
    primary step becomes the candidate, unless previously rolled back),
    quality samples via :meth:`note_quality`, and :meth:`decide` resolves
    the race once BOTH arms have ``min_samples``: promote when the
    canary's mean quality is no worse than stable's minus ``tolerance``,
    roll back otherwise. Rolled-back steps land in ``bad_steps`` and are
    never re-offered — the regression stays fenced even though the
    training run that produced it keeps publishing newer steps."""

    def __init__(self, fraction: float = 0.05, min_samples: int = 20,
                 tolerance: float = 0.0, window: int = 256):
        if not 0.0 < fraction <= 0.5:
            raise ValueError(f"canary fraction must be in (0, 0.5], "
                             f"got {fraction}")
        #: Every ``period``-th infer request serves the canary arm —
        #: deterministic, so a test (or an operator reading loadgen
        #: percentiles) sees exactly the configured split.
        self.period = max(2, round(1.0 / float(fraction)))
        self.min_samples = max(1, int(min_samples))
        self.tolerance = float(tolerance)
        self.stable_step: int | None = None
        self.canary_step: int | None = None
        self.bad_steps: set[int] = set()
        self.promotions = 0
        self.rollbacks = 0
        self._requests = 0
        self._quality = {"stable": deque(maxlen=window),
                         "canary": deque(maxlen=window)}

    def offer(self, step: int) -> None:
        """A newly synced step: first ever becomes stable outright;
        anything newer becomes (or replaces) the canary candidate, with
        a fresh quality window — samples for an older candidate say
        nothing about this one."""
        step = int(step)
        if self.stable_step is None:
            self.stable_step = step
            return
        if step <= max(self.stable_step, self.canary_step or 0) \
                or step in self.bad_steps:
            return
        self.canary_step = step
        self._quality["canary"].clear()

    def pick_arm(self) -> str:
        """Route one infer request. Counter-based: request k goes to the
        canary iff a candidate exists and k % period == 0."""
        self._requests += 1
        if self.canary_step is not None \
                and self._requests % self.period == 0:
            return "canary"
        return "stable"

    def note_quality(self, arm: str, step: int, value: float) -> None:
        """Ingest one client-reported score. Stamped with the step the
        client was SERVED — feedback for a step that is no longer the
        arm's current step is dropped (it would pollute the window that
        decides a different step's fate)."""
        current = (self.stable_step if arm == "stable"
                   else self.canary_step)
        if current is not None and int(step) == current:
            self._quality[arm].append(float(value))

    def decide(self) -> str | None:
        """Resolve the candidate once both windows are full enough.
        Returns "promote" / "rollback" / None (still collecting)."""
        if self.canary_step is None:
            return None
        sq, cq = self._quality["stable"], self._quality["canary"]
        if len(sq) < self.min_samples or len(cq) < self.min_samples:
            return None
        stable_mean = sum(sq) / len(sq)
        canary_mean = sum(cq) / len(cq)
        if canary_mean >= stable_mean - self.tolerance:
            self.stable_step = self.canary_step
            self._quality["stable"] = deque(cq, maxlen=cq.maxlen)
            self.promotions += 1
            outcome = "promote"
        else:
            self.bad_steps.add(self.canary_step)
            self.rollbacks += 1
            outcome = "rollback"
        self.canary_step = None
        self._quality["canary"].clear()
        return outcome

    def view(self) -> dict:
        return {"stable_step": self.stable_step,
                "canary_step": self.canary_step,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "bad_steps": sorted(self.bad_steps),
                "period": self.period}


class ReplicaServer:
    """Read-only cache of one shard primary, behind the PS wire."""

    def __init__(self, primary: str, port: int = 0, shard_id: int = 0,
                 advertise: str | None = None,
                 metrics_advertise: str | None = None,
                 poll_interval: float = 0.05,
                 staleness_bound_s: float | None = None,
                 rpc_timeout: float = 10.0,
                 clock=time.time,
                 canary: bool = False,
                 canary_fraction: float = 0.05,
                 canary_min_samples: int = 20,
                 canary_tolerance: float = 0.0,
                 history: int = 8,
                 faults=None,
                 parent: str | None = None,
                 reparent_after: int = 3,
                 reparent_cooldown_s: float = 5.0,
                 coalesce: bool = True,
                 coalesce_wait_s: float | None = None):
        self.primary = primary
        #: Subscribe source — the primary itself, or an interior replica
        #: when this node is a deeper tier of a fan-out tree. Writes
        #: ALWAYS redirect to ``primary``; only the refresh subscription
        #: follows ``parent`` (and re-parenting moves it).
        self.parent = parent or primary
        self.port = int(port)
        self.shard_id = int(shard_id)
        #: The address announced to the primary (what the shard map
        #: publishes to clients); filled from the bound port at start()
        #: when not given.
        self.advertise = advertise
        #: The metrics-endpoint address announced alongside it (host:port
        #: of this process's /metrics server, when one is running) — how
        #: the fleet collector (telemetry/fleet.py) discovers replicas as
        #: scrape targets from the primary's /cluster view.
        self.metrics_advertise = metrics_advertise
        self.poll_interval = float(poll_interval)
        #: Tier = parent's tier + 1, learned from the parent's reply
        #: head each poll (a primary reply carries no ``replica`` flag,
        #: so its children land at tier 1). Provisional until the first
        #: successful poll.
        self.tier = 1 if self.parent == self.primary else 2
        #: Explicit bound = per-node override; None = derived from the
        #: tier (``tier_staleness_bound``), re-derived when it changes.
        self._staleness_override = staleness_bound_s is not None
        self.staleness_bound_s = (float(staleness_bound_s)
                                  if self._staleness_override
                                  else tier_staleness_bound(self.tier))
        self.rpc_timeout = float(rpc_timeout)
        self.clock = clock
        self.reparent_after = max(1, int(reparent_after))
        self.reparent_cooldown_s = float(reparent_cooldown_s)
        self.coalesce = bool(coalesce)
        #: How long an identical delta poll parks on the single-flight
        #: latch before giving up and serving the (still valid) cached
        #: NOT_MODIFIED reply — bounded so a slow parent can never turn
        #: coalescing into consumer-visible hangs.
        self._coalesce_wait_s = (float(coalesce_wait_s)
                                 if coalesce_wait_s is not None
                                 else min(1.0, max(0.05,
                                                   4 * self.poll_interval)))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._step: int | None = None     # guarded by: self._lock
        self._reply: bytes = b""          # guarded by: self._lock
        self._nm_reply: bytes = b""       # guarded by: self._lock
        self._last_sync: float | None = None  # guarded by: self._lock
        #: Single-flight refresh latch state: inflight is True while a
        #: poll RPC is on the wire; gen bumps when it lands (success OR
        #: failure), releasing parked fetches.
        self._refresh_inflight = False  # guarded by: self._lock
        self._refresh_gen = 0       # guarded by: self._lock
        self._poll_rounds = 0       # guarded by: self._lock
        self._coalesced_count = 0   # guarded by: self._lock
        self._serves = 0            # guarded by: self._lock
        #: address -> child announce row — the subtree this node
        #: forwards upstream as ``descendants``.
        self._children: dict[str, dict] = {}  # guarded by: self._lock
        #: Last adopted topology view + the head of the last content
        #: re-pack. ``_nm_topo_reply`` is the pre-encoded NOT_MODIFIED
        #: variant with the topology attached, served to children whose
        #: ``have_topology`` is behind.
        self._topology: dict | None = None  # guarded by: self._lock
        self._head: dict | None = None      # guarded by: self._lock
        self._nm_topo_reply: bytes = b""    # guarded by: self._lock
        #: Re-parent hysteresis stamp (poll-thread only).
        self._last_reparent = float("-inf")
        #: Canary serve state (all guarded by: self._lock). ``canary``
        #: is the controller or None (training-path replicas carry no
        #: history and serve infer fetches like plain fetches).
        self.canary = CanaryController(
            fraction=canary_fraction, min_samples=canary_min_samples,
            tolerance=canary_tolerance) if canary else None
        self._history = max(2, int(history))
        # step -> primary payload; guarded by: self._lock
        self._payloads: dict[int, bytes] = {}
        self._arm_replies: dict[str, bytes] = {}  # guarded by: self._lock
        # Deterministic replica-tier fault injection (comms/faults.py):
        # ``refresh.*`` rules wrap the subscription poll (this replica as
        # a client of its primary), ``subscribe.*`` rules its own serving
        # handler. Env DPS_FAULTS_REPLICA applies when the caller passes
        # nothing — autoscaler-spawned replicas inherit the environment,
        # so one seeded schedule covers the whole elastic tier.
        if faults is None:
            faults = os.environ.get("DPS_FAULTS_REPLICA") or None
        if faults is not None and isinstance(faults, str):
            from .faults import FaultInjector
            faults = FaultInjector(faults, side="replica")
        self.faults = faults
        #: Refresh backoff ceiling: a dead primary is polled at most this
        #: often instead of hammered at poll_interval (the PR 5 heartbeat
        #: discipline applied to the replica tier).
        self._backoff_cap = max(1.0, 20.0 * self.poll_interval)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._server: grpc.Server | None = None
        self._channel = None
        self._fetch_stub = None
        from ..telemetry import LATENCY_BUCKETS, get_registry
        reg = get_registry()
        self._tm_fetches = reg.counter("dps_replica_fetches_total")
        self._tm_refreshes = reg.counter("dps_replica_refreshes_total")
        # Refresh DURATION (wire transfer + re-pack) on the shared
        # LATENCY_BUCKETS scheme — distinct from dps_replica_lag_seconds,
        # which is an AGE gauge (time since last sync), not a duration.
        self._tm_refresh_hist = reg.histogram(
            "dps_replica_refresh_seconds", buckets=LATENCY_BUCKETS)
        self._tm_refresh_errors = reg.counter(
            "dps_replica_refresh_errors_total")
        # Serve-path latency (this replica answering client fetches,
        # incl. infer) on the SLO-grade scheme, with head-sampled trace
        # exemplars — the replica-tier half of the fleet observatory's
        # p99 -> trace join (docs/OBSERVABILITY.md "Fleet observatory").
        self._tm_serve_hist = reg.histogram(
            "dps_replica_serve_seconds", buckets=LATENCY_BUCKETS)
        from ..telemetry import ExemplarSampler
        self._exemplars = ExemplarSampler(rate=0.1, seed=os.getpid())
        self._tm_stale = reg.counter("dps_replica_stale_rejects_total")
        self._tm_redirects = reg.counter("dps_replica_redirects_total")
        self._tm_step = reg.gauge("dps_replica_step")
        # Fan-out tree + coalescing surface (docs/SHARDING.md "Fan-out
        # trees"): polls counts every completed refresh round trip
        # (incl. NOT_MODIFIED — the denominator of the coalesce ratio),
        # coalesced counts delta polls answered off someone else's
        # refresh, and the ratio gauge is their cumulative quotient.
        self._tm_polls = reg.counter("dps_replica_polls_total")
        self._tm_coalesced = reg.counter("dps_replica_coalesced_total")
        self._tm_coalesce_ratio = reg.gauge("dps_coalesce_ratio")
        self._tm_reparents = reg.counter("dps_replica_reparents_total")
        self._tm_tier = reg.gauge("dps_replica_tier")
        self._tm_tier.set(self.tier)
        self._tm_infer = {arm: reg.counter("dps_infer_requests_total",
                                           arm=arm)
                          for arm in ("stable", "canary")}
        self._tm_promote = reg.counter("dps_canary_promotions_total")
        self._tm_rollback = reg.counter("dps_canary_rollbacks_total")
        self._tm_stable_step = reg.gauge("dps_canary_stable_step")

    # -- subscription (replica -> primary) -----------------------------------

    # dpslint: hot-path — one refresh per primary step; re-pack only
    def _poll_once(self) -> None:
        """One refresh poll. The raw reply BYTES are the cache — the
        tensor payload is never decoded here, so a replica's refresh
        cost is the wire transfer plus one envelope re-pack, regardless
        of model size. While the RPC is on the wire the single-flight
        latch is raised: identical delta polls from children park on it
        and are all answered from this one refresh."""
        t0 = time.perf_counter()
        with self._lock:
            have = self._step
            serves = self._serves
            desc = self._descendant_rows_locked()
            topo_have = int((self._topology or {}).get("version", 0))
            self._refresh_inflight = True
        try:
            announce = {"shard_id": self.shard_id,
                        "address": self.advertise,
                        # parent/tier: poll-thread-only writes; other
                        # threads only ever read the atomic reference.
                        "parent": self.parent, "tier": self.tier,  # dpslint: ignore[thread-shared]
                        "fetches": serves}
            if self.metrics_advertise:
                # Adopted by the fleet collector's discovery pass via the
                # primary's sharding view (docs/OBSERVABILITY.md).
                announce["metrics"] = self.metrics_advertise
            if desc:
                # Forward the cached subtree so announces compose through
                # interior nodes — the primary's shard view sees every
                # tier, not just its direct children.
                announce["descendants"] = desc
            meta: dict = {"replica": announce, "have_topology": topo_have}
            if have is not None:
                meta["have_step"] = int(have)
            raw = self._fetch_stub(pack_msg(meta),
                                   timeout=self.rpc_timeout)
            rmeta, payload = unpack_msg(raw)
        except Exception:  # noqa: BLE001 — release the latch, re-raise
            with self._lock:
                self._refresh_done_locked()
            raise
        now = self.clock()
        # Tier = parent's tier + 1. A primary reply carries no
        # ``replica`` flag; a pre-tree parent replica stamps the flag
        # but no ``tier`` — assume tier 1 (it only ever fed off a
        # primary).
        ptier = int(rmeta.get("tier") or 1) if rmeta.get("replica") else 0
        self._set_tier(ptier + 1)
        topo = rmeta.get("topology")
        if rmeta.get("not_modified"):
            with self._lock:
                self._last_sync = now
                if isinstance(topo, dict):
                    self._adopt_topology_locked(topo)
                self._refresh_done_locked()
            self._tm_polls.inc()
            self._tm_refresh_hist.observe(time.perf_counter() - t0)
            return
        step = int(rmeta["global_step"])
        # Re-pack with the replica's own envelope over the primary's
        # payload bytes, once per step; every client fetch then serves
        # these exact bytes.
        head = {"global_step": step, "replica": True,
                "shard_id": self.shard_id, "tier": self.tier}
        reply = pack_msg(head, bytes(payload))
        nm_reply = pack_msg({**head, "not_modified": True})
        with self._lock:
            self._step = step
            self._reply = reply
            self._nm_reply = nm_reply
            self._head = head
            self._last_sync = now
            if isinstance(topo, dict):
                self._adopt_topology_locked(topo)
            elif self._topology is not None:
                self._repack_topo_reply_locked()
            if self.canary is not None:
                self._payloads[step] = bytes(payload)
                self.canary.offer(step)
                self._evict_history_locked()
                self._repack_arms_locked()
            self._refresh_done_locked()
        self._tm_refreshes.inc()
        self._tm_polls.inc()
        self._tm_step.set(step)
        self._tm_refresh_hist.observe(time.perf_counter() - t0)

    def _set_tier(self, tier: int) -> None:
        """Adopt a (possibly changed) tier: re-derive the staleness
        bound unless this node pinned an explicit override. Cached reply
        heads keep the old tier until the next content refresh — a
        transient that only delays children's own tier update by one
        step (docs/SHARDING.md "Fan-out trees")."""
        tier = max(1, int(tier))
        if tier == self.tier:
            return
        self.tier = tier
        if not self._staleness_override:
            # Poll-thread-only write of an atomic float reference; the
            # serve gate reads whichever bound is current.
            self.staleness_bound_s = tier_staleness_bound(tier)  # dpslint: ignore[thread-shared]
        self._tm_tier.set(tier)

    def _refresh_done_locked(self) -> None:
        """Lower the single-flight latch (success or failure) and
        release every parked delta poll — on failure they fall back to
        the still-valid cached reply rather than waiting out a backoff
        cycle."""
        self._refresh_inflight = False
        self._refresh_gen += 1
        self._poll_rounds += 1
        self._cond.notify_all()

    def _adopt_topology_locked(self, topo: dict) -> None:
        """Adopt a newer topology view from upstream and pre-encode the
        NOT_MODIFIED + topology variant children hydrate from."""
        have = int((self._topology or {}).get("version", 0))
        if int(topo.get("version", 0)) <= have:
            return
        self._topology = topo
        self._repack_topo_reply_locked()

    def _repack_topo_reply_locked(self) -> None:
        if self._head is not None and self._topology is not None:
            self._nm_topo_reply = pack_msg(
                {**self._head, "not_modified": True,
                 "topology": self._topology})

    def _descendant_rows_locked(self) -> list[dict]:
        """Flatten the cached child announces (plus THEIR descendants)
        into the rows forwarded upstream; silent children age out on
        the shared expiry horizon. Bounded — a malformed subtree cannot
        balloon the announce envelope."""
        now = self.clock()
        for addr in [a for a, row in self._children.items()
                     if now - row.get("ts", now) > CHILD_EXPIRE_S]:
            del self._children[addr]
        rows: list[dict] = []
        for row in self._children.values():
            rows.append({k: row[k]
                         for k in ("address", "shard_id", "parent",
                                   "tier", "step", "fetches", "metrics")
                         if row.get(k) is not None})
            rows.extend(row.get("descendants") or [])
        return rows[:64]

    def _note_child(self, meta: dict) -> None:
        """Ingest a child replica's announce (this node as its subscribe
        source): cache the row + its forwarded subtree so the next
        upstream poll relays the whole branch. Mirrors the primary's
        ShardInfo.note_replica, tier-tagged and keyed by address so a
        re-announce replaces rather than duplicates."""
        rep = meta.get("replica")
        if not isinstance(rep, dict) or not rep.get("address"):
            return
        row = {"address": str(rep["address"]),
               "shard_id": rep.get("shard_id", self.shard_id),
               "parent": rep.get("parent") or self.advertise,
               "tier": int(rep.get("tier") or self.tier + 1),
               "step": meta.get("have_step", 0),
               "fetches": rep.get("fetches"),
               "metrics": rep.get("metrics"),
               "descendants": rep.get("descendants") or [],
               "ts": self.clock()}
        with self._lock:
            self._children[row["address"]] = row

    def _evict_history_locked(self) -> None:
        """Cap the step history, never evicting a step an arm is pinned
        to — the stable payload must survive arbitrarily many newer
        steps."""
        pinned = {self.canary.stable_step, self.canary.canary_step}
        for step in sorted(self._payloads):
            if len(self._payloads) <= self._history:
                break
            if step not in pinned:
                del self._payloads[step]

    def _repack_arms_locked(self) -> None:
        """Pre-encode one full reply PER ARM (same once-per-change
        discipline as the train-path cache): serving an infer request is
        then a dict lookup regardless of model size."""
        arms: dict[str, bytes] = {}
        for arm, step in (("stable", self.canary.stable_step),
                          ("canary", self.canary.canary_step)):
            payload = self._payloads.get(step) if step is not None else None
            if payload is not None:
                arms[arm] = pack_msg(
                    {"global_step": step, "serving_step": step,
                     "arm": arm, "replica": True,
                     "shard_id": self.shard_id}, payload)
        self._arm_replies = arms
        if self.canary.stable_step is not None:
            self._tm_stable_step.set(self.canary.stable_step)

    def _poll_loop(self) -> None:
        """Refresh forever, backing off a dead primary. Consecutive
        failures double the wait up to ``_backoff_cap`` (capped
        exponential — an unreachable primary sees a few polls per
        second-ish, not a poll_interval-rate hammer), one log line per
        FAILING/RECOVERED transition, every failure counted. The
        staleness stamp keeps aging throughout, so the serve gate still
        fails loud."""
        failing = False
        failures = 0
        delay = self.poll_interval
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception as e:  # noqa: BLE001 — any refresh failure backs off
                self._tm_refresh_errors.inc()
                failures += 1
                if not failing:
                    failing = True
                    print(f"REPLICA_REFRESH_FAILING shard={self.shard_id} "
                          f"primary={self.primary} parent={self.parent} "
                          f"error={type(e).__name__}", flush=True)
                if failures >= self.reparent_after \
                        and self._maybe_reparent():
                    failures = 0
                    delay = self.poll_interval
                    continue
                self._stop.wait(delay)
                delay = min(delay * 2.0, self._backoff_cap)
                continue
            if failing:
                failing = False
                print(f"REPLICA_REFRESH_RECOVERED shard={self.shard_id} "
                      f"primary={self.primary} parent={self.parent}",
                      flush=True)
            failures = 0
            delay = self.poll_interval
            self._stop.wait(self.poll_interval)

    def _maybe_reparent(self) -> bool:
        """Sustained refresh failure: re-point the subscription at a new
        source picked from the cached topology, preferring the dead
        parent's own tier (our tier minus one) and falling back to the
        primary. The cooldown is the hysteresis guard — a flapping
        parent cannot make a child ricochet around the tree faster than
        once per window. Returns True when the stub was re-pointed."""
        now = time.monotonic()
        if now - self._last_reparent < self.reparent_cooldown_s:
            return False
        target = self._pick_parent()
        if target is None or target == self.parent:
            if self.parent == self.primary:
                return False
            target = self.primary
        self._last_reparent = now
        old, self.parent = self.parent, target
        self._connect()
        self._tm_reparents.inc()
        journal_event("reparent", shard=self.shard_id, old=old,
                      new=target, tier=self.tier)
        print(f"REPLICA_REPARENTED shard={self.shard_id} old={old} "
              f"new={target} tier={self.tier}", flush=True)
        return True

    def _pick_parent(self) -> str | None:
        """Choose a re-parent target from the cached topology: nodes at
        tier (own − 1) that are not us, not the dead parent, and not in
        our own subtree (adopting a descendant would close a cycle);
        lowest announced lag wins, address as the deterministic tie
        break. None = no candidate (caller falls back to the primary)."""
        with self._lock:
            topo = self._topology
            subtree = set(self._children)
        if not isinstance(topo, dict):
            return None
        nodes = [n for n in (topo.get("nodes") or [])
                 if isinstance(n, dict) and n.get("address")]
        by_addr = {str(n["address"]): n for n in nodes}
        # Close the subtree over the topology's parent pointers: any
        # node whose ancestry walks through us is ours.
        for addr in by_addr:
            a, seen = addr, set()
            while a in by_addr and a not in seen:
                seen.add(a)
                a = by_addr[a].get("parent")
                if a == self.advertise:
                    subtree.add(addr)
                    break
        want = max(1, self.tier - 1)
        pool = sorted(
            (float(n.get("lag_steps") or 0.0), str(n["address"]))
            for n in nodes
            if int(n.get("tier") or 1) == want
            and str(n["address"]) not in subtree
            and n["address"] not in (self.advertise, self.parent))
        if not pool:
            return str(topo.get("primary") or self.primary)
        return pool[0][1]

    def _connect(self) -> None:
        """(Re)build the subscription channel + stub to ``self.parent``,
        re-applying the refresh-side fault wrapper (the injector object
        is shared, so deterministic ``n=``/``every=`` schedules keep
        counting across a re-parent)."""
        ident = lambda b: b  # noqa: E731
        # stop() join()s the poll thread before touching _channel — the
        # join is the happens-before edge, no lock needed.
        if self._channel is not None:  # dpslint: ignore[thread-shared]
            self._channel.close()
        self._channel = grpc.insecure_channel(self.parent,
                                              options=GRPC_OPTIONS)
        stub = self._channel.unary_unary(
            f"/{SERVICE_NAME}/FetchParameters",
            request_serializer=ident, response_deserializer=ident)
        if self.faults is not None:
            from .faults import REFRESH_OP, _FaultyCall
            stub = _FaultyCall(stub, self.faults, REFRESH_OP)
        self._fetch_stub = stub

    # -- serving (client -> replica) -----------------------------------------

    def _fresh_or_abort(self, ctx):
        now = self.clock()
        with self._lock:
            last = self._last_sync
        if last is None or now - last > self.staleness_bound_s:
            self._tm_stale.inc()
            ctx.abort(grpc.StatusCode.UNAVAILABLE,
                      f"replica stale (last sync "
                      f"{'never' if last is None else round(now - last, 2)}"
                      f"); use primary {self.primary}")

    # dpslint: hot-path — the ≥10x fetch-QPS lever: dict lookup + write
    def _fetch_parameters(self, request: bytes, ctx) -> bytes:
        self._fresh_or_abort(ctx)
        meta, _ = unpack_msg(request)
        if self.canary is not None and meta.get("infer"):
            return self._serve_infer(meta)
        self._note_child(meta)
        have = meta.get("have_step")
        topo_have = meta.get("have_topology")
        self._tm_fetches.inc()
        with self._lock:
            self._serves += 1
            if have is not None and self._step is not None \
                    and int(have) == self._step:
                if self.coalesce and self._refresh_inflight:
                    # Single-flight latch: an identical delta poll
                    # arriving mid-refresh parks here; when the refresh
                    # lands every parked poll is answered from the one
                    # refreshed payload — the same pre-encoded bytes,
                    # zero extra encodes or upstream RPCs.
                    gen = self._refresh_gen
                    self._cond.wait_for(lambda: self._refresh_gen != gen,
                                        timeout=self._coalesce_wait_s)
                    self._coalesced_count += 1
                    self._tm_coalesced.inc()
                    self._tm_coalesce_ratio.set(
                        self._coalesced_count
                        / max(1, self._poll_rounds))
                    if self._step is not None \
                            and int(have) != self._step:
                        return self._reply
                if topo_have is not None and self._nm_topo_reply \
                        and self._topology is not None \
                        and int(topo_have) < int(
                            self._topology.get("version", 0)):
                    # Child behind on topology: serve the pre-encoded
                    # NOT_MODIFIED + topology variant so the view
                    # propagates down the tree (delta-gated — an
                    # up-to-date child gets the bare NM bytes).
                    return self._nm_topo_reply
                return self._nm_reply
            return self._reply

    def _serve_infer(self, meta: dict) -> bytes:
        """One inference request against the canary-split serve tier
        (docs/SHARDING.md "Serve tier"): ingest any piggybacked quality
        feedback, resolve the candidate if both windows filled, then
        route this request to an arm and answer its pre-encoded reply.
        Freshness was already gated by the caller."""
        q = meta.get("quality")
        with self._lock:
            if isinstance(q, dict):
                try:
                    self.canary.note_quality(str(q["arm"]),
                                             int(q["step"]),
                                             float(q["value"]))
                except (KeyError, TypeError, ValueError):
                    pass  # malformed feedback never fails the serve
                outcome = self.canary.decide()
                if outcome is not None:
                    (self._tm_promote if outcome == "promote"
                     else self._tm_rollback).inc()
                    self._evict_history_locked()
                    self._repack_arms_locked()
            arm = self.canary.pick_arm()
            reply = self._arm_replies.get(arm) \
                or self._arm_replies.get("stable")
            if arm == "canary" and "canary" not in self._arm_replies:
                arm = "stable"  # candidate vanished between pick and pack
            self._tm_infer[arm].inc()
            return reply if reply is not None else self._reply

    def _timed_serve(self, fn):
        """Wrap the (possibly fault-wrapped) serve handler with the
        serve-latency histogram + head-sampled trace exemplars. Installed
        OUTSIDE the fault injector so injected serve-path latency lands
        in the histogram the fleet rollups merge — the observability
        plane must see the faults it exists to surface. Tracing off:
        one perf_counter pair + an observe."""
        from ..telemetry import trace_enabled, trace_span

        def wrapped(request: bytes, ctx) -> bytes:
            t0 = time.perf_counter()
            if not trace_enabled():
                try:
                    return fn(request, ctx)
                finally:
                    self._tm_serve_hist.observe(time.perf_counter() - t0)
            sp = None
            try:
                with trace_span("rpc.replica_serve",
                                shard=self.shard_id) as sp:
                    return fn(request, ctx)
            finally:
                dur = time.perf_counter() - t0
                tid = getattr(getattr(sp, "ctx", None), "trace_id", None)
                if tid is not None and self._exemplars.sample():
                    self._tm_serve_hist.observe(dur, exemplar=tid)
                else:
                    self._tm_serve_hist.observe(dur)
        return wrapped

    def _redirect(self, request: bytes, ctx) -> bytes:
        self._tm_redirects.inc()
        return pack_msg({"accepted": False, "received": False,
                         "acknowledged": False, "replica": True,
                         "redirect": self.primary,
                         "shard_id": self.shard_id})

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Bind, start serving and polling. Returns the bound port."""
        ident = lambda b: b  # noqa: E731
        fetch_handler = self._fetch_parameters
        if self.faults is not None:
            # The serving direction decides under its own pseudo-op so a
            # schedule can fail serve traffic without touching the
            # subscription (and vice versa).
            from .faults import SUBSCRIBE_OP
            fetch_handler = self.faults.wrap_handler(SUBSCRIBE_OP,
                                                     fetch_handler)
        fetch_handler = self._timed_serve(fetch_handler)
        handlers = grpc.method_handlers_generic_handler(SERVICE_NAME, {
            name: grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=ident, response_serializer=ident)
            for name, fn in [("FetchParameters", fetch_handler),
                             ("RegisterWorker", self._redirect),
                             ("PushGradrients", self._redirect),
                             ("JobFinished", self._redirect)]
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=20),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((handlers,))
        bound = self._server.add_insecure_port(f"[::]:{self.port}")
        self.port = bound
        if self.advertise is None:
            self.advertise = f"localhost:{bound}"
        self._server.start()
        self._connect()
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="replica-poll", daemon=True)
        self._thread.start()
        return bound

    def stop(self, grace: float = 0.5) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._server is not None:
            self._server.stop(grace).wait()
        if self._channel is not None:
            self._channel.close()

    def view(self) -> dict:
        """Local status (cli replica logs it; tests poke it)."""
        now = self.clock()
        with self._lock:
            last = self._last_sync
            out = {"primary": self.primary, "parent": self.parent,
                   "tier": self.tier, "shard_id": self.shard_id,
                   "address": self.advertise, "step": self._step,
                   "synced": last is not None,
                   "sync_age_s": (None if last is None
                                  else round(max(0.0, now - last), 3)),
                   "staleness_bound_s": self.staleness_bound_s,
                   "children": len(self._children),
                   "coalesced": self._coalesced_count,
                   "polls": self._poll_rounds}
            if self.canary is not None:
                out["canary"] = self.canary.view()
            return out
