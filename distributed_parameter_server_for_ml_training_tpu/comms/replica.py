"""Delta-fed read replica: the wide cheap tier of the sharded topology.

A :class:`ReplicaServer` speaks the same four RPCs as a shard primary
(docs/WIRE_PROTOCOL.md) but holds no store at all — it subscribes to its
primary over the delta-fetch protocol (a background loop polls
``FetchParameters`` with ``have_step``; an idle primary answers with the
cached header-only NOT_MODIFIED reply, so an up-to-date replica costs the
primary a few bytes per poll) and serves fetch traffic from **cached
bytes**:

- the primary's tensor payload is kept VERBATIM — never decoded — and the
  full fetch reply is pre-encoded once per step, so serving a fetch is a
  dict lookup plus a socket write (this, times N replicas, is the ≥10×
  aggregate fetch-QPS lever the recorded experiment pins);
- ``have_step`` fetches at the replica's current step get the pre-encoded
  NOT_MODIFIED reply — the delta protocol composes through the tier.

Writes don't belong here: RegisterWorker / PushGradrients / JobFinished
answer a ``redirect`` to the primary (docs/SHARDING.md "Routing rules").

**Staleness contract**: every successful poll (including NOT_MODIFIED —
the primary confirming "your step is current" is freshness) stamps
``last_sync``; once that stamp ages past ``staleness_bound_s`` the
replica REFUSES fetches (UNAVAILABLE, redirect in the detail) instead of
serving arbitrarily old params. A replica can be behind by at most one
poll interval of real data, and a partitioned replica fails loud.

Each poll announces ``replica: {shard_id, address}`` in the fetch meta;
the primary's ShardInfo (ps/sharding.py) turns that plus ``have_step``
into the published replica membership and the ``dps_replica_lag_*``
gauges.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from .service import GRPC_OPTIONS, SERVICE_NAME, pack_msg, unpack_msg

__all__ = ["ReplicaServer"]


class ReplicaServer:
    """Read-only cache of one shard primary, behind the PS wire."""

    def __init__(self, primary: str, port: int = 0, shard_id: int = 0,
                 advertise: str | None = None,
                 poll_interval: float = 0.05,
                 staleness_bound_s: float = 5.0,
                 rpc_timeout: float = 10.0,
                 clock=time.time):
        self.primary = primary
        self.port = int(port)
        self.shard_id = int(shard_id)
        #: The address announced to the primary (what the shard map
        #: publishes to clients); filled from the bound port at start()
        #: when not given.
        self.advertise = advertise
        self.poll_interval = float(poll_interval)
        self.staleness_bound_s = float(staleness_bound_s)
        self.rpc_timeout = float(rpc_timeout)
        self.clock = clock
        self._lock = threading.Lock()
        self._step: int | None = None     # guarded by: self._lock
        self._reply: bytes = b""          # guarded by: self._lock
        self._nm_reply: bytes = b""       # guarded by: self._lock
        self._last_sync: float | None = None  # guarded by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._server: grpc.Server | None = None
        self._channel = None
        self._fetch_stub = None
        from ..telemetry import get_registry
        reg = get_registry()
        self._tm_fetches = reg.counter("dps_replica_fetches_total")
        self._tm_refreshes = reg.counter("dps_replica_refreshes_total")
        self._tm_stale = reg.counter("dps_replica_stale_rejects_total")
        self._tm_redirects = reg.counter("dps_replica_redirects_total")
        self._tm_step = reg.gauge("dps_replica_step")

    # -- subscription (replica -> primary) -----------------------------------

    # dpslint: hot-path — one refresh per primary step; re-pack only
    def _poll_once(self) -> None:
        """One refresh poll. The raw reply BYTES are the cache — the
        tensor payload is never decoded here, so a replica's refresh
        cost is the wire transfer plus one envelope re-pack, regardless
        of model size."""
        with self._lock:
            have = self._step
        meta: dict = {"replica": {"shard_id": self.shard_id,
                                  "address": self.advertise}}
        if have is not None:
            meta["have_step"] = int(have)
        raw = self._fetch_stub(pack_msg(meta), timeout=self.rpc_timeout)
        rmeta, payload = unpack_msg(raw)
        now = self.clock()
        if rmeta.get("not_modified"):
            with self._lock:
                self._last_sync = now
            return
        step = int(rmeta["global_step"])
        # Re-pack with the replica's own envelope over the primary's
        # payload bytes, once per step; every client fetch then serves
        # these exact bytes.
        head = {"global_step": step, "replica": True,
                "shard_id": self.shard_id}
        reply = pack_msg(head, bytes(payload))
        nm_reply = pack_msg({**head, "not_modified": True})
        with self._lock:
            self._step = step
            self._reply = reply
            self._nm_reply = nm_reply
            self._last_sync = now
        self._tm_refreshes.inc()
        self._tm_step.set(step)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 — a dead primary stalls the
                pass           # stamp; the staleness gate fails us loud.
            self._stop.wait(self.poll_interval)

    # -- serving (client -> replica) -----------------------------------------

    def _fresh_or_abort(self, ctx):
        now = self.clock()
        with self._lock:
            last = self._last_sync
        if last is None or now - last > self.staleness_bound_s:
            self._tm_stale.inc()
            ctx.abort(grpc.StatusCode.UNAVAILABLE,
                      f"replica stale (last sync "
                      f"{'never' if last is None else round(now - last, 2)}"
                      f"); use primary {self.primary}")

    # dpslint: hot-path — the ≥10x fetch-QPS lever: dict lookup + write
    def _fetch_parameters(self, request: bytes, ctx) -> bytes:
        self._fresh_or_abort(ctx)
        meta, _ = unpack_msg(request)
        have = meta.get("have_step")
        self._tm_fetches.inc()
        with self._lock:
            if have is not None and self._step is not None \
                    and int(have) == self._step:
                return self._nm_reply
            return self._reply

    def _redirect(self, request: bytes, ctx) -> bytes:
        self._tm_redirects.inc()
        return pack_msg({"accepted": False, "received": False,
                         "acknowledged": False, "replica": True,
                         "redirect": self.primary,
                         "shard_id": self.shard_id})

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Bind, start serving and polling. Returns the bound port."""
        ident = lambda b: b  # noqa: E731
        handlers = grpc.method_handlers_generic_handler(SERVICE_NAME, {
            name: grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=ident, response_serializer=ident)
            for name, fn in [("FetchParameters", self._fetch_parameters),
                             ("RegisterWorker", self._redirect),
                             ("PushGradrients", self._redirect),
                             ("JobFinished", self._redirect)]
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=20),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((handlers,))
        bound = self._server.add_insecure_port(f"[::]:{self.port}")
        self.port = bound
        if self.advertise is None:
            self.advertise = f"localhost:{bound}"
        self._server.start()
        self._channel = grpc.insecure_channel(self.primary,
                                              options=GRPC_OPTIONS)
        self._fetch_stub = self._channel.unary_unary(
            f"/{SERVICE_NAME}/FetchParameters",
            request_serializer=ident, response_deserializer=ident)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="replica-poll", daemon=True)
        self._thread.start()
        return bound

    def stop(self, grace: float = 0.5) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._server is not None:
            self._server.stop(grace).wait()
        if self._channel is not None:
            self._channel.close()

    def view(self) -> dict:
        """Local status (cli replica logs it; tests poke it)."""
        now = self.clock()
        with self._lock:
            last = self._last_sync
            return {"primary": self.primary, "shard_id": self.shard_id,
                    "address": self.advertise, "step": self._step,
                    "synced": last is not None,
                    "sync_age_s": (None if last is None
                                   else round(max(0.0, now - last), 3)),
                    "staleness_bound_s": self.staleness_bound_s}
