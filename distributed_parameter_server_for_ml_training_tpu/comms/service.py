"""gRPC parameter service: the reference wire protocol, re-hosted.

Serves a :class:`~..ps.store.ParameterStore` over gRPC for multi-host (DCN)
deployments. Protocol parity with src/communication/ps.proto:4-19 — the same
four unary-unary RPCs under the same service name, including the load-bearing
wire-protocol typo ``PushGradrients`` (ps.proto:12; SURVEY.md quirk 1):

    /ps.ParameterServer/RegisterWorker
    /ps.ParameterServer/PushGradrients
    /ps.ParameterServer/FetchParameters
    /ps.ParameterServer/JobFinished

Implemented with gRPC generic handlers (no protoc codegen): messages are a
JSON envelope + optional tensor payload (comms/wire.py) instead of the
reference's pickled bytes inside protobuf (worker.py:289) — same opacity on
the wire, none of pickle's code execution.

Channel/server tuning parity (server.py:372-381): 500 MB max message sizes,
keepalive 30 s / 5 s timeout, permit-without-calls, ThreadPoolExecutor(20).
"""

from __future__ import annotations

import json
import struct
import threading
from concurrent import futures

import grpc

from ..ps.store import ParameterStore
from .wire import decode_tensor_dict, encode_tensor_dict

SERVICE_NAME = "ps.ParameterServer"

# server.py:372-378 / worker.py:203-209
GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 500 * 1024 * 1024),
    ("grpc.max_receive_message_length", 500 * 1024 * 1024),
    ("grpc.keepalive_time_ms", 30_000),
    ("grpc.keepalive_timeout_ms", 5_000),
    ("grpc.keepalive_permit_without_calls", 1),
]


def pack_msg(meta: dict, payload: bytes = b"") -> bytes:
    header = json.dumps(meta).encode("utf-8")
    return struct.pack("<I", len(header)) + header + payload


def unpack_msg(data: bytes) -> tuple[dict, memoryview]:
    """Split the envelope WITHOUT copying the payload: the returned
    memoryview aliases ``data``, and the zero-copy tensor decode
    (comms/wire.py) builds array views directly over it — bytes-slicing
    here used to cost one full-payload copy per message."""
    (hlen,) = struct.unpack_from("<I", data, 0)
    mv = memoryview(data)
    meta = json.loads(bytes(mv[4:4 + hlen]).decode("utf-8"))
    return meta, mv[4 + hlen:]


class ParameterService:
    """Generic-handler implementation of the 4-RPC lifecycle."""

    def __init__(self, store: ParameterStore):
        self.store = store
        # Push dedupe: the client retries hot RPCs at-least-once
        # (client.py:_invoke); without this, a push whose reply was lost
        # AFTER it completed a sync round would be re-stashed into the
        # NEXT round as a stale duplicate (round-4 ADVICE). The client
        # stamps every push with a unique token (identical bytes across
        # retries); a token matching the worker's most recent push is a
        # retry of work already applied (or still applying: a
        # DEADLINE_EXCEEDED retry can overtake its original — the retry
        # then WAITS on the entry's event so the reply reports the
        # original's true outcome, not a guess). Most-recent-only
        # suffices: pushes are synchronous per worker, so a retry always
        # precedes that worker's next distinct push.
        # wid -> [token, outcome (None while in flight), done event]
        self._push_seen: dict[int, list] = {}
        self._push_seen_lock = threading.Lock()
        # Handler-side telemetry: per-RPC span + request/reply byte
        # counters (telemetry/). Client-side spans (comms/client.py)
        # include the wire + queueing; the delta between the two
        # distributions in one snapshot stream IS the network cost.
        from ..telemetry import get_registry
        reg = get_registry()
        self._tm_rpc = {
            name: (reg.histogram("dps_rpc_handler_seconds", rpc=name),
                   reg.counter("dps_rpc_handler_bytes_total", rpc=name,
                               direction="in"),
                   reg.counter("dps_rpc_handler_bytes_total", rpc=name,
                               direction="out"),
                   reg.counter("dps_rpc_handler_calls_total", rpc=name))
            for name in ["RegisterWorker", "PushGradrients",
                         "FetchParameters", "JobFinished"]
        }

    # -- RPC bodies (request bytes -> reply bytes) --------------------------

    def _membership_fields(self) -> dict:
        """Live membership for elastic remote workers (round-2 VERDICT item
        3): the wire now carries what in-process workers read directly from
        the store, so remote workers reshard at epoch boundaries too — fixing
        across the process boundary what the reference's restart pollution
        broke there (README.md:368-371)."""
        if not getattr(self.store.config, "elastic", False):
            return {}
        return {"active_workers": self.store.membership_snapshot()}

    def register_worker(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        worker_id, total = self.store.register_worker(
            meta.get("worker_name", ""))
        return pack_msg({
            "worker_id": worker_id,
            "total_workers": total,
            # Client needs the server's codecs/mode to compress correctly
            # (the store PROPERTY — the config field may hold the
            # backend-default sentinel None).
            "push_codec": self.store.push_codec,
            "fetch_codec": getattr(self.store, "fetch_codec", "none"),
            "mode": self.store.config.mode,
            "learning_rate": self.store.config.learning_rate,
            "elastic": bool(getattr(self.store.config, "elastic", False)),
            # Delta-fetch capability (docs/WIRE_PROTOCOL.md): clients may
            # send ``have_step`` on FetchParameters and must then handle a
            # NOT_MODIFIED reply. Advertised so old clients (which never
            # send have_step) and new clients against old servers (which
            # would ignore it) both keep working.
            "delta_fetch": bool(getattr(self.store, "supports_delta_fetch",
                                        False)),
            # Trace-context capability (docs/WIRE_PROTOCOL.md): clients may
            # attach a trace field to push frame headers / fetch meta and
            # this server will parent its handler/store spans on it. Same
            # gating discipline as delta_fetch — old clients never attach,
            # new clients against old servers see no advertisement and
            # stay silent, so mixed versions degrade to untraced.
            "trace_context": True,
            **self._membership_fields(),
        })

    def push_gradrients(self, request: bytes, ctx) -> bytes:
        meta, payload = unpack_msg(request)
        wid = int(meta["worker_id"])
        token = meta.get("push_token")
        if token is not None:
            with self._push_seen_lock:
                prev = self._push_seen.get(wid)
                if prev is not None and prev[0] == token:
                    dup = prev
                else:
                    dup = None
                    self._push_seen[wid] = [token, None, threading.Event()]
            if dup is not None:
                # Retry of a push already seen. If the original is still
                # in flight, wait for its outcome — answering early with
                # a fabricated accepted=True would misreport an async
                # push the staleness gate later rejects.
                finished = dup[2].wait(timeout=120.0)
                if not finished and dup[1] is None:
                    # Original STILL running after the wait: don't invent
                    # an outcome in either direction — fail retryably so
                    # the client's next attempt re-checks.
                    if ctx is not None:
                        ctx.abort(grpc.StatusCode.UNAVAILABLE,
                                  "push still in flight; retry")
                    raise TimeoutError("push still in flight")
                return pack_msg({
                    "received": True, "accepted": bool(dup[1]),
                    "duplicate": True,
                    "global_step": self.store.global_step})
        grads = decode_tensor_dict(payload)
        accepted = False
        try:
            accepted = self.store.push(wid, grads, int(meta["fetched_step"]))
        finally:
            # On an exception the event still fires (outcome False) so a
            # waiting retry is never stranded until its timeout.
            if token is not None:
                with self._push_seen_lock:
                    entry = self._push_seen.get(wid)
                    if entry is not None and entry[0] == token:
                        entry[1] = accepted
                        entry[2].set()
        return pack_msg({"received": True, "accepted": accepted,
                         "global_step": self.store.global_step})

    def fetch_parameters(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        wid = None if meta.get("worker_id") is None \
            else int(meta["worker_id"])
        have = meta.get("have_step")
        if have is not None \
                and getattr(self.store, "supports_delta_fetch", False):
            params, step = self.store.fetch(wid, have_step=int(have))
            if not params and step == int(have):
                # Version-gated delta fetch: the canonical step hasn't
                # advanced past what the client holds — the reply costs a
                # header instead of the full model (the straggler-wait /
                # polling fetch win; docs/WIRE_PROTOCOL.md).
                return pack_msg({"global_step": step, "not_modified": True,
                                 **self._membership_fields()})
        else:
            params, step = self.store.fetch(wid)
        return pack_msg({"global_step": step, **self._membership_fields()},
                        encode_tensor_dict(params))

    def job_finished(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        self.store.job_finished(int(meta["worker_id"]))
        return pack_msg({"acknowledged": True})

    # -- wiring --------------------------------------------------------------

    def _instrumented(self, name: str, fn):
        """Wrap an RPC body with its span + byte counters. The span covers
        the full handler (decode + store work + encode); durations record
        even when the body raises/aborts — error handling time is real.

        With tracing enabled, the wrapper also adopts the client's
        propagated trace context (fetch meta / push frame header,
        docs/WIRE_PROTOCOL.md) and opens an ``rpc.server`` span under it,
        so the store spans recorded inside the body attach causally to
        the worker step that issued the RPC. An untraced or legacy peer
        yields no context and the span becomes a local root."""
        from ..telemetry import now, trace_enabled, trace_span, \
            use_wire_context
        from .wire import peek_trace
        hist, b_in, b_out, calls = self._tm_rpc[name]

        def wrapped(request: bytes, ctx) -> bytes:
            t0 = now()
            b_in.inc(len(request))
            calls.inc()
            wire_ctx = None
            if trace_enabled():
                try:
                    meta, payload = unpack_msg(request)
                    wire_ctx = meta.get("trace") or \
                        (peek_trace(payload) if len(payload) else None)
                except Exception:
                    wire_ctx = None  # malformed request fails in fn, not here
            try:
                with use_wire_context(wire_ctx), \
                        trace_span("rpc.server", rpc=name):
                    reply = fn(request, ctx)
            finally:
                hist.observe(now() - t0)
            b_out.inc(len(reply))
            return reply

        return wrapped

    def handlers(self) -> grpc.GenericRpcHandler:
        ident = lambda b: b  # noqa: E731 — bytes pass through untouched
        method_map = {
            "RegisterWorker": self.register_worker,
            "PushGradrients": self.push_gradrients,  # quirk 1, on purpose
            "FetchParameters": self.fetch_parameters,
            "JobFinished": self.job_finished,
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, {
            name: grpc.unary_unary_rpc_method_handler(
                self._instrumented(name, fn),
                request_deserializer=ident, response_serializer=ident)
            for name, fn in method_map.items()
        })


def serve(store: ParameterStore, port: int = 8000,
          max_rpc_workers: int = 20) -> tuple[grpc.Server, int]:
    """Start the service (server.py:370-393). Returns (server, bound_port) —
    pass port=0 to pick a free port. Callers own shutdown. ThreadPool of 20
    reproduces the reference's cap — including its quirk 9 (20 < the
    32-worker max)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_rpc_workers),
        options=GRPC_OPTIONS)
    server.add_generic_rpc_handlers((ParameterService(store).handlers(),))
    bound = server.add_insecure_port(f"[::]:{port}")
    server.start()
    return server, bound
