"""gRPC parameter service: the reference wire protocol, re-hosted.

Serves a :class:`~..ps.store.ParameterStore` over gRPC for multi-host (DCN)
deployments. Protocol parity with src/communication/ps.proto:4-19 — the same
four unary-unary RPCs under the same service name, including the load-bearing
wire-protocol typo ``PushGradrients`` (ps.proto:12; SURVEY.md quirk 1):

    /ps.ParameterServer/RegisterWorker
    /ps.ParameterServer/PushGradrients
    /ps.ParameterServer/FetchParameters
    /ps.ParameterServer/JobFinished

Implemented with gRPC generic handlers (no protoc codegen): messages are a
JSON envelope + optional tensor payload (comms/wire.py) instead of the
reference's pickled bytes inside protobuf (worker.py:289) — same opacity on
the wire, none of pickle's code execution.

Channel/server tuning parity (server.py:372-381): 500 MB max message sizes,
keepalive 30 s / 5 s timeout, permit-without-calls, ThreadPoolExecutor(20).
"""

from __future__ import annotations

import json
import struct
import threading
import time
from collections import OrderedDict
from concurrent import futures

import grpc

from ..ps.sharding import key_slot
from ..ps.store import ParameterStore
from ..telemetry.journal import journal_event
from ..ps.tenancy import DEFAULT_JOB, WID_STRIDE, job_key, \
    normalize_job_id, parse_jobs_spec, split_job_key
from .wire import decode_tensor_dict, encode_tensor_dict, \
    frame_checksum_ok

SERVICE_NAME = "ps.ParameterServer"

#: Admin reshard sub-operations (docs/SHARDING.md "Migration protocol").
#: The 5th RPC is admin-plane: only shard PRIMARIES register it, so a
#: replica answers it UNIMPLEMENTED and can never be talked into a
#: handoff. ``status`` and ``abort`` are the crash-safety pair (ISSUE
#: 13): status exposes the primary's durable migration record so a
#: resumed coordinator can decide roll-forward vs roll-back; abort
#: unwinds a half-done handoff (donor unfreezes, recipient drops the
#: adopted range) with the live map untouched.
RESHARD_OPS = ("export", "import", "commit", "apply_ranges", "status",
               "abort")

#: Default TTL on the donor's export freeze (docs/ROBUSTNESS.md
#: "Migration failure matrix"): a coordinator that dies between export
#: and map publish would otherwise leave ``[lo, hi)`` frozen forever.
#: Once the lease expires the donor auto-unfreezes and clears its
#: migration record — the map never moved, so nothing else needs
#: unwinding. After the new map publishes the lease no longer applies:
#: that migration is roll-forward-only.
DEFAULT_MIGRATION_LEASE_S = 30.0

#: Completed push-token outcomes kept for dedupe (and persisted in store
#: snapshots, checkpoint/manager.py). One entry per client nonce; 4x the
#: 32-worker cap leaves room for reconnecting clients' fresh nonces without
#: evicting live ones.
PUSH_SEEN_CAP = 128

#: Ceiling on how long a duplicate push waits for its original's outcome
#: when the caller carries no deadline. With a deadline, the wait is
#: bounded by ``ctx.time_remaining()`` minus a reply margin instead —
#: a flat 120 s outlived the client's 60 s rpc_timeout and pinned server
#: threads (round-5 ADVICE).
DUP_WAIT_CAP_S = 30.0

#: Ceiling on how long an RPC queues for weighted-fair admission
#: (docs/TENANCY.md "QoS semantics") before it is throttled with
#: RESOURCE_EXHAUSTED — which is in the client's RETRYABLE_CODES, so a
#: throttled worker backs off and retries instead of dying. Short on
#: purpose: backpressure should surface as bounded handler queueing plus
#: client-side backoff, never as pinned pool threads (the DUP_WAIT
#: lesson above).
ADMISSION_WAIT_CAP_S = 2.0

#: Handler slots the admission scheduler hands out concurrently — kept
#: below the 20-thread gRPC pool (server.py:381 parity) so a saturated
#: job throttles at admission while threads remain to ANSWER the
#: throttles and serve other jobs.
ADMISSION_CAPACITY = 16

#: Server->worker control directives (docs/ROBUSTNESS.md "Self-healing"):
#: the remediation layer posts these and the fetch/push reply envelope
#: meta carries them to capable workers, which act at step boundaries.
#: The names are a wire/doc contract exactly like metric/span/rule names;
#: ``tests/test_docs_drift.py`` pins this table to the doc both
#: directions.
DIRECTIVE_CATALOG = {
    "refetch_params": "drop the delta-fetch basis and take a full fresh "
                      "fetch at the next step boundary",
    "quarantine": "skip gradient pushes for `steps` boundary windows and "
                  "reset error-feedback residuals (suspected-poisoned "
                  "local state)",
    "rebalance_shard": "finish the current epoch early and recompute the "
                       "data shard from live membership at the next epoch",
    "drain": "finish cleanly at the next step boundary (flush the pending "
             "window, then JobFinished)",
}

#: Outstanding directives kept per worker; older ones are dropped first
#: (a worker that never fetches must not grow server memory).
DIRECTIVES_PER_WORKER_CAP = 16


def parse_push_token(token) -> tuple[str, int]:
    """Split a ``nonce:count`` push token. The count orders a client's
    pushes, so the dedupe table can refuse ZOMBIE tokens — a
    deadline-expired first attempt executing after its retry succeeded and
    newer pushes landed (round-5 ADVICE: any ``count <=`` last-seen is a
    duplicate, and a lower count never evicts a higher one). A token
    without a parsable counter degrades to exact-match semantics: the
    whole token becomes the nonce, count -1."""
    s = str(token)
    nonce, sep, cnt = s.rpartition(":")
    if sep and cnt.isdigit():
        return nonce, int(cnt)
    return s, -1

# server.py:372-378 / worker.py:203-209
GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 500 * 1024 * 1024),
    ("grpc.max_receive_message_length", 500 * 1024 * 1024),
    ("grpc.keepalive_time_ms", 30_000),
    ("grpc.keepalive_timeout_ms", 5_000),
    ("grpc.keepalive_permit_without_calls", 1),
    # Client-channel reconnect pacing (ignored by servers). gRPC's default
    # reconnect backoff grows to ~2 minutes; a worker waiting out a server
    # RESTART (docs/ROBUSTNESS.md) would then sit in channel backoff long
    # after the replacement is up, and the reconnect window would expire
    # on a healthy server. Capping at 2 s keeps session resume prompt
    # while still backing off a truly dead peer.
    ("grpc.initial_reconnect_backoff_ms", 250),
    ("grpc.max_reconnect_backoff_ms", 2_000),
]


class RawJSON(str):
    """A pre-encoded JSON fragment. :func:`pack_msg` splices a RawJSON
    value into the envelope verbatim instead of re-serializing it — the
    hot-path cache for meta that changes rarely but rides every RPC (the
    worker's piggybacked health report is re-encoded per heartbeat ping
    today; comms/client.py caches it per report revision). The value MUST
    be a complete, valid JSON document; nothing re-validates it here."""

    __slots__ = ()


def pack_msg(meta: dict, payload: bytes = b"") -> bytes:
    raw = {k: v for k, v in meta.items() if isinstance(v, RawJSON)}
    if raw:
        base = json.dumps({k: v for k, v in meta.items()
                           if not isinstance(v, RawJSON)})
        frag = ",".join(f'"{k}":{v}' for k, v in raw.items())
        header = (base[:-1] + ("," if len(base) > 2 else "")
                  + frag + "}").encode("utf-8")
    else:
        header = json.dumps(meta).encode("utf-8")
    return struct.pack("<I", len(header)) + header + payload


def unpack_msg(data: bytes) -> tuple[dict, memoryview]:
    """Split the envelope WITHOUT copying the payload: the returned
    memoryview aliases ``data``, and the zero-copy tensor decode
    (comms/wire.py) builds array views directly over it — bytes-slicing
    here used to cost one full-payload copy per message."""
    (hlen,) = struct.unpack_from("<I", data, 0)
    mv = memoryview(data)
    meta = json.loads(bytes(mv[4:4 + hlen]).decode("utf-8"))
    return meta, mv[4 + hlen:]


class WeightedFairAdmission:
    """Weighted-fair admission over the push/fetch handler path
    (docs/TENANCY.md "QoS semantics"): one job's storm cannot starve
    another's trickle.

    Each job holds at most ``max_inflight`` admitted RPCs (its spec's
    hard cap), and once the shared ``capacity`` is contended, at most
    its *fair share* — ``capacity * weight / total_weight``, floored at
    1 so every live job always makes progress. Under the cap an RPC
    waits (bounded by the caller's deadline and
    :data:`ADMISSION_WAIT_CAP_S`) for a slot; on timeout it is
    throttled and the handler aborts RESOURCE_EXHAUSTED, which the
    client retries with backoff. Per-job instruments:
    ``dps_job_queue_depth{job}`` (admitted + waiting),
    ``dps_job_admitted_total{job}``, ``dps_job_throttled_total{job}`` —
    series are dropped on job drain (JobManager.drain), the PR 11
    replica-lag lifecycle pattern.
    """

    def __init__(self, jobs, capacity: int = ADMISSION_CAPACITY,
                 registry=None):
        self.jobs = jobs  # JobManager: live weight/max_inflight source
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight: dict[str, int] = {}  # guarded by: self._lock
        self._waiting: dict[str, int] = {}  # guarded by: self._lock
        from ..telemetry import get_registry
        self._reg = registry or get_registry()
        # job -> (depth gauge, admitted ctr, throttled ctr); created on a
        # job's first admission, removed at drain (registry.remove).
        self._instr: dict[str, tuple] = {}  # guarded by: self._lock

    def _instruments_locked(self, job: str) -> tuple:
        tup = self._instr.get(job)
        if tup is None:
            tup = (self._reg.gauge("dps_job_queue_depth", job=job),
                   self._reg.counter("dps_job_admitted_total", job=job),
                   self._reg.counter("dps_job_throttled_total", job=job))
            self._instr[job] = tup
        return tup

    def _limits(self, job: str) -> tuple[int, int]:
        """(fair share, hard max-inflight) from the live job table."""
        table = self.jobs.qos_table()
        weight, max_inflight = table.get(job, (1.0, 8))
        total_w = sum(w for w, _ in table.values()) or 1.0
        fair = max(1, int(self.capacity * weight / total_w))
        return fair, int(max_inflight)

    def _depth_locked(self, job: str, gauge) -> None:
        gauge.set(self._inflight.get(job, 0) + self._waiting.get(job, 0))

    def admit(self, job: str, budget_s: float) -> bool:
        """Take an admission slot for ``job``, waiting up to
        ``budget_s``; False means throttled (counted)."""
        deadline = time.monotonic() + max(0.0, float(budget_s))
        with self._lock:
            depth_g, admitted_c, throttled_c = self._instruments_locked(job)
            self._waiting[job] = self._waiting.get(job, 0) + 1
            self._depth_locked(job, depth_g)
            try:
                while True:
                    fair, cap = self._limits(job)
                    mine = self._inflight.get(job, 0)
                    total = sum(self._inflight.values())
                    if mine < cap and (total < self.capacity
                                       or mine < fair):
                        self._inflight[job] = mine + 1
                        admitted_c.inc()
                        return True
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        throttled_c.inc()
                        return False
                    self._cond.wait(remaining)
            finally:
                self._waiting[job] -= 1
                self._depth_locked(job, depth_g)

    def release(self, job: str) -> None:
        with self._lock:
            n = self._inflight.get(job, 0)
            if n <= 1:
                self._inflight.pop(job, None)
            else:
                self._inflight[job] = n - 1
            tup = self._instr.get(job)
            if tup is not None:
                self._depth_locked(job, tup[0])
            self._cond.notify_all()

    def forget_job(self, job: str) -> None:
        """Drop a drained job's scheduler state. The metric series
        themselves are removed by JobManager.drain."""
        with self._lock:
            self._inflight.pop(job, None)
            self._waiting.pop(job, None)
            self._instr.pop(job, None)
            self._cond.notify_all()

    def view(self) -> dict:
        """Per-job admission state for /cluster and cli status."""
        with self._lock:
            names = (set(self._inflight) | set(self._waiting)
                     | set(self._instr))
            out = {}
            for j in sorted(names):
                fair, cap = self._limits(j)
                out[j] = {"inflight": self._inflight.get(j, 0),
                          "waiting": self._waiting.get(j, 0),
                          "fair_share": fair, "max_inflight": cap}
            return out


class ParameterService:
    """Generic-handler implementation of the 4-RPC lifecycle."""

    def __init__(self, store: ParameterStore, faults=None, monitor=None,
                 reject_nonfinite: bool = False, sharding=None,
                 jobs=None):
        self.store = store
        # Tenancy (docs/TENANCY.md): when a ps/tenancy.JobManager is
        # attached, every envelope routes by its ``job`` meta key to that
        # job's own store, worker ids stride per job, and push/fetch pass
        # through the weighted-fair admission scheduler below. None (the
        # default) is the single-job server, byte-identical to every
        # prior PR — same legacy-degradation discipline as sharding.
        self.jobs = jobs
        # Sharding state (ps/sharding.py ShardInfo): when set, this server
        # is ONE shard primary of a consistent-hash partition — the
        # registration reply publishes the shard map (that presence IS the
        # capability advertisement), fetch replies refresh it delta-gated
        # on the client's ``have_shard_map`` version, and replica
        # announces riding fetch meta feed the live replica membership.
        # None = single-server wire, byte-identical to every prior PR —
        # same legacy-degradation discipline as delta_fetch/directives.
        self.sharding = sharding
        # Self-healing guard (docs/ROBUSTNESS.md): a push whose OWN
        # piggybacked health report flags a non-finite loss/grad is
        # refused synchronously. The evidence and the poison ride the
        # same envelope, so this is the only reaction that can beat the
        # apply — the monitor's quarantine (async, next evaluation) would
        # always arrive one poisoned aggregate too late. Off by default
        # (reference parity: the reference applied NaN); cli serve turns
        # it on with the remediation engine.
        self.reject_nonfinite = reject_nonfinite
        # Cluster health monitor (telemetry/cluster.py): when attached,
        # registration advertises the health_report capability and the
        # fetch/push handlers feed piggybacked worker health reports into
        # it. None = the capability is never advertised and clients stay
        # silent (docs/OBSERVABILITY.md) — same gating discipline as
        # delta_fetch / trace_context.
        self.monitor = monitor
        # Push dedupe: the client retries hot RPCs at-least-once
        # (client.py:_invoke); without this, a push whose reply was lost
        # AFTER it completed a sync round would be re-stashed into the
        # NEXT round as a stale duplicate (round-4 ADVICE). The client
        # stamps every push with a ``nonce:count`` token (identical bytes
        # across retries); the table is keyed by NONCE and ordered by
        # COUNT, so (a) a zombie attempt whose count is below the last
        # seen is refused instead of re-applied (round-5 ADVICE — the old
        # most-recent-token-per-worker scheme let it evict the newer
        # record AND re-apply the old gradient), and (b) a client that
        # reconnects under a fresh worker id after a server restart keeps
        # deduping, because its nonce — not its id — is the key. A retry
        # of a still-in-flight original WAITS on the entry's event so the
        # reply reports the original's true outcome, not a guess.
        # nonce -> [count, outcome (None while in flight), done event,
        #           worker_id, step_at_completion]; LRU-bounded.
        self._push_seen: OrderedDict[str, list] = OrderedDict()  # guarded by: self._push_seen_lock
        self._push_seen_lock = threading.Lock()
        # Directive channel (docs/ROBUSTNESS.md "Self-healing"): per-worker
        # outstanding server->worker directives, attached to every fetch/
        # push reply until the worker acks them (at-least-once delivery;
        # the client dedupes by seq). Only workers that advertised the
        # capability at registration ever get them — legacy peers' replies
        # carry nothing, same degradation discipline as health reports.
        self._directive_lock = threading.Lock()
        self._directives: dict[int, list[dict]] = {}  # guarded by: self._directive_lock
        self._directive_seq = 0  # guarded by: self._directive_lock
        self._directive_capable: set[int] = set()  # guarded by: self._directive_lock
        # Server-side push quarantine (remediation action): worker id ->
        # wall-clock ts until which its pushes are refused (acknowledged,
        # never applied). Belt-and-braces beside the quarantine directive:
        # a legacy worker that can't hear the directive still can't poison
        # the aggregate.
        self._quarantined: dict[int, float] = {}  # guarded by: self._directive_lock
        # Activity-coupled membership expiry (satellite: a stalled elastic
        # round unsticks on the next push/registration instead of waiting
        # for the serve loop's next timer tick). The throttle stamp needs
        # its own lock: handler threads race the read-modify-write, and
        # two passing the age check at once ran DUPLICATE expiry sweeps.
        self._expire_lock = threading.Lock()
        self._last_expire_check = 0.0  # guarded by: self._expire_lock
        # Deterministic fault injection (comms/faults.py): wraps the RPC
        # handler bodies in handlers(); None = no faults.
        from .faults import FaultInjector
        if isinstance(faults, str):
            faults = FaultInjector(faults, side="server")
        self.faults = faults
        # Handler-side telemetry: per-RPC span + request/reply byte
        # counters (telemetry/). Client-side spans (comms/client.py)
        # include the wire + queueing; the delta between the two
        # distributions in one snapshot stream IS the network cost.
        from ..telemetry import LATENCY_BUCKETS, get_registry
        reg = get_registry()
        # dps_rpc_server_latency_seconds / dps_rpc_server_errors_total are
        # the SLO-facing pair (telemetry/slo.py): the finer LATENCY_BUCKETS
        # scheme puts an edge at every plausible p99 objective, and the
        # error counter makes availability = errors/calls computable from
        # snapshot deltas alone. dps_rpc_handler_seconds stays (coarser
        # legacy edges pinned by committed snapshot history).
        self._tm_rpc = {
            name: (reg.histogram("dps_rpc_handler_seconds", rpc=name),
                   reg.counter("dps_rpc_handler_bytes_total", rpc=name,
                               direction="in"),
                   reg.counter("dps_rpc_handler_bytes_total", rpc=name,
                               direction="out"),
                   reg.counter("dps_rpc_handler_calls_total", rpc=name),
                   reg.histogram("dps_rpc_server_latency_seconds",
                                 buckets=LATENCY_BUCKETS, method=name),
                   reg.counter("dps_rpc_server_errors_total", method=name))
            for name in ["RegisterWorker", "PushGradrients",
                         "FetchParameters", "JobFinished", "Reshard",
                         "SubmitJob"]
        }
        # Trace exemplars (docs/OBSERVABILITY.md "Fleet observatory"):
        # head-sampled trace ids attached to the SLO latency histogram so
        # a fleet p99 spike resolves to flight-recorder traces. One
        # counter-based sampler across all methods — no RNG on the hot
        # path, pid-seeded phase so co-started shards don't sample the
        # same beat.
        from ..telemetry import ExemplarSampler
        import os
        self._tm_exemplars = ExemplarSampler(rate=0.1, seed=os.getpid())
        # Per-job QoS (docs/TENANCY.md): constructed with the job table
        # so drain can tear down scheduler state alongside the job.
        self.qos = None
        if jobs is not None:
            self.qos = WeightedFairAdmission(jobs, registry=reg)
            jobs.qos = self.qos
        # Live-reshard state (docs/SHARDING.md "Migration protocol"):
        # slots this primary froze at export and is handing away. A push
        # touching a draining slot is disowned — dropped from the apply
        # and named in the reply so the client re-routes it — which is
        # what makes the exported snapshot authoritative: nothing can
        # land on the donor's copy after export.
        self._reshard_lock = threading.Lock()
        self._draining: set[int] = set()  # guarded by: self._reshard_lock
        # Durable migration ledger (docs/ROBUSTNESS.md "Migration failure
        # matrix"): this primary's record of the in-flight handoff it is
        # donor or recipient of — persisted into store snapshots
        # (checkpoint/manager.py migration_fn) and restored with them, so
        # a primary that crashes mid-migration comes back knowing exactly
        # which phase it had reached. None = no migration in flight.
        self._migration: dict | None = None  # guarded by: self._reshard_lock
        self._tm_reshard = {
            op: reg.counter("dps_reshard_events_total", op=op)
            for op in RESHARD_OPS}
        self._tm_lease_expired = reg.counter(
            "dps_reshard_lease_expired_total")
        self._tm_disowned = reg.counter("dps_push_disowned_keys_total")
        # Pushes refused because their frame failed the CRC trailer check
        # (docs/WIRE_PROTOCOL.md "Checksum trailer"); feeds the
        # wire_corrupt health rule via the monitor.
        self._tm_wire_corrupt = reg.counter("dps_wire_corrupt_total")
        # Surface the in-flight migration in the shard map's /cluster
        # view (degradation-pinned: servers without the provider simply
        # publish no "migration" block).
        if sharding is not None:
            sharding.migration_provider = self.migration_view
        # Pushes refused while their worker was quarantined (remediation
        # action; docs/ROBUSTNESS.md).
        self._tm_quarantined = reg.counter(
            "dps_service_quarantined_pushes_total")
        # Encoded header-only NOT_MODIFIED reply cache (single entry: the
        # current step). At replica-refresh/heartbeat QPS the NM reply is
        # the whole serve path, and re-running json.dumps + struct.pack
        # per RPC dominated it; one idle step serves identical bytes to
        # every poller. Keyed on everything that shapes the reply —
        # entered only when the qscale/directive/shard-map attachments are
        # empty — and invalidated by key mismatch when the step or the
        # membership view moves.
        self._nm_cache: tuple | None = None  # guarded by: self._nm_lock
        self._nm_lock = threading.Lock()
        #: Single-flight guard over the NM-reply build: the key being
        #: encoded right now, or None. Identical delta polls racing a
        #: step transition park on the condition and serve the one
        #: freshly built reply instead of each paying the pack
        #: (docs/SHARDING.md "Fan-out trees" — coalescing semantics).
        self._nm_building = None  # guarded by: self._nm_lock
        self._nm_cond = threading.Condition(self._nm_lock)
        self._tm_nm_cache_hits = reg.counter(
            "dps_fetch_nm_cache_hits_total")

    # -- directive channel (docs/ROBUSTNESS.md "Self-healing") ---------------

    def post_directive(self, worker_id: int, action: str,
                       **params) -> int | None:
        """Queue a server->worker directive; returns its seq, or None when
        the worker never advertised the capability (legacy peer — the
        caller records the remediation as skipped, training untouched).
        Delivery is at-least-once: the directive rides every fetch/push
        reply to that worker until acked; the client dedupes by seq."""
        if action not in DIRECTIVE_CATALOG:
            raise ValueError(f"unknown directive {action!r} (catalog: "
                             f"{sorted(DIRECTIVE_CATALOG)})")
        wid = int(worker_id)
        with self._directive_lock:
            if wid not in self._directive_capable:
                return None
            self._directive_seq += 1
            seq = self._directive_seq
            box = self._directives.setdefault(wid, [])
            box.append({"seq": seq, "action": action, **params})
            del box[:-DIRECTIVES_PER_WORKER_CAP]
        journal_event("directive", worker=wid, action=action, seq=seq)
        return seq

    def directives_for(self, worker_id) -> list[dict]:
        with self._directive_lock:
            return [dict(d) for d in self._directives.get(worker_id, [])]

    def _note_ack(self, worker_id, meta: dict) -> None:
        ack = meta.get("directives_ack")
        if ack is None:
            return
        try:
            ack = int(ack)
        except (TypeError, ValueError):
            return
        with self._directive_lock:
            box = self._directives.get(worker_id)
            if box:
                box[:] = [d for d in box if d["seq"] > ack]

    def _directive_fields(self, worker_id, meta: dict) -> dict:
        """Reply-meta fields for the directive channel: process the
        request's ack, then attach whatever is still outstanding."""
        if worker_id is None:
            return {}
        self._note_ack(worker_id, meta)
        out = self.directives_for(worker_id)
        return {"directives": out} if out else {}

    # -- server-side push quarantine (remediation action) --------------------

    def quarantine(self, worker_id: int, seconds: float) -> None:
        """Refuse this worker's pushes (acknowledged, never applied) for
        ``seconds`` — the server-side half of the quarantine remediation;
        works even against legacy workers that can't hear the directive."""
        with self._directive_lock:
            self._quarantined[int(worker_id)] = time.time() + float(seconds)

    def unquarantine(self, worker_id: int) -> None:
        with self._directive_lock:
            self._quarantined.pop(int(worker_id), None)

    def is_quarantined(self, worker_id) -> bool:
        with self._directive_lock:
            until = self._quarantined.get(worker_id)
            if until is None:
                return False
            if time.time() >= until:
                del self._quarantined[worker_id]
                return False
            return True

    def quarantine_view(self) -> dict[int, float]:
        """worker id -> seconds remaining (for /cluster)."""
        now = time.time()
        with self._directive_lock:
            return {w: round(until - now, 3)
                    for w, until in self._quarantined.items()
                    if until > now}

    # -- activity-coupled membership expiry ----------------------------------

    def _expire_tick(self) -> None:
        """Run membership expiry on push/registration activity, throttled,
        so an elastic round stalled on a dead worker unsticks as soon as a
        LIVE worker shows up — not a full serve-loop/timer interval later.
        The reaped ids feed the monitor exactly like the serve loop's."""
        timeout = getattr(self.store.config, "worker_timeout", None)
        if not timeout:
            return
        now = time.time()
        with self._expire_lock:
            if now - self._last_expire_check < min(1.0, timeout / 4.0):
                return
            self._last_expire_check = now
        try:
            # Tenancy sweeps every job's store and reports GLOBAL ids;
            # the single-job path is the primary store, ids untouched.
            expired = self.store.expire_stale_workers() \
                if self.jobs is None else self.jobs.expire_stale_workers()
        except Exception:  # noqa: BLE001 — expiry must not fail the RPC
            return
        if expired:
            print(f"expired silent workers: {expired}", flush=True)
            if self.monitor is not None:
                try:
                    self.monitor.note_expired(expired)
                except Exception:  # noqa: BLE001
                    pass

    # -- RPC bodies (request bytes -> reply bytes) --------------------------

    def _job_of(self, meta: dict) -> str:
        """Resolve the envelope's job id (docs/TENANCY.md). Tenancy off
        means everything is the default job and the ``job`` key is never
        read — the key is capability-gated on this server advertising
        ``jobs`` at registration. Garbled ids degrade to the default
        namespace, never fail the RPC (the health-report discipline)."""
        if self.jobs is None:
            return DEFAULT_JOB
        return normalize_job_id(meta.get("job"))

    def _route(self, meta: dict):
        """``(job, store, local_worker_id)`` for an envelope: the job
        from the ``job`` meta key (falling back to the global id's
        stride for a capable peer whose ping omitted the label), the
        store from the job table, and the LOCAL worker id from stripping
        the per-job stride off the global id the wire carries
        (ps/tenancy.WID_STRIDE). Tenancy off routes everything to the
        primary store with ids untouched."""
        wid = meta.get("worker_id")
        wid = None if wid is None else int(wid)
        if self.jobs is None:
            return DEFAULT_JOB, self.store, wid
        job = normalize_job_id(meta.get("job"))
        if job == DEFAULT_JOB and wid is not None:
            job = self.jobs.job_name_of(wid)
        lwid = None if wid is None else wid % WID_STRIDE
        return job, self.jobs.store_for(job), lwid

    def _membership_fields(self, store=None) -> dict:
        """Live membership for elastic remote workers (round-2 VERDICT item
        3): the wire now carries what in-process workers read directly from
        the store, so remote workers reshard at epoch boundaries too — fixing
        across the process boundary what the reference's restart pollution
        broke there (README.md:368-371). ``store`` routes the view to a
        job's own store under tenancy; membership is per-job (local ids:
        the worker reshards its data among its OWN job's peers)."""
        store = self.store if store is None else store
        if not getattr(store.config, "elastic", False):
            return {}
        return {"active_workers": store.membership_snapshot()}

    def _qscale_fields(self, have_step: int | None = None,
                       store=None) -> dict:
        """Shared-scale table fields for a reply (docs/WIRE_PROTOCOL.md):
        the store's per-layer gradient absmax table + version, attached
        when the store publishes one AND the client's known version
        (``have_qscales``) is older. Stores without the capability (native
        arena, device) contribute nothing. ``store`` routes to a job's
        own table under tenancy (scales are per-job state)."""
        store = self.store if store is None else store
        fn = getattr(store, "gradient_scales", None)
        if not callable(fn):
            return {}
        try:
            have = None if have_step is None else int(have_step)
        except (TypeError, ValueError):
            have = None  # garbled version: resend the table, never fail
        scales, step = fn()
        if not scales or (have is not None and have >= step):
            return {}
        return {"qscales": scales, "qscale_step": step}

    def _shard_fields(self, have_version=None) -> dict:
        """Shard-map fields for a reply (docs/SHARDING.md): the full map
        at registration (``have_version`` None — its presence there IS the
        capability advertisement), then refreshed via fetch replies only
        when the client's known version (``have_shard_map``) is older —
        the same delta idiom as the qscale table. Unsharded servers
        contribute nothing and the wire stays single-server."""
        if self.sharding is None:
            return {}
        try:
            have = None if have_version is None else int(have_version)
        except (TypeError, ValueError):
            have = None  # garbled version: resend the map, never fail
        m = self.sharding.shard_map()
        if have is not None and have >= m["version"]:
            return {}
        return {"shard_map": m}

    def _note_replica(self, meta: dict) -> None:
        """Ingest a replica announce riding fetch meta: ``replica:
        {shard_id, address}`` plus the fetch's own ``have_step`` gives the
        primary this replica's applied step — the lag source behind the
        ``dps_replica_lag_*`` gauges and the published replica list.
        Observability + routing metadata only; never fails the fetch."""
        rep = meta.get("replica")
        if self.sharding is None or not isinstance(rep, dict):
            return
        try:
            self.sharding.note_replica(rep.get("address"),
                                       meta.get("have_step", 0),
                                       self.store.global_step,
                                       metrics=rep.get("metrics"),
                                       parent=rep.get("parent"),
                                       tier=rep.get("tier"),
                                       fetches=rep.get("fetches"))
            # An interior node forwards its cached subtree as
            # ``descendants`` rows — each one a full announce, so the
            # shard view covers every tier of the fan-out tree, not
            # just the primary's direct children. Bounded: a garbled
            # or hostile subtree cannot balloon the ingest.
            for d in (rep.get("descendants") or [])[:64]:
                if isinstance(d, dict):
                    self.sharding.note_replica(
                        d.get("address"), d.get("step", 0),
                        self.store.global_step,
                        metrics=d.get("metrics"),
                        parent=d.get("parent"), tier=d.get("tier"),
                        fetches=d.get("fetches"))
        except Exception:  # noqa: BLE001
            pass

    def _topology_fields(self, have_version=None) -> dict:
        """Fan-out-tree topology fields for a reply (docs/SHARDING.md
        "Fan-out trees"): attached only for replica polls that sent
        ``have_topology`` with a version older than the live one — the
        same delta idiom as the shard map, so steady-state NM replies
        stay attachment-free and cacheable."""
        if self.sharding is None \
                or not callable(getattr(self.sharding, "topology", None)):
            return {}
        try:
            have = None if have_version is None else int(have_version)
        except (TypeError, ValueError):
            have = None  # garbled version: resend the view, never fail
        topo = self.sharding.topology()
        if have is not None and have >= topo["version"]:
            return {}
        return {"topology": topo}

    def _disowned_keys(self, names) -> list[str]:
        """Pushed keys whose slot this primary does not currently own
        (map moved under the client) or is draining away (mid-handoff).
        Routed on the BASE tensor name so codec companions
        (``name::int8scale`` etc.) travel with their tensor."""
        if self.sharding is None:
            return []
        lo, hi = self.sharding.my_range()
        with self._reshard_lock:
            if self._draining:
                # Lazy lease check on the hot path's cold branch: a
                # frozen range must not keep disowning pushes after its
                # donor lease lapsed.
                self._lease_expired_locked()
            draining = set(self._draining)
        out = []
        for k in names:
            slot = key_slot(str(k).split("::", 1)[0])
            if not lo <= slot < hi or slot in draining:
                out.append(k)
        return out

    def _keys_in_slots(self, lo: int, hi: int) -> list[str]:
        """This store's parameter names living in ``[lo, hi)`` — the
        donor's export subset, derived from slots at call time so the
        admin never has to know key names."""
        return [k for k in self.store.param_names()
                if lo <= key_slot(k) < hi]

    # -- durable migration ledger + lease (docs/ROBUSTNESS.md) ---------------

    @staticmethod
    def _migration_plan(plan) -> dict | None:
        """Normalized coordinator plan from the request's ``migration``
        field; None for legacy coordinators (ledger-less reshard, the
        pre-lease behavior) or a garbled plan."""
        if not isinstance(plan, dict):
            return None
        try:
            return {
                "id": str(plan["id"]),
                "slot_lo": int(plan["slot_lo"]),
                "slot_hi": int(plan["slot_hi"]),
                "ranges": [[int(a), int(b)]
                           for a, b in (plan.get("ranges") or [])],
                "map_version": int(plan.get("map_version") or 0),
                "lease_ttl": float(plan.get("lease_ttl")
                                   or DEFAULT_MIGRATION_LEASE_S),
            }
        except (KeyError, TypeError, ValueError):
            return None

    def _lease_expired_locked(self) -> bool:
        """Lazy lease enforcement (requires ``_reshard_lock``): a donor
        whose pre-publish freeze outlived its TTL auto-unfreezes and
        clears its record — the map never moved, so the abort is local
        and complete. Returns True when it fired. Checked wherever the
        frozen range could wedge traffic: reshard ops, the push
        ownership filter, the status/cluster views, and snapshot
        restore. After ``apply_ranges`` publishes the new map the phase
        is no longer ``export`` and the lease stops applying — from
        there the migration is roll-forward-only."""
        rec = self._migration
        if rec is None or rec.get("role") != "donor" \
                or rec.get("phase") != "export":
            return False
        if time.time() <= float(rec.get("lease_deadline", 0.0)):
            return False
        self._draining.clear()
        self._migration = None
        self._tm_lease_expired.inc()
        print(f"RESHARD_LEASE_EXPIRED migration={rec.get('id')} "
              f"slots=[{rec.get('slot_lo')},{rec.get('slot_hi')}) "
              f"frozen range auto-unfrozen, map untouched", flush=True)
        return True

    def migration_view(self) -> dict | None:
        """Compact in-flight-migration block for ``GET /cluster`` /
        ``cli status`` (riding the sharding view via the provider hook);
        None when no migration is in flight."""
        with self._reshard_lock:
            self._lease_expired_locked()
            rec = self._migration
            if rec is None:
                return None
            out = {"id": rec["id"], "role": rec["role"],
                   "phase": rec["phase"],
                   "slot_lo": rec["slot_lo"], "slot_hi": rec["slot_hi"],
                   "map_version": rec["map_version"],
                   # The full target partition: a resumed coordinator
                   # (cli.py _reshard_resume) rebuilds its plan from
                   # this block, and apply_ranges needs every shard's
                   # post-move range, not just the migrated window.
                   "ranges": [list(r)
                              for r in (rec.get("ranges") or [])],
                   "frozen_slots": len(self._draining)}
            if rec["role"] == "donor" and rec["phase"] == "export":
                out["lease_remaining_s"] = round(
                    float(rec.get("lease_deadline", 0.0)) - time.time(), 3)
            return out

    def migration_snapshot(self) -> dict | None:
        """The full migration record for checkpoint persistence
        (checkpoint/manager.py ``migration_fn``), or None."""
        with self._reshard_lock:
            self._lease_expired_locked()
            return None if self._migration is None \
                else dict(self._migration)

    def load_migration(self, rec) -> bool:
        """Restore a persisted migration record (server restart mid-
        migration). A donor still in its ``export`` phase re-freezes its
        range — unless the lease lapsed while the server was down, in
        which case the restore IS the auto-abort (map untouched).
        Malformed records are ignored: a garbled ledger must degrade to
        a resumable-by-status=absent migration, not a refused restore.
        Returns True when a record was installed."""
        if not isinstance(rec, dict):
            return False
        try:
            rec = {
                "id": str(rec["id"]), "role": str(rec["role"]),
                "phase": str(rec["phase"]),
                "slot_lo": int(rec["slot_lo"]),
                "slot_hi": int(rec["slot_hi"]),
                "ranges": [[int(a), int(b)]
                           for a, b in (rec.get("ranges") or [])],
                "map_version": int(rec.get("map_version") or 0),
                "lease_ttl": float(rec.get("lease_ttl")
                                   or DEFAULT_MIGRATION_LEASE_S),
                "lease_deadline": float(rec.get("lease_deadline", 0.0)),
                "started_at": float(rec.get("started_at", 0.0)),
            }
        except (KeyError, TypeError, ValueError):
            return False
        with self._reshard_lock:
            self._migration = rec
            if rec["role"] == "donor" and rec["phase"] == "export":
                self._draining.update(range(rec["slot_lo"],
                                            rec["slot_hi"]))
                if self._lease_expired_locked():
                    return False
        print(f"RESHARD_RESTORED migration={rec['id']} "
              f"role={rec['role']} phase={rec['phase']}", flush=True)
        return True

    def reshard(self, request: bytes, ctx) -> bytes:
        """Admin-plane slot-range handoff (docs/SHARDING.md "Migration
        protocol"). Four sub-operations, driven by ``cli reshard``:

        - ``export``: freeze ``[slot_lo, slot_hi)`` (pushes touching it
          are disowned from this instant) and return a consistent params
          subset + the completed push-token journal + the step — the
          donor half. Nothing is dropped yet.
        - ``import``: graft a transferred subset + journal into this
          store — the recipient half. Exactly-once survives the handoff
          because the donor's journal seeds this service's dedupe table
          BEFORE any client is re-routed here.
        - ``apply_ranges``: install the coordinator's new slot partition
          + map version (every primary converges to the same revision);
          clears any draining slots this shard no longer owns.
        - ``commit``: drop the donor's copy of the migrated range after
          the recipient confirmed adoption; clears the drain markers.
        """
        meta, payload = unpack_msg(request)
        if self.sharding is None:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "reshard: this server is not a shard primary")
            raise ValueError("reshard on unsharded server")
        op = str(meta.get("op"))
        if op not in RESHARD_OPS:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"reshard: unknown op {op!r}")
            raise ValueError(f"unknown reshard op {op!r}")
        self._tm_reshard[op].inc()
        plan = self._migration_plan(meta.get("migration"))
        # Every reply carries the CURRENT map (full, never delta-gated):
        # the coordinator derives the new partition from the donor's live
        # ranges instead of trusting its own stale picture.
        if op == "status":
            # Read-only: the resumed coordinator's crash-point oracle.
            return pack_msg({"migration": self.migration_view(),
                             "global_step": self.store.global_step,
                             **self._shard_fields()})
        if op == "abort":
            return self._reshard_abort(plan)
        if op == "export":
            lo, hi = int(meta["slot_lo"]), int(meta["slot_hi"])
            with self._reshard_lock:
                self._lease_expired_locked()
                rec = self._migration
                if rec is not None and (plan is None
                                        or rec["id"] != plan["id"]):
                    if ctx is not None:
                        ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                  f"reshard: migration {rec['id']} "
                                  f"already in flight")
                    raise ValueError("migration already in flight")
                self._draining.update(range(lo, hi))
                if plan is not None:
                    # Same id re-export is idempotent (resume replays the
                    # phase): the range got no applies while frozen, so a
                    # second export snapshot is byte-equivalent.
                    now = time.time()
                    self._migration = {**plan, "role": "donor",
                                       "phase": "export",
                                       "lease_deadline":
                                           now + plan["lease_ttl"],
                                       "started_at": now}
            if plan is not None:
                journal_event("migration", id=plan["id"], phase="export",
                              mig_role="donor", slot_lo=lo, slot_hi=hi)
            keys = self._keys_in_slots(lo, hi)
            params, step = self.store.export_params(keys)
            return pack_msg({"export_step": step,
                             "journal": self.journal_snapshot(),
                             "exported": len(params),
                             **self._shard_fields()},
                            encode_tensor_dict(params))
        if op == "import":
            with self._reshard_lock:
                rec = self._migration
                if rec is not None and (plan is None
                                        or rec["id"] != plan["id"]):
                    if ctx is not None:
                        ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                  f"reshard: migration {rec['id']} "
                                  f"already in flight")
                    raise ValueError("migration already in flight")
            params = decode_tensor_dict(payload)
            adopted = self.store.adopt_params(params)
            loaded = self.load_journal(meta.get("journal"))
            if plan is not None:
                now = time.time()
                with self._reshard_lock:
                    self._migration = {**plan, "role": "recipient",
                                       "phase": "import",
                                       "lease_deadline":
                                           now + plan["lease_ttl"],
                                       "started_at": now}
                journal_event("migration", id=plan["id"], phase="import",
                              mig_role="recipient")
            return pack_msg({"adopted": adopted, "journal_loaded": loaded,
                             **self._shard_fields()})
        if op == "apply_ranges":
            version = self._apply_ranges(meta)
            # The adopted map is now the sole ownership authority: drain
            # markers for slots handed away are redundant (the range
            # check disowns), and markers for slots the map says we KEEP
            # would contradict it (an aborted handoff must un-freeze).
            applied = None
            with self._reshard_lock:
                self._draining.clear()
                rec = self._migration
                if rec is not None and (plan is None
                                        or rec["id"] == plan["id"]):
                    if rec["role"] == "donor":
                        # Map published: the lease stops applying and
                        # the only exit is forward (commit).
                        rec["phase"] = "apply_ranges"
                    else:
                        # The recipient now OWNS the adopted range — its
                        # half of the migration is complete.
                        self._migration = None
                    applied = (rec["id"], rec["role"])
            if applied is not None:
                journal_event("migration", id=applied[0],
                              phase="apply_ranges", mig_role=applied[1])
            return pack_msg({"map_version": version,
                             **self._shard_fields()})
        # commit: the recipient holds the range; release the donor copy.
        lo, hi = int(meta["slot_lo"]), int(meta["slot_hi"])
        dropped = self.store.drop_params(self._keys_in_slots(lo, hi))
        committed = None
        with self._reshard_lock:
            self._draining -= set(range(lo, hi))
            rec = self._migration
            if rec is not None and (plan is None
                                    or rec["id"] == plan["id"]):
                committed = rec["id"]
                self._migration = None
        if committed is not None:
            journal_event("migration", id=committed, phase="commit",
                          mig_role="donor", dropped=dropped)
        return pack_msg({"dropped": dropped, **self._shard_fields()})

    def _apply_ranges(self, meta: dict) -> int:
        """Adopt the coordinator's partition — idempotently. A resumed
        coordinator re-applies the SAME plan to every primary; bumping
        the version again on a primary that already holds it would churn
        every client's cached map for nothing, so an exact match
        (ranges AND version already at-or-past the plan's) is a no-op."""
        ranges = meta["ranges"]
        want = meta.get("map_version")
        try:
            want_i = None if want is None else int(want)
            norm = [(int(a), int(b)) for a, b in ranges]
        except (TypeError, ValueError):
            want_i, norm = None, None
        if want_i is not None and norm is not None \
                and self.sharding.version >= want_i \
                and self.sharding.ranges() == norm:
            return self.sharding.version
        return self.sharding.adopt_ranges(ranges, want)

    def _reshard_abort(self, plan: dict | None) -> bytes:
        """Roll back this primary's half of a migration: donor
        unfreezes; a recipient that never came to own the range drops
        its adopted copies (ownership stays exclusive — the donor still
        owns and serves them). The live map is untouched either way."""
        dropped = 0
        with self._reshard_lock:
            rec = self._migration
            if rec is not None and (plan is None
                                    or rec["id"] == plan["id"]):
                if rec["role"] == "recipient":
                    lo, hi = rec["slot_lo"], rec["slot_hi"]
                    my_lo, my_hi = self.sharding.my_range()
                    if not (my_lo <= lo and hi <= my_hi):
                        dropped = self.store.drop_params(
                            self._keys_in_slots(lo, hi))
                self._draining.clear()
                self._migration = None
                print(f"RESHARD_ABORT migration={rec['id']} "
                      f"role={rec['role']} phase={rec['phase']} "
                      f"dropped={dropped}", flush=True)
        return pack_msg({"aborted": True, "dropped": dropped,
                         **self._shard_fields()})

    def register_worker(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        self._expire_tick()
        # Tenancy routing (docs/TENANCY.md): register into the job's own
        # store (its own membership, config, params), then stride the
        # local id so the cluster keeps ONE flat worker-id space. A
        # legacy peer sends no ``job`` and lands in the default job,
        # whose ids are the local ids — the pre-tenancy wire exactly.
        job = self._job_of(meta)
        store = self.store if self.jobs is None \
            else self.jobs.store_for(job)
        worker_id, total = store.register_worker(
            meta.get("worker_name", ""))
        if self.jobs is not None:
            worker_id = self.jobs.to_global(job, worker_id)
        # Directive capability is advertised by the WORKER (the directives
        # flow server->worker, so the server must know the peer can act on
        # them): legacy clients send no capabilities list and their
        # replies never carry directives — training untouched.
        caps = meta.get("capabilities")
        capable = isinstance(caps, (list, tuple)) and "directives" in caps
        with self._directive_lock:
            # A reused id slot (elastic respawn) must not inherit its
            # predecessor's undelivered directives, quarantine, or
            # capability — unconditionally: a LEGACY replacement must not
            # stay quarantined for its predecessor's sins, nor keep
            # accepting posts it will never hear.
            self._directives.pop(worker_id, None)
            self._quarantined.pop(worker_id, None)
            if capable:
                self._directive_capable.add(worker_id)
            else:
                self._directive_capable.discard(worker_id)
        return pack_msg({
            "worker_id": worker_id,
            "total_workers": total,
            # Client needs the server's codecs/mode to compress correctly
            # (the store PROPERTY — the config field may hold the
            # backend-default sentinel None). Under tenancy these are the
            # JOB store's fields: per-job aggregation config is exactly
            # what the client must adopt (sync quorum for job A, async
            # staleness for job B, same server).
            "push_codec": store.push_codec,
            "fetch_codec": getattr(store, "fetch_codec", "none"),
            "mode": store.config.mode,
            "learning_rate": store.config.learning_rate,
            # The async staleness bound, so a reconnecting client can make
            # the worker-side discard-or-repush call for its in-flight
            # gradient without a wasted round trip (docs/ROBUSTNESS.md).
            "staleness_bound": int(getattr(store.config,
                                           "staleness_bound", 5)),
            "elastic": bool(getattr(store.config, "elastic", False)),
            # Delta-fetch capability (docs/WIRE_PROTOCOL.md): clients may
            # send ``have_step`` on FetchParameters and must then handle a
            # NOT_MODIFIED reply. Advertised so old clients (which never
            # send have_step) and new clients against old servers (which
            # would ignore it) both keep working.
            "delta_fetch": bool(getattr(store, "supports_delta_fetch",
                                        False)),
            # Trace-context capability (docs/WIRE_PROTOCOL.md): clients may
            # attach a trace field to push frame headers / fetch meta and
            # this server will parent its handler/store spans on it. Same
            # gating discipline as delta_fetch — old clients never attach,
            # new clients against old servers see no advertisement and
            # stay silent, so mixed versions degrade to untraced.
            "trace_context": True,
            # Health-report capability (docs/OBSERVABILITY.md): clients may
            # attach a compact worker health report to fetch/push envelope
            # meta; this server feeds it to the cluster monitor. Gated on
            # the monitor actually existing so legacy peers (and monitor-
            # less servers) degrade to report-less heartbeats.
            "health_report": self.monitor is not None,
            # Compressed-domain capability (docs/WIRE_PROTOCOL.md): this
            # store aggregates quantized pushes without decoding and
            # publishes per-layer gradient scales (negotiated here,
            # refreshed via fetch replies). Same gating discipline as
            # delta_fetch — legacy clients ignore the field and keep
            # pushing fp16/int8 with their own scales.
            "compressed_domain": bool(getattr(
                store, "supports_compressed_domain", False)),
            # Directive-channel capability (docs/ROBUSTNESS.md): this
            # server may attach control directives to fetch/push reply
            # meta. Clients that advertised the capability above attach
            # acks and act on them; every other pairing degrades to a
            # directive-less wire.
            "directives": True,
            # Checksum capability (docs/WIRE_PROTOCOL.md "Checksum
            # trailer"): this server verifies the CRC-32 trailer on push
            # frames and REFUSES corrupt ones. Capable clients attach
            # the trailer to their push payloads; legacy pairings
            # degrade to unchecksummed frames exactly like delta_fetch /
            # trace_context (a server that never advertised would choke
            # on the 4 trailer bytes, so the client must gate on this).
            "checksum": True,
            # Tenancy capability (docs/TENANCY.md): advertised ONLY when
            # a job table is attached, with the job the peer landed in
            # echoed back (a capable client adopts it and labels every
            # subsequent envelope). Single-job servers add neither key —
            # the legacy reply stays byte-identical.
            **({"jobs": True, "job": job} if self.jobs is not None
               else {}),
            **self._qscale_fields(store=store),
            **self._membership_fields(store),
            # Shard-map capability (docs/SHARDING.md): present only when
            # this server runs as a shard primary. A capable client fans
            # pushes/fetches out per the map and refreshes it via
            # have_shard_map; a legacy client ignores the field and keeps
            # talking to this one shard (it sees a key-subset store).
            **self._shard_fields(),
        })

    def _ingest_health(self, worker_id, meta: dict) -> None:
        """Feed a piggybacked health report to the cluster monitor.
        Observability only: any failure (garbled report, monitor bug) is
        swallowed — it must never fail the RPC that carried it."""
        if self.monitor is None:
            return
        health = meta.get("health")
        if worker_id is None or not isinstance(health, dict):
            return
        try:
            self.monitor.ingest(worker_id, health)
        except Exception:  # noqa: BLE001
            pass

    def _refuse_corrupt(self, wid, meta: dict, store=None) -> bytes:
        """Refuse a push whose payload failed integrity verification
        (CRC trailer mismatch, or a frame the decoder rejects): counted
        (``dps_wire_corrupt_total``), surfaced to the health engine
        (``wire_corrupt`` rule), never applied — and never journaled, so
        the client's clean retry of the same token can still apply."""
        store = self.store if store is None else store
        self._tm_wire_corrupt.inc()
        if self.monitor is not None:
            try:
                self.monitor.note_corrupt_frame()
            except Exception:  # noqa: BLE001 — observability only
                pass
        print(f"WIRE_CORRUPT push refused worker={wid}", flush=True)
        return pack_msg({"received": False, "accepted": False,
                         "corrupt": True,
                         "global_step": store.global_step,
                         **self._directive_fields(wid, meta)})

    def push_gradrients(self, request: bytes, ctx) -> bytes:
        meta, payload = unpack_msg(request)
        job, store, lwid = self._route(meta)
        if self.qos is not None and not self.qos.admit(
                job, self._admission_budget(ctx)):
            if ctx is not None:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          f"job {job!r} throttled (weighted-fair "
                          f"admission); retry with backoff")
            raise TimeoutError(f"push throttled for job {job!r}")
        try:
            return self._push_body(meta, payload, ctx, job, store, lwid)
        finally:
            if self.qos is not None:
                self.qos.release(job)

    @staticmethod
    def _admission_budget(ctx) -> float:
        """QoS admission wait, bounded by the CALLER's remaining
        deadline minus a reply margin (the DUP_WAIT_CAP_S lesson: a
        server-side wait must never outlive the client's patience)."""
        budget = ADMISSION_WAIT_CAP_S
        if ctx is not None and callable(getattr(ctx, "time_remaining",
                                                None)):
            remaining = ctx.time_remaining()
            if remaining is not None:
                budget = max(0.0, min(budget, remaining - 1.0))
        return budget

    def _push_body(self, meta: dict, payload, ctx, job: str, store,
                   lwid: int) -> bytes:
        wid = int(meta["worker_id"])
        # Integrity gate FIRST — before the dedupe lifecycle records
        # anything for this token. frame_checksum_ok is None (no
        # trailer: legacy peer, nothing to verify) or a verdict; only an
        # explicit False refuses.
        if len(payload) and frame_checksum_ok(payload) is False:
            return self._refuse_corrupt(wid, meta, store)
        self._ingest_health(wid, meta)
        self._expire_tick()
        health = meta.get("health")
        nonfinite = (self.reject_nonfinite and isinstance(health, dict)
                     and (health.get("loss_finite") is False
                          or health.get("grad_finite") is False))
        # Remediation quarantine (plus its synchronous nonfinite half: a
        # push whose OWN report flags poison). Evaluated here but gated
        # AFTER the dedupe lookup below — a retry of a token whose
        # original was already APPLIED must replay the journaled outcome
        # even while its worker is quarantined, or the exactly-once reply
        # contract lies to the reconcile path. Only NEW pushes are
        # refused, and without recording an entry, so the same token
        # retried after the quarantine lifts applies normally.
        blocked = nonfinite or self.is_quarantined(wid)
        token = meta.get("push_token")
        entry = None
        if token is not None:
            nonce, count = parse_push_token(token)
            # Job-scoped dedupe namespace (docs/TENANCY.md): the nonce is
            # prefixed with the job, so IDENTICAL tokens under two jobs
            # are distinct entries — no cross-job dedupe collision — and
            # the journal filters per job at checkpoint time. The default
            # job's nonces stay bare (pre-tenancy journals round-trip).
            nonce = job_key(job, nonce)
            with self._push_seen_lock:
                prev = self._push_seen.get(nonce)
                if prev is not None and count <= prev[0]:
                    dup, stale = prev, count < prev[0]
                else:
                    # New push (or the first with a HIGHER count): record
                    # it — unless quarantine refuses it below. A lower
                    # count never replaces a higher one — the branch
                    # above already routed it away.
                    dup, stale = None, False
                    if not blocked:
                        entry = [count, None, threading.Event(), wid,
                                 None]
                        self._push_seen[nonce] = entry
                        self._push_seen.move_to_end(nonce)
                        while len(self._push_seen) > PUSH_SEEN_CAP:
                            self._push_seen.popitem(last=False)
            if dup is not None:
                if stale:
                    # ZOMBIE: a deadline-expired attempt executing after
                    # newer pushes from the same client already landed.
                    # Its gradient was either applied by the retry that
                    # overtook it or superseded — re-applying it here was
                    # the round-5 double-apply bug. Nobody is usually
                    # listening for this reply; answer terminally.
                    return pack_msg({
                        "received": True, "accepted": False,
                        "duplicate": True, "stale_token": True,
                        "global_step": store.global_step})
                # Retry of the push most recently seen from this client.
                # If the original is still in flight, wait for its
                # outcome — answering early with a fabricated
                # accepted=True would misreport an async push the
                # staleness gate later rejects. The wait is bounded by
                # the CALLER's remaining deadline (minus a margin to get
                # the reply out), falling back to a cap well under the
                # client's 60 s rpc_timeout — a flat 120 s outlived every
                # caller and pinned one of the 20 pool threads per
                # stacked retry (round-5 ADVICE).
                budget = DUP_WAIT_CAP_S
                remaining = None
                if ctx is not None and callable(
                        getattr(ctx, "time_remaining", None)):
                    remaining = ctx.time_remaining()
                if remaining is not None:
                    budget = max(0.0, min(budget, remaining - 1.0))
                dup[2].wait(timeout=budget)
                if dup[1] is None:
                    # Original STILL running after the wait — or it was
                    # corrupt-refused and its entry undone (event set,
                    # outcome never recorded): don't invent an outcome
                    # in either direction — fail retryably so the
                    # client's next attempt re-checks.
                    if ctx is not None:
                        ctx.abort(grpc.StatusCode.UNAVAILABLE,
                                  "push still in flight; retry")
                    raise TimeoutError("push still in flight")
                return pack_msg({
                    "received": True, "accepted": bool(dup[1]),
                    "duplicate": True,
                    "global_step": store.global_step})
        if blocked:
            # Quarantine refusal for a NEW push: acknowledge (the worker
            # must not die retrying) but never apply — a suspected-
            # poisoned worker's gradients stay out of the aggregate even
            # when the peer is too old to hear the quarantine directive.
            self._tm_quarantined.inc()
            return pack_msg({"received": True, "accepted": False,
                             "quarantined": True,
                             "global_step": store.global_step,
                             **self._directive_fields(wid, meta)})
        try:
            grads = decode_tensor_dict(payload)
        except ValueError:
            # A garbled frame that carried no trailer (or a truncation
            # the cheap pre-check let through): refuse it like a CRC
            # failure, and UNDO the in-flight dedupe entry so a clean
            # retry of the same token applies instead of replaying a
            # refusal. Waiters on the entry wake (outcome None) and
            # fail retryably.
            if entry is not None:
                with self._push_seen_lock:
                    if self._push_seen.get(nonce) is entry:
                        del self._push_seen[nonce]
                entry[2].set()
            return self._refuse_corrupt(wid, meta, store)
        # Ownership filter (docs/SHARDING.md "Migration protocol"): keys
        # whose slot this primary no longer owns — the map moved while
        # the client pushed on a cached one, or the slot is mid-handoff
        # (draining) — are dropped from the apply and NAMED in the reply
        # beside a fresh map, so the client re-routes that slice to the
        # current owner under a fresh token. The rest of the push applies
        # normally: round accounting must see the worker either way.
        disowned = self._disowned_keys(grads)
        shard_extra: dict = {}
        if disowned:
            for k in disowned:
                grads.pop(k, None)
            self._tm_disowned.inc(len(disowned))
            shard_extra = {"disowned": disowned, **self._shard_fields()}
        accepted = False
        try:
            accepted = store.push(lwid, grads, int(meta["fetched_step"]))
        finally:
            # On an exception the event still fires (outcome False) so a
            # waiting retry is never stranded until its timeout. The
            # captured entry object is updated directly — an entry the
            # LRU bound evicted mid-flight still wakes its waiters.
            if entry is not None:
                entry[1] = accepted
                entry[4] = store.global_step
                entry[2].set()
        return pack_msg({"received": True, "accepted": accepted,
                         "global_step": store.global_step,
                         **shard_extra,
                         **self._directive_fields(wid, meta)})

    # -- durable push-token journal (docs/ROBUSTNESS.md) ---------------------

    def journal_snapshot(self, job: str | None = None) -> list[dict]:
        """COMPLETED push-token outcomes, oldest first — the bounded
        journal a store snapshot persists (checkpoint/manager.py) so a
        restarted server still dedupes in-flight push retries from before
        the crash. In-flight entries are skipped: their outcome is
        unknown, and claiming one either way would be a lie the retry
        acts on. ``job`` filters to one job's namespace (nonces carry
        the job prefix, docs/TENANCY.md) so each job's checkpoint
        lineage journals ONLY its own tokens — cross-job journal leakage
        is structurally impossible."""
        with self._push_seen_lock:
            return [
                {"nonce": nonce, "count": e[0], "accepted": bool(e[1]),
                 "worker_id": e[3], "step": e[4]}
                for nonce, e in self._push_seen.items()
                if e[2].is_set()
                and (job is None or split_job_key(nonce)[0] == job)
            ]

    def load_journal(self, entries) -> int:
        """Seed the dedupe table from a persisted journal (server
        restart). Returns the number of entries loaded. Entries arrive
        completed (their events are pre-set); malformed records are
        skipped — a corrupt journal must degrade to weaker dedupe, not a
        refused restore."""
        loaded = 0
        with self._push_seen_lock:
            for rec in entries or []:
                try:
                    nonce = str(rec["nonce"])
                    count = int(rec["count"])
                    accepted = bool(rec["accepted"])
                    wid = int(rec.get("worker_id", -1))
                    step = rec.get("step")
                except (KeyError, TypeError, ValueError):
                    continue
                prev = self._push_seen.get(nonce)
                if prev is not None and count <= prev[0]:
                    continue  # never downgrade to a lower count
                ev = threading.Event()
                ev.set()
                self._push_seen[nonce] = [count, accepted, ev, wid, step]
                self._push_seen.move_to_end(nonce)
                loaded += 1
            while len(self._push_seen) > PUSH_SEEN_CAP:
                self._push_seen.popitem(last=False)
        return loaded

    def fetch_parameters(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        job, store, lwid = self._route(meta)
        if self.qos is not None and not self.qos.admit(
                job, self._admission_budget(ctx)):
            if ctx is not None:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          f"job {job!r} throttled (weighted-fair "
                          f"admission); retry with backoff")
            raise TimeoutError(f"fetch throttled for job {job!r}")
        try:
            return self._fetch_body(meta, job, store, lwid)
        finally:
            if self.qos is not None:
                self.qos.release(job)

    # dpslint: hot-path — every worker ping; NM replies serve a cached encode
    def _fetch_body(self, meta: dict, job: str, store,
                    lwid) -> bytes:
        wid = None if meta.get("worker_id") is None \
            else int(meta["worker_id"])
        # Heartbeat pings are fetches — the report rides the ping's
        # envelope meta, so a delta-gated ping (header-only both ways)
        # still refreshes the cluster monitor's view of this worker.
        self._ingest_health(wid, meta)
        self._note_replica(meta)
        have = meta.get("have_step")
        # Scale-table refresh rides the same reply (delta-gated on the
        # client's known version): new rounds move both the params and
        # the shared scales, so one fetch refreshes both. Legacy clients
        # never send have_qscales and never pay for a table they ignore.
        qfields = self._qscale_fields(meta["have_qscales"], store=store) \
            if "have_qscales" in meta else {}
        dfields = self._directive_fields(wid, meta)
        sfields = self._shard_fields(meta["have_shard_map"]) \
            if "have_shard_map" in meta else {}
        tfields = self._topology_fields(meta["have_topology"]) \
            if "have_topology" in meta else {}
        if have is not None \
                and getattr(store, "supports_delta_fetch", False):
            params, step = store.fetch(lwid, have_step=int(have))
            if not params and step == int(have):
                # Version-gated delta fetch: the canonical step hasn't
                # advanced past what the client holds — the reply costs a
                # header instead of the full model (the straggler-wait /
                # polling fetch win; docs/WIRE_PROTOCOL.md).
                mfields = self._membership_fields(store)
                if qfields or dfields or sfields or tfields:
                    return pack_msg({"global_step": step,
                                     "not_modified": True, **qfields,
                                     **dfields, **sfields, **tfields,
                                     **mfields})
                # Attachment-free NM reply: serve the cached encode. The
                # key folds in the membership view so an elastic join/
                # leave at an unchanged step still invalidates — and the
                # job, so two jobs idling at the same step never serve
                # each other's cached header.
                key = (job, step, repr(mfields))
                with self._nm_lock:
                    if self._nm_cache is not None \
                            and self._nm_cache[0] == key:
                        self._tm_nm_cache_hits.inc()
                        return self._nm_cache[1]
                    if self._nm_building == key:
                        # Single-flight: someone else is encoding this
                        # exact reply right now — park briefly and serve
                        # their bytes (counted as a cache hit: identical
                        # polls coalesced onto one encode).
                        self._nm_cond.wait_for(
                            lambda: self._nm_building != key
                            or (self._nm_cache is not None
                                and self._nm_cache[0] == key),
                            timeout=0.25)
                        if self._nm_cache is not None \
                                and self._nm_cache[0] == key:
                            self._tm_nm_cache_hits.inc()
                            return self._nm_cache[1]
                    else:
                        self._nm_building = key
                reply = pack_msg({"global_step": step,
                                  "not_modified": True, **mfields})
                with self._nm_lock:
                    self._nm_cache = (key, reply)
                    if self._nm_building == key:
                        self._nm_building = None
                    self._nm_cond.notify_all()
                return reply
        else:
            params, step = store.fetch(lwid)
        return pack_msg({"global_step": step, **qfields, **dfields,
                         **sfields, **tfields,
                         **self._membership_fields(store)},
                        encode_tensor_dict(params))

    def job_finished(self, request: bytes, ctx) -> bytes:
        meta, _ = unpack_msg(request)
        _, store, lwid = self._route(meta)
        store.job_finished(int(lwid))
        return pack_msg({"acknowledged": True})

    def submit_job(self, request: bytes, ctx) -> bytes:
        """Admin-plane job control (docs/TENANCY.md): submit a job from
        a one-entry ``--jobs``-grammar spec (``job_spec`` meta key), or
        drain one (``drain_job``). Requires tenancy to be enabled —
        single-job servers answer FAILED_PRECONDITION, the Reshard-on-
        a-replica discipline."""
        meta, _ = unpack_msg(request)
        if self.jobs is None:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "submit_job: tenancy is not enabled on this "
                          "server (start it with --jobs)")
            raise ValueError("submit_job on a single-job server")
        drain = meta.get("drain_job")
        if drain is not None:
            try:
                drained = self.jobs.drain(str(drain))
            except ValueError as e:
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                raise
            return pack_msg({"drained": bool(drained),
                             "jobs": self.jobs.names()})
        try:
            specs = parse_jobs_spec(str(meta.get("job_spec") or ""))
            if len(specs) != 1:
                raise ValueError(
                    "job_spec must declare exactly one job")
            state = self.jobs.submit(specs[0])
        except ValueError as e:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            raise
        return pack_msg({"submitted": state.name, "index": state.index,
                         "jobs": self.jobs.names()})

    # -- wiring --------------------------------------------------------------

    def _instrumented(self, name: str, fn):
        """Wrap an RPC body with its span + byte counters. The span covers
        the full handler (decode + store work + encode); durations record
        even when the body raises/aborts — error handling time is real.

        With tracing enabled, the wrapper also adopts the client's
        propagated trace context (fetch meta / push frame header,
        docs/WIRE_PROTOCOL.md) and opens an ``rpc.server`` span under it,
        so the store spans recorded inside the body attach causally to
        the worker step that issued the RPC. An untraced or legacy peer
        yields no context and the span becomes a local root."""
        from ..telemetry import now, trace_enabled, trace_span, \
            use_wire_context
        from .wire import peek_trace
        hist, b_in, b_out, calls, slo_hist, errors = self._tm_rpc[name]

        def wrapped(request: bytes, ctx) -> bytes:
            t0 = now()
            b_in.inc(len(request))
            calls.inc()
            wire_ctx = None
            if trace_enabled():
                try:
                    meta, payload = unpack_msg(request)
                    wire_ctx = meta.get("trace") or \
                        (peek_trace(payload) if len(payload) else None)
                except Exception:  # noqa: BLE001
                    wire_ctx = None  # malformed request fails in fn, not here
            sp = None
            try:
                with use_wire_context(wire_ctx), \
                        trace_span("rpc.server", rpc=name) as sp:
                    reply = fn(request, ctx)
            except Exception:  # noqa: BLE001 — counted, then re-raised
                # Aborts (incl. injected unavailable/deadline faults)
                # raise through grpc's ctx.abort — count them where the
                # SLO availability objective reads, then let the abort
                # propagate unchanged.
                errors.inc()
                raise
            finally:
                dur = now() - t0
                hist.observe(dur)
                # Exemplar: the span's trace id, head-sampled. _NullSpan
                # (tracing off) has ctx None, so this stays a cheap
                # getattr when disabled.
                tid = getattr(getattr(sp, "ctx", None), "trace_id", None)
                if tid is not None and self._tm_exemplars.sample():
                    slo_hist.observe(dur, exemplar=tid)
                else:
                    slo_hist.observe(dur)
            b_out.inc(len(reply))
            return reply

        return wrapped

    def handlers(self) -> grpc.GenericRpcHandler:
        ident = lambda b: b  # noqa: E731 — bytes pass through untouched
        method_map = {
            "RegisterWorker": self.register_worker,
            "PushGradrients": self.push_gradrients,  # quirk 1, on purpose
            "FetchParameters": self.fetch_parameters,
            "JobFinished": self.job_finished,
            # Admin plane (docs/SHARDING.md "Migration protocol"): only
            # primaries register it; replicas answer UNIMPLEMENTED.
            "Reshard": self.reshard,
            # Admin plane (docs/TENANCY.md): job submit/drain; answers
            # FAILED_PRECONDITION on single-job servers.
            "SubmitJob": self.submit_job,
        }
        def wire(name, fn):
            # Fault injection sits INSIDE the instrumentation wrapper, so
            # injected delays/aborts land in the handler latency histogram
            # and call counters like real ones would — chaos telemetry
            # must look like production telemetry.
            body = fn
            if self.faults is not None:
                body = self.faults.wrap_handler(name, body)
            return self._instrumented(name, body)

        return grpc.method_handlers_generic_handler(SERVICE_NAME, {
            name: grpc.unary_unary_rpc_method_handler(
                wire(name, fn),
                request_deserializer=ident, response_serializer=ident)
            for name, fn in method_map.items()
        })


def serve(store: ParameterStore, port: int = 8000,
          max_rpc_workers: int = 20,
          service: ParameterService | None = None
          ) -> tuple[grpc.Server, int]:
    """Start the service (server.py:370-393). Returns (server, bound_port) —
    pass port=0 to pick a free port. Callers own shutdown. ThreadPool of 20
    reproduces the reference's cap — including its quirk 9 (20 < the
    32-worker max). ``service`` lets callers that need a handle on the
    service object (push-token journal persistence, fault injection —
    cli serve) construct it themselves."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_rpc_workers),
        options=GRPC_OPTIONS)
    svc = service if service is not None else ParameterService(store)
    server.add_generic_rpc_handlers((svc.handlers(),))
    bound = server.add_insecure_port(f"[::]:{port}")
    server.start()
    return server, bound
