"""Sharded remote store: per-shard fan-out behind the RemoteStore API.

:class:`ShardedRemoteStore` duck-types :class:`~.client.RemoteStore`'s
full worker-facing surface — so :class:`~..ps.worker.PSWorker` trains
against a consistent-hash-partitioned parameter tier (docs/SHARDING.md)
completely unchanged. One :class:`~.client.RemoteStore` per shard
primary underneath; this layer only routes and reassembles:

- **push** partitions the gradient dict by slot owner — through the
  live shard map once one is adopted (slot ranges move under live
  migration), falling back to the canonical
  :func:`~..ps.sharding.shard_for_key` partition before any map is
  seen — and sends each shard its slice,
  with that shard's OWN last-fetched step (staleness accounting is
  per-shard) and that store's OWN push token (each shard keeps its own
  exactly-once journal, so dedupe/crash recovery/session resume shard
  naturally — nothing here re-implements them).
- **fetch** fans out with per-shard ``have_step`` (delta-gated
  independently: an idle shard answers header-only NOT_MODIFIED while a
  busy one ships params) and reassembles from the per-shard param cache.
- **session resume** reuses the single-server machinery verbatim: a
  SessionLostError from any shard escalates to PSWorker, whose recovery
  calls reset_channel / register_worker / repush_last here — each fans
  out, and per-shard journals replay-or-apply each slice independently
  (a restarted shard applies, the survivors answer ``duplicate``).

The topology bootstraps from the shard map: construct with a single seed
address and the registration reply's published map supplies the peer
primaries, or pass the full primary list (``cli worker --shards``).
"""

from __future__ import annotations

import threading

import numpy as np

from ..ps.sharding import key_slot, shard_for_key, shard_for_slot
from .client import RemoteStore


class ShardedRemoteStore:
    """N per-shard RemoteStores behind the one-store client API."""

    decompresses_fetches = True

    def __init__(self, addresses, **remote_kwargs):
        """``addresses``: either the full ordered primary list (index =
        shard id), or a single seed address whose registration reply's
        shard map supplies the rest (deferred to register_worker)."""
        if isinstance(addresses, str):
            addresses = [a for a in addresses.split(",") if a]
        self._remote_kwargs = dict(remote_kwargs)
        self._stores: list[RemoteStore] = [
            RemoteStore(a, **self._remote_kwargs) for a in addresses]
        self._seeded = len(self._stores) == 1  # may grow from the map
        self._lock = threading.Lock()
        self._wids: list[int] = []  # guarded by: self._lock
        # guarded by: self._lock
        self._shard_steps: list[int | None] = [None] * len(self._stores)
        # guarded by: self._lock
        self._param_cache: list[dict] = [{} for _ in self._stores]
        self._health_provider = None
        self._health_revision = None

    # -- topology ------------------------------------------------------------

    @property
    def shard_count(self) -> int:
        return len(self._stores)

    @property
    def address(self) -> str:
        return ",".join(s.address for s in self._stores)

    @property
    def shard_map(self):
        return self._stores[0].shard_map

    def _adopt_map_locked(self) -> None:
        """Grow from seed: after the first registration, the published
        shard map's primary list replaces the single seed store with the
        full fan-out (new stores for the peers, the seed kept for its
        own shard)."""
        m = self._stores[0].shard_map
        if not self._seeded or m is None or m["shard_count"] == 1:
            return
        seed = self._stores[0]
        primaries = [s["primary"] for s in m["shards"]]
        try:
            seed_idx = primaries.index(seed.address)
        except ValueError:
            seed_idx = 0  # seed spoke for a shard under another name
        stores = []
        for i, addr in enumerate(primaries):
            stores.append(seed if i == seed_idx
                          else RemoteStore(addr, **self._remote_kwargs))
        self._stores = stores
        self._shard_steps = [None] * len(stores)
        self._param_cache = [{} for _ in stores]
        self._seeded = False

    # -- capability / config passthrough (all shards run one config) ---------

    def __getattr__(self, name):
        if name in {"push_codec", "fetch_codec", "supports_delta_fetch",
                    "supports_trace_context", "supports_health_report",
                    "supports_compressed_domain", "supports_directives",
                    "supports_checksum", "config"}:
            return getattr(self._stores[0], name)
        raise AttributeError(name)

    @property
    def health_provider(self):
        return self._health_provider

    @health_provider.setter
    def health_provider(self, fn):
        self._health_provider = fn
        for s in self._stores:
            s.health_provider = fn

    @property
    def health_revision(self):
        return self._health_revision

    @health_revision.setter
    def health_revision(self, fn):
        self._health_revision = fn
        for s in self._stores:
            s.health_revision = fn

    # -- lifecycle -----------------------------------------------------------

    def register_worker(self, worker_name: str = "",
                        retries: int | None = None) -> tuple[int, int]:
        """Register with every shard primary (seed first, so its map can
        grow the fan-out). Returns shard 0's (worker_id, total_workers) —
        the identity PSWorker logs; the per-shard ids live here."""
        wid0, total = self._stores[0].register_worker(worker_name,
                                                      retries=retries)
        with self._lock:
            self._adopt_map_locked()
            stores = list(self._stores)
        wids = [wid0]
        for s in stores[1:]:
            wid, _ = s.register_worker(worker_name, retries=retries)
            wids.append(wid)
        with self._lock:
            self._wids = wids
            self._shard_steps = [None] * len(stores)
            # Health plumbing installed before the map grew the fan-out
            # must reach the new stores too.
            for s in stores:
                s.health_provider = self._health_provider
                s.health_revision = self._health_revision
        return wid0, total

    def fetch(self, worker_id: int | None = None,
              have_step: int | None = None
              ) -> tuple[dict[str, np.ndarray], int]:
        """Fan out, delta-gated PER SHARD (each shard is asked against
        its own last-seen step — a global ``have_step`` would force full
        refetches from idle shards whenever one shard advanced). Returns
        the caller's NOT_MODIFIED contract unchanged: ``({}, have_step)``
        only when EVERY shard stood still; otherwise the merged full
        dict at the minimum shard step (the conservative basis for
        staleness accounting)."""
        with self._lock:
            stores = list(self._stores)
            wids = list(self._wids) or [None] * len(stores)
            shard_steps = list(self._shard_steps)
        parts: list[tuple[int, dict, int]] = []
        all_nm = have_step is not None
        for i, s in enumerate(stores):
            hs = shard_steps[i] if have_step is not None else None
            params, step = s.fetch(wids[i], have_step=hs)
            nm = hs is not None and not params and step == hs
            if not nm:
                all_nm = False
            parts.append((i, params, step))
        with self._lock:
            for i, params, step in parts:
                self._shard_steps[i] = step
                if params:
                    self._param_cache[i] = params
            steps = [p[2] for p in parts]
            gstep = min(steps) if steps else 0
            if all_nm and gstep == have_step:
                return {}, int(have_step)
            merged: dict[str, np.ndarray] = {}
            for cache in self._param_cache:
                merged.update(cache)
            return merged, gstep

    def _route_ranges(self) -> list | None:
        """Slot ranges from the freshest adopted shard map, or None when
        no usable map exists (pre-registration, or a map whose shard
        count disagrees with the fan-out). With None the router falls
        back to the canonical boot-time partition — correct until the
        first live migration, which always publishes a map first."""
        best = None
        for s in self._stores:
            m = s.shard_map
            if m is not None and (best is None
                                  or m["version"] > best["version"]):
                best = m
        if best is None or best["shard_count"] != len(self._stores):
            return None
        return [tuple(sh["slot_range"]) for sh in best["shards"]]

    def _owner(self, name, n: int, ranges) -> int:
        """Key -> shard id, through the LIVE map when one is adopted
        (slot ranges move under migration; docs/SHARDING.md). Companion
        keys (``w::int8scale`` etc.) route on the base tensor name so a
        quantized slice never splits from its scales."""
        if ranges is None:
            return shard_for_key(name, n)
        base = str(name).split("::", 1)[0]
        return shard_for_slot(key_slot(base), ranges)

    def push(self, worker_id: int, gradients: dict,
             fetched_step: int) -> bool:
        """Partition by key owner (live map when adopted, canonical
        otherwise) and push each shard its slice against that shard's own
        fetched step. Every shard gets a push even when its slice is
        empty — in sync mode a round only closes when all workers report,
        so skipping a keyless shard would wedge its rounds behind
        everyone else's. A slice the target DISOWNED (it pushed on a map
        that moved mid-flight) is re-routed once to the new owner under a
        fresh token in async mode; in sync mode it is dropped — a second
        push into the new owner's round would double-report this worker
        and skew the round barrier, and a dropped async-equivalent slice
        costs the same as one staleness reject."""
        with self._lock:
            stores = list(self._stores)
            wids = list(self._wids) or [worker_id] * len(stores)
            shard_steps = list(self._shard_steps)
        n = len(stores)
        ranges = self._route_ranges()
        slices: list[dict] = [{} for _ in range(n)]
        for name, g in gradients.items():
            slices[self._owner(name, n, ranges)][name] = g
        ok = True
        for i, s in enumerate(stores):
            step = shard_steps[i] if shard_steps[i] is not None \
                else fetched_step
            ok = s.push(wids[i], slices[i], int(step)) and ok
            disowned = s.last_disowned
            if disowned:
                s.last_disowned = []
                ok = self._reroute_disowned(
                    i, disowned, slices[i], stores, wids, shard_steps,
                    fetched_step) and ok
        return ok

    def _reroute_disowned(self, src: int, disowned, src_slice: dict,
                          stores, wids, shard_steps,
                          fetched_step: int) -> bool:
        """One re-route attempt for a disowned slice, against the map
        the reply carried (already adopted by the per-shard client). No
        recursion: a slice disowned AGAIN mid-re-route is dropped, the
        same worst case as a stale async push. Sync mode drops outright
        (see push's docstring)."""
        if getattr(self.config, "mode", "sync") != "async":
            return True
        ranges = self._route_ranges()
        if ranges is None:
            return True
        regroup: dict[int, dict] = {}
        for k in disowned:
            if k in src_slice:
                j = self._owner(k, len(stores), ranges)
                if j != src:
                    regroup.setdefault(j, {})[k] = src_slice[k]
        ok = True
        for j, grads in regroup.items():
            step = shard_steps[j] if shard_steps[j] is not None \
                else fetched_step
            ok = stores[j].push(wids[j], grads, int(step)) and ok
        return ok

    def repush_last(self, worker_id: int):
        """Session-resume reconciliation, fanned out: every shard replays
        its own last push token verbatim — restarted shards apply from
        scratch or answer from their restored journal, survivors answer
        ``duplicate``. Outcome is AND-ed like push's."""
        with self._lock:
            stores = list(self._stores)
            wids = list(self._wids) or [worker_id] * len(stores)
        outcomes = [s.repush_last(wids[i]) for i, s in enumerate(stores)]
        known = [o for o in outcomes if o is not None]
        return all(known) if known else None

    def job_finished(self, worker_id: int) -> None:
        with self._lock:
            stores = list(self._stores)
            wids = list(self._wids) or [worker_id] * len(stores)
        for i, s in enumerate(stores):
            s.job_finished(wids[i])

    def reset_channel(self) -> None:
        for s in self._stores:
            s.reset_channel()

    def close(self) -> None:
        for s in self._stores:
            s.close()

    # -- piggybacked state (merged views) ------------------------------------

    def take_directives(self) -> list[dict]:
        out: list[dict] = []
        for s in self._stores:
            out.extend(s.take_directives())
        return out

    def gradient_scales(self) -> tuple[dict[str, float], int]:
        """Per-shard tables merged (key sets are disjoint by
        construction); the version is the minimum so a stale shard keeps
        refreshing."""
        merged: dict[str, float] = {}
        steps = []
        for s in self._stores:
            scales, step = s.gradient_scales()
            merged.update(scales)
            steps.append(step)
        return merged, (min(steps) if steps else 0)

    def membership_snapshot(self) -> list[int]:
        return self._stores[0].membership_snapshot()

    def wire_stats(self) -> dict:
        out = {"wire_bytes_out": 0, "wire_bytes_in": 0, "rpc_counts": {}}
        for s in self._stores:
            st = s.wire_stats()
            out["wire_bytes_out"] += st["wire_bytes_out"]
            out["wire_bytes_in"] += st["wire_bytes_in"]
            for k, v in st["rpc_counts"].items():
                out["rpc_counts"][k] = out["rpc_counts"].get(k, 0) + v
        return out
