"""Tensor-dict wire format.

The reference pickles ``{name: np.ndarray}`` dicts onto the wire
(worker.py:289, server.py:222) — simple but unsafe (pickle executes code) and
Python-bound. This codec keeps the same logical payload with a safe,
language-neutral layout, so a future C++/other-host peer can speak it:

    [u32 header_len][header JSON utf-8][raw buffer 0][raw buffer 1]...

header: {"tensors": [{"name": str, "dtype": str, "shape": [int...]}...]}
Buffers are C-contiguous little-endian, concatenated in header order.

fp16 gradient compression (worker.py:264-268) composes naturally: cast the
arrays before encoding and the wire carries half the bytes.
"""

from __future__ import annotations

import json
import struct
from typing import Mapping

import ml_dtypes  # ships with jax; provides the numpy bfloat16 dtype
import numpy as np

_ALLOWED_DTYPES = {
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def encode_tensor_dict(tensors: Mapping[str, np.ndarray]) -> bytes:
    metas = []
    buffers = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dtype = arr.dtype.name
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError(f"unsupported dtype {dtype} for {name!r}")
        metas.append({"name": name, "dtype": dtype,
                      "shape": list(arr.shape)})
        buffers.append(arr.tobytes())
    header = json.dumps({"tensors": metas}).encode("utf-8")
    return b"".join([struct.pack("<I", len(header)), header, *buffers])


def decode_tensor_dict(payload: bytes) -> dict[str, np.ndarray]:
    if len(payload) < 4:
        raise ValueError("truncated payload")
    (hlen,) = struct.unpack_from("<I", payload, 0)
    header_end = 4 + hlen
    if header_end > len(payload):
        raise ValueError("truncated header")
    header = json.loads(payload[4:header_end].decode("utf-8"))
    out: dict[str, np.ndarray] = {}
    offset = header_end
    for meta in header["tensors"]:
        dtype = meta["dtype"]
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError(f"unsupported dtype {dtype}")
        dt = _resolve_dtype(dtype)
        shape = tuple(int(s) for s in meta["shape"])
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape \
            else dt.itemsize
        end = offset + nbytes
        if end > len(payload):
            raise ValueError(f"truncated buffer for {meta['name']!r}")
        arr = np.frombuffer(payload[offset:end], dtype=dt).reshape(shape)
        out[str(meta["name"])] = arr.copy()  # own the memory
        offset = end
    return out
