"""Tensor-dict wire format: versioned, zero-copy, chunkable.

The reference pickles ``{name: np.ndarray}`` dicts onto the wire
(worker.py:289, server.py:222) — simple but unsafe (pickle executes code) and
Python-bound. This codec keeps the same logical payload with a safe,
language-neutral layout, so a future C++/other-host peer can speak it.

Frame v2 (current)::

    [u8 0xD5 magic][u8 version=2][u8 flags][u8 reserved]
    [u32 header_len LE][header JSON utf-8][raw buffer 0][raw buffer 1]...

header: {"tensors": [{"name": str, "dtype": str, "shape": [int...]}...]}
Buffers are C-contiguous little-endian, concatenated in header order.
flags bit 0 marks a CHUNK frame (see *Chunked framing* below); flags
bit 1 marks a CHECKSUMMED frame carrying a 4-byte CRC-32 trailer over
everything before it (preamble + header + buffers) — corruption anywhere
in the frame, header included, fails decode with ``ValueError`` instead
of applying garbled tensors (docs/WIRE_PROTOCOL.md "Checksum trailer").
The trailer is capability-gated by the caller: legacy decoders that
predate it would mistake the 4 extra bytes for buffer slack, so encoders
only set it for peers that advertised ``checksum`` at registration.

Frame v1 (legacy, still decoded)::

    [u32 header_len LE][header JSON utf-8][raw buffer 0]...

Copy discipline — the host-side cost THC and the gradient-compression
studies (PAPERS.md) identify as the post-codec bottleneck:

- **encode**: exactly ONE copy per tensor — each buffer is memcpy'd once
  into the output frame by ``bytes.join`` over buffer views (the previous
  codec paid ``tobytes()`` + ``join`` = two copies). A non-contiguous
  input costs one extra copy to make it contiguous. The
  :func:`set_copy_count_hook` test hook counts every buffer copy so the
  single-copy invariant is pinned by a tier-1 test.
- **decode**: ZERO copies — tensors are ``np.frombuffer`` views into
  memoryview slices of the payload (read-only when the payload is
  ``bytes``; the payload stays alive via the arrays' ``.base``). Callers
  that must mutate in place pass ``copy=True``.

Chunked framing: payloads near the gRPC message ceiling (500 MB here,
GRPC_OPTIONS) can be encoded as N self-describing chunk frames
(:func:`encode_tensor_dict_chunks`) carried as separate messages by a
streaming transport and reassembled by
:func:`decode_tensor_dict_chunks`. Chunk boundaries prefer tensor
boundaries, so reassembly stays zero-copy unless a single tensor is
bigger than the chunk budget (only the spanning tensors are copied).

fp16 gradient compression (worker.py:264-268) composes naturally: cast the
arrays before encoding and the wire carries half the bytes.
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import Callable, Mapping

import ml_dtypes  # ships with jax; provides the numpy bfloat16 dtype
import numpy as np

from ..ops.packed import PackedInt4, as_packed_int4, packed_int4_nbytes

#: First byte of every v2+ frame. v1 frames start with the low byte of
#: their u32 header length instead; decode disambiguates by checking that
#: a v1 header begins with '{' at offset 4.
WIRE_MAGIC = 0xD5
WIRE_VERSION = 2
FLAG_CHUNK = 0x01
#: Frame carries a 4-byte CRC-32 trailer (zlib.crc32 — the stdlib
#: checksum; the container ships no crc32c wheel, and the repo already
#: keys its slot space on the same polynomial, ps/sharding.py:key_slot).
FLAG_CRC = 0x02

_PREAMBLE = 4  # magic + version + flags + reserved
_CRC_TRAILER = 4  # u32 LE crc32 appended after the last buffer

#: Upper bound on the JSON tensor table. A real table is ~100 bytes per
#: tensor; 16 MiB is orders of magnitude past any real model and small
#: enough that a corrupt/hostile length field can't trigger a giant
#: allocation before validation.
MAX_HEADER_BYTES = 16 << 20

_ALLOWED_DTYPES = {
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
    # Packed-nibble wire dtype (two signed 4-bit values per byte): the
    # header shape is the LOGICAL element shape, the buffer holds
    # ceil(n/2) bytes. numpy has no packed int4, so these tensors travel
    # as ops/packed.py's PackedInt4 (a uint8 array remembering its logical
    # shape); the quantization math lives in ops/compression.py.
    "int4",
}

# -- copy accounting (tier-1 zero-copy guard) --------------------------------

_copy_hook: Callable[[str, str], None] | None = None


def set_copy_count_hook(hook: Callable[[str, str], None] | None):
    """Install ``hook(tensor_name, reason)`` called once per buffer copy the
    encode path performs (reasons: ``'make_contiguous'``, ``'frame_write'``).
    Returns the previous hook. Tests use this to pin the at-most-one-copy
    invariant; pass ``None`` to uninstall."""
    global _copy_hook
    prev, _copy_hook = _copy_hook, hook
    return prev


def _note_copy(name: str, reason: str) -> None:
    if _copy_hook is not None:
        _copy_hook(name, reason)


# -- encode ------------------------------------------------------------------

# dpslint: hot-path — the zero-copy primitive everything else leans on
def _buffer_view(arr: np.ndarray) -> memoryview:
    """Raw little-endian bytes of a C-contiguous array, WITHOUT copying.

    Routed through a uint8 view because custom dtypes (bfloat16) don't
    export a standard buffer format; reshape(-1) first so 0-d arrays view
    cleanly."""
    return memoryview(arr.reshape(-1).view(np.uint8))


# dpslint: hot-path — per-tensor, every push and fetch
def _prepare(tensors: Mapping[str, np.ndarray]) -> tuple[list, list]:
    """Validate + normalize to (metas, contiguous arrays)."""
    metas, arrays = [], []
    for name, arr in tensors.items():
        if isinstance(arr, PackedInt4):
            # Wire dtype "int4": header shape is the LOGICAL shape, buffer
            # is the packed nibbles (as_packed_int4 validated the length).
            a = arr if arr.flags.c_contiguous else np.ascontiguousarray(arr)
            if a is not arr:
                _note_copy(str(name), "make_contiguous")
            metas.append({"name": str(name), "dtype": "int4",
                          "shape": list(arr.logical_shape)})
            arrays.append(np.asarray(a, np.uint8))
            continue
        a = np.asarray(arr)
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
            _note_copy(str(name), "make_contiguous")
        dtype = a.dtype.name
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError(f"unsupported dtype {dtype} for {name!r}")
        metas.append({"name": str(name), "dtype": dtype,
                      "shape": list(a.shape)})
        arrays.append(a)
    return metas, arrays


# dpslint: hot-path — the ONE sanctioned copy is the final join
def _frame(header_obj: dict, bodies: list, flags: int = 0,
           checksum: bool = False) -> bytes:
    """Assemble one v2 frame. ``bodies`` are buffer-protocol objects; each
    is copied exactly once by the final join. ``checksum`` sets FLAG_CRC
    and appends the CRC-32 trailer; the CRC is accumulated incrementally
    over the pieces BEFORE the join, so the one-copy-per-tensor
    discipline holds for checksummed frames too."""
    if checksum:
        flags |= FLAG_CRC
    header = json.dumps(header_obj).encode("utf-8")
    preamble = struct.pack("<BBBBI", WIRE_MAGIC, WIRE_VERSION, flags, 0,
                           len(header))
    if not checksum:
        return b"".join([preamble, header, *bodies])
    crc = zlib.crc32(header, zlib.crc32(preamble))
    for b in bodies:
        crc = zlib.crc32(b, crc)
    return b"".join([preamble, header, *bodies, struct.pack("<I", crc)])


# dpslint: hot-path — one buffer copy per tensor, enforced statically
def encode_tensor_dict(tensors: Mapping[str, np.ndarray],
                       trace: dict | None = None,
                       checksum: bool = False) -> bytes:
    """Encode to a single v2 frame (one buffer copy per tensor).

    ``trace`` (optional, capability-gated by the caller —
    docs/WIRE_PROTOCOL.md) adds a ``"trace"`` field to the v2 frame
    header: ``{"trace_id": str, "span_id": str}``, the distributed-tracing
    context of the worker operation that produced this payload. Decoders
    that don't know the field ignore it (the tensor table is keyed), and
    legacy v1 frames simply never carry one — mixed versions degrade to
    untraced, never break.

    ``checksum`` (capability-gated by the caller exactly like ``trace``)
    appends the CRC-32 integrity trailer — only send it to peers that
    advertised ``checksum`` at registration."""
    metas, arrays = _prepare(tensors)
    for m, a in zip(metas, arrays):
        if a.nbytes:
            _note_copy(m["name"], "frame_write")
    header: dict = {"tensors": metas}
    if trace is not None:
        header["trace"] = trace
    return _frame(header, [_buffer_view(a) for a in arrays],
                  checksum=checksum)


def encode_tensor_dict_chunks(tensors: Mapping[str, np.ndarray],
                              max_chunk_bytes: int,
                              checksum: bool = False) -> list[bytes]:
    """Encode as N chunk frames, each body at most ``max_chunk_bytes``.

    Chunk 0's header carries the tensor table + total payload length; every
    chunk's header carries ``{"chunk": {"index", "total", "offset"}}``.
    Splits land on tensor boundaries when possible (zero-copy reassembly);
    a tensor larger than the budget is hard-split mid-buffer.

    ``checksum`` appends the CRC-32 trailer to EVERY chunk frame — each
    chunk is verified independently at parse, so reassembly only ever
    sees clean segments.
    """
    if max_chunk_bytes < 1:
        raise ValueError(f"max_chunk_bytes must be >= 1, got "
                         f"{max_chunk_bytes}")
    metas, arrays = _prepare(tensors)
    # Cut the logical buffer section into per-chunk segment lists.
    chunks: list[list] = [[]]
    sizes = [0]
    for m, a in zip(metas, arrays):
        if not a.nbytes:
            continue  # zero-element tensors occupy no buffer bytes
        _note_copy(m["name"], "frame_write")
        view = _buffer_view(a)
        pos = 0
        while pos < a.nbytes:
            room = max_chunk_bytes - sizes[-1]
            if room == 0:
                chunks.append([])
                sizes.append(0)
                continue
            take = min(room, a.nbytes - pos)
            # Prefer starting a fresh chunk over splitting a tensor that
            # would fit whole in an empty one.
            if pos == 0 and take < a.nbytes and a.nbytes <= max_chunk_bytes:
                chunks.append([])
                sizes.append(0)
                continue
            chunks[-1].append(view[pos:pos + take])
            sizes[-1] += take
            pos += take
    total_payload = sum(sizes)
    frames = []
    offset = 0
    for i, (bodies, size) in enumerate(zip(chunks, sizes)):
        header: dict = {"chunk": {"index": i, "total": len(chunks),
                                  "offset": offset}}
        if i == 0:
            header["tensors"] = metas
            header["payload_len"] = total_payload
        frames.append(_frame(header, bodies, flags=FLAG_CHUNK,
                             checksum=checksum))
        offset += size
    return frames


# -- decode ------------------------------------------------------------------

# dpslint: hot-path — header parse only; bodies stay views
def _parse_frame(payload) -> tuple[dict, memoryview, int]:
    """-> (header dict, body memoryview, flags). Accepts v2 and legacy v1
    frames; validates the header length BEFORE any allocation sized by it."""
    mv = memoryview(payload)
    if len(mv) < 4:
        raise ValueError("truncated payload")
    # Disambiguation order matters: a LEGACY v1 frame whose u32 header_len
    # happens to be 0x...02D5 (e.g. exactly 725 — a realistic JSON table
    # size) also starts with [0xD5, 0x02]. A v2 frame's header JSON always
    # begins '{' at offset 8; a v1 frame's always begins '{' at offset 4 —
    # and a v1 header can't have '{' at BOTH (offset 8 is char 4 of
    # '{"tensors...', i.e. 'n'), so checking the v2 position first is
    # unambiguous for every frame either encoder ever produced.
    if (mv[0] == WIRE_MAGIC and mv[1] == WIRE_VERSION
            and len(mv) >= _PREAMBLE + 5 and mv[_PREAMBLE + 4] == 0x7B):
        flags, header_off = mv[2], _PREAMBLE
    elif len(mv) >= 5 and mv[4] == 0x7B:  # '{' at offset 4 => legacy v1
        flags, header_off = 0, 0
    elif mv[0] == WIRE_MAGIC and mv[1] != WIRE_VERSION:
        raise ValueError(f"unsupported wire version {mv[1]}")
    else:
        flags, header_off = 0, 0  # let the v1 length checks reject it
    if flags & FLAG_CRC:
        # Verify BEFORE trusting anything length-prefixed: the CRC covers
        # the whole frame (header included), so a flipped header byte
        # fails here rather than steering the tensor-table parse.
        if len(mv) < header_off + 4 + _CRC_TRAILER:
            raise ValueError("truncated payload")
        (want,) = struct.unpack_from("<I", mv, len(mv) - _CRC_TRAILER)
        if zlib.crc32(mv[:len(mv) - _CRC_TRAILER]) != want:
            raise ValueError("wire checksum mismatch (corrupt frame)")
        mv = mv[:len(mv) - _CRC_TRAILER]
    if len(mv) < header_off + 4:
        raise ValueError("truncated payload")
    (hlen,) = struct.unpack_from("<I", payload, header_off)
    if hlen > MAX_HEADER_BYTES:
        raise ValueError(f"header_len {hlen} exceeds cap {MAX_HEADER_BYTES}")
    header_end = header_off + 4 + hlen
    if header_end > len(mv):
        raise ValueError("truncated header")
    try:
        header = json.loads(bytes(mv[header_off + 4:header_end])
                            .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"bad frame header: {e}") from e
    if not isinstance(header, dict):
        raise ValueError("bad frame header: not an object")
    return header, mv[header_end:], flags


def _tensor_extent(meta: dict) -> tuple[np.dtype, tuple, int, bool]:
    """Validated (dtype, shape, nbytes, packed) from one header entry.
    Rejects NaN/float/negative/bool dims and unknown dtypes before any
    allocation; the size product is computed in unbounded Python ints, so
    it cannot overflow into a small bogus value. ``packed`` marks the
    "int4" wire dtype: ``shape`` is the LOGICAL shape, the buffer holds
    ``ceil(prod(shape)/2)`` uint8s of packed nibbles."""
    dtype = meta.get("dtype")
    if dtype not in _ALLOWED_DTYPES:
        raise ValueError(f"unsupported dtype {dtype}")
    packed = dtype == "int4"
    if packed:
        dt = np.dtype(np.uint8)
    elif dtype == "bfloat16":
        dt = np.dtype(ml_dtypes.bfloat16)
    else:
        dt = np.dtype(dtype)
    raw_shape = meta.get("shape", [])
    if not isinstance(raw_shape, list):
        raise ValueError(f"bad shape {raw_shape!r} for {meta.get('name')!r}")
    for s in raw_shape:
        if isinstance(s, bool) or not isinstance(s, int) or s < 0:
            raise ValueError(
                f"bad shape dim {s!r} for {meta.get('name')!r}")
    shape = tuple(raw_shape)
    nbytes = packed_int4_nbytes(shape) if packed \
        else dt.itemsize * math.prod(shape)
    return dt, shape, nbytes, packed


# dpslint: hot-path — frombuffer views; copy only on explicit opt-in
def _tensors_from_body(header: dict, body: memoryview,
                       copy: bool) -> dict[str, np.ndarray]:
    metas = header.get("tensors")
    if not isinstance(metas, list):
        raise ValueError("bad frame header: missing tensor table")
    out: dict[str, np.ndarray] = {}
    offset = 0
    for meta in metas:
        dt, shape, nbytes, packed = _tensor_extent(meta)
        end = offset + nbytes
        if end > len(body):
            raise ValueError(f"truncated buffer for {meta.get('name')!r}")
        arr = np.frombuffer(body[offset:end], dtype=dt)
        if packed:
            out[str(meta.get("name"))] = as_packed_int4(
                arr.copy() if copy else arr, shape)
        else:
            arr = arr.reshape(shape)
            out[str(meta.get("name"))] = arr.copy() if copy else arr
        offset = end
    return out


# dpslint: hot-path — zero-copy decode is the whole point of v2 frames
def decode_tensor_dict(payload, *, copy: bool = False
                       ) -> dict[str, np.ndarray]:
    """Decode one frame (v2 or legacy v1) to ``{name: ndarray}``.

    Default is ZERO-COPY: arrays are read-only views into ``payload``
    (which stays alive via ``.base``). ``copy=True`` returns owned,
    writable arrays instead."""
    header, body, flags = _parse_frame(payload)
    if flags & FLAG_CHUNK:
        raise ValueError("chunk frame: use decode_tensor_dict_chunks")
    return _tensors_from_body(header, body, copy)


def peek_trace(payload) -> dict | None:
    """Trace context from a frame header, or None (absent field, legacy v1
    frame, malformed/empty payload — never raises: a garbled trace field
    must degrade to untraced, not fail the RPC). Parses only the JSON
    header; the tensor buffers are untouched."""
    try:
        header, _, _ = _parse_frame(payload)
    except (ValueError, struct.error):
        return None
    trace = header.get("trace")
    return trace if isinstance(trace, dict) else None


def frame_checksum_ok(payload) -> bool | None:
    """Cheap integrity verdict for one frame: ``True`` (CRC trailer
    present and valid), ``False`` (present but wrong — corrupt or
    truncated), ``None`` (frame carries no trailer: legacy v1, or a v2
    peer that never negotiated the capability — nothing to verify).

    The push handler calls this BEFORE the dedupe lifecycle
    (comms/service.py): a corrupt push must be refused without recording
    a token entry, so the client's clean retry of the same token can
    still apply."""
    mv = memoryview(payload)
    if (len(mv) < _PREAMBLE or mv[0] != WIRE_MAGIC
            or mv[1] != WIRE_VERSION or not mv[2] & FLAG_CRC):
        return None
    if len(mv) < _PREAMBLE + 4 + _CRC_TRAILER:
        return False
    (want,) = struct.unpack_from("<I", mv, len(mv) - _CRC_TRAILER)
    return zlib.crc32(mv[:len(mv) - _CRC_TRAILER]) == want


def is_chunk_frame(payload) -> bool:
    """True iff ``payload`` is a v2 chunk frame (cheap preamble check)."""
    mv = memoryview(payload)
    return (len(mv) >= _PREAMBLE and mv[0] == WIRE_MAGIC
            and mv[1] == WIRE_VERSION and bool(mv[2] & FLAG_CHUNK))


def decode_tensor_dict_chunks(frames, *, copy: bool = False
                              ) -> dict[str, np.ndarray]:
    """Reassemble chunk frames (any order) and decode.

    Tensors contained within a single chunk decode as zero-copy views of
    that chunk; only tensors spanning a chunk boundary are stitched into
    fresh buffers."""
    parsed: dict[int, tuple[dict, memoryview]] = {}
    total = None
    for frame in frames:
        header, body, flags = _parse_frame(frame)
        if not flags & FLAG_CHUNK:
            raise ValueError("not a chunk frame; use decode_tensor_dict")
        info = header.get("chunk")
        if not isinstance(info, dict):
            raise ValueError("chunk frame missing chunk descriptor")
        idx, n = int(info["index"]), int(info["total"])
        if total is None:
            total = n
        elif n != total:
            raise ValueError(f"inconsistent chunk totals ({n} vs {total})")
        if idx in parsed:
            raise ValueError(f"duplicate chunk {idx}")
        parsed[idx] = (header, body)
    if total is None or sorted(parsed) != list(range(total)):
        raise ValueError(
            f"incomplete chunk set: have {sorted(parsed)} of {total}")
    head = parsed[0][0]
    metas = head.get("tensors")
    if not isinstance(metas, list):
        raise ValueError("chunk 0 missing tensor table")
    payload_len = head.get("payload_len")
    # Segment table: (logical start, body) in order, offsets contiguous.
    segments = []
    offset = 0
    for i in range(total):
        header, body = parsed[i]
        if int(header["chunk"].get("offset", -1)) != offset:
            raise ValueError(f"chunk {i} offset mismatch")
        segments.append((offset, body))
        offset += len(body)
    if payload_len is not None and offset != int(payload_len):
        raise ValueError(
            f"chunk payload length {offset} != declared {payload_len}")

    out: dict[str, np.ndarray] = {}
    pos = 0
    seg_i = 0
    for meta in metas:
        dt, shape, nbytes, packed = _tensor_extent(meta)
        end = pos + nbytes
        if end > offset:
            raise ValueError(f"truncated buffer for {meta.get('name')!r}")
        # Advance to the segment containing pos.
        while seg_i + 1 < len(segments) and segments[seg_i + 1][0] <= pos:
            seg_i += 1
        seg_start, seg_body = segments[seg_i]
        if end <= seg_start + len(seg_body) or nbytes == 0:
            raw = seg_body[pos - seg_start:end - seg_start]
            arr = np.frombuffer(raw, dtype=dt)
            if packed:
                arr = as_packed_int4(arr.copy() if copy else arr, shape)
            else:
                arr = arr.reshape(shape)
                arr = arr.copy() if copy else arr
        else:  # spans chunks: stitch (the only copying reassembly path)
            buf = bytearray(nbytes)
            filled = 0
            j = seg_i
            while filled < nbytes:
                s_start, s_body = segments[j]
                lo = pos + filled - s_start
                take = min(len(s_body) - lo, nbytes - filled)
                buf[filled:filled + take] = s_body[lo:lo + take]
                filled += take
                j += 1
            arr = np.frombuffer(bytes(buf), dtype=dt)
            arr = as_packed_int4(arr, shape) if packed \
                else arr.reshape(shape)
        out[str(meta.get("name"))] = arr
        pos = end
    return out
