"""Deterministic, seeded fault injection for the comms layer.

The crash-recovery subsystem (docs/ROBUSTNESS.md) is only credible if its
claims hold under *injected* faults, reproducibly — "we survived one lucky
run" is not fault tolerance. This module is the single injection point both
sides of the wire share:

- **client side** (`install_client_faults`): wraps ``RemoteStore``'s raw
  gRPC callables, so an injected UNAVAILABLE/DEADLINE_EXCEEDED exercises
  the real retry + reconnect machinery (`comms/client.py`), and an
  injected ``drop_reply`` performs the REAL call and then discards the
  reply — the server applied the gradient, the client never heard — which
  is exactly the lost-reply case the push-token exactly-once dedupe exists
  for (`comms/service.py`);
- **server side** (``ParameterService(faults=...)``): wraps the RPC
  handler bodies — delays model a slow server, aborts model an
  overloaded one, ``drop_reply`` aborts AFTER the handler (and therefore
  the store apply) completed, and ``kill`` hard-exits the process
  mid-handler to produce a deterministic crash point for restart drills
  (`experiments/run_chaos_soak.py`).

Determinism: every rule owns a counter and, for probabilistic rules, a
``random.Random`` seeded from ``(spec seed, rule index)``. A decision is a
pure function of the spec and the per-op call index, so the same seed and
the same call sequence replay the same fault schedule
(tests/test_recovery.py pins this).

Spec grammar (CLI ``--faults`` / env ``DPS_FAULTS_CLIENT`` /
``DPS_FAULTS_SERVER``)::

    spec  := [ 'seed=' int ';' ] rule ( ';' rule )*
    rule  := op '.' kind [ '=' float ] '@' when
    op    := 'push' | 'fetch' | 'register' | 'finish' | 'any' | 'compute'
           | 'reshard' | 'refresh' | 'subscribe'
    kind  := 'unavailable' | 'deadline' | 'delay' | 'drop_reply' | 'kill'
           | 'delay_compute' | 'partition' | 'corrupt'
    when  := 'p=' float          # per-call probability (seeded RNG)
           | 'n=' int(,int)*     # specific 1-based call indices for op
           | 'every=' int        # every k-th call

Examples::

    seed=7;push.unavailable@p=0.2        # 20% of pushes fail UNAVAILABLE
    fetch.delay=0.05@every=3             # every 3rd fetch sleeps 50 ms
    push.drop_reply@n=2,5                # pushes 2 and 5 apply, reply lost
    any.kill@n=40                        # the 40th RPC kills the server
    compute.delay_compute=0.25@every=1   # every local step +250 ms (a
                                         # deterministic straggler; the
                                         # worker loop polls this op once
                                         # per step — 'any' never matches)
    reshard.kill@n=2                     # 2nd migration op kills the
                                         # primary mid-handoff
    refresh.partition=2@n=5              # the replica's 5th refresh
                                         # opens a 2 s partition window
    push.corrupt@every=4                 # every 4th push payload gets a
                                         # deterministic byte flip

The first matching rule per call wins. ``delay`` composes with nothing —
it IS the action (the call proceeds after the sleep).

Serve-tier ops (ISSUE 13): ``reshard`` targets the admin-plane Reshard
RPC; ``refresh`` the replica's subscription poll against its primary
(client side of `comms/replica.py`); ``subscribe`` the replica's OWN
fetch-serving handler. ``any`` still means exactly the four worker RPCs
(``ANY_EXCLUDED``) so pre-existing seeded chaos schedules replay
byte-identically.

New kinds: ``partition`` drops every matching call — both directions,
nothing sent, nothing executed — for a ``value``-second window opened
when the rule triggers (default 1 s); ``corrupt`` flips one
deterministically-chosen byte of the request's tensor-payload region and
lets the call proceed, which is exactly what the wire CRC trailer
(comms/wire.py FLAG_CRC) must catch.
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time
from dataclasses import dataclass

import grpc

__all__ = [
    "ANY_EXCLUDED",
    "COMPUTE_OP",
    "FAULT_KINDS",
    "FAULT_OPS",
    "REFRESH_OP",
    "SUBSCRIBE_OP",
    "FaultInjector",
    "FaultRule",
    "InjectedRpcError",
    "corrupt_request",
    "install_client_faults",
    "parse_fault_spec",
]

#: The pseudo-op the worker loop polls once per LOCAL STEP for injected
#: compute slowdowns (``ps/worker.py`` via :meth:`maybe_delay_compute`) —
#: deliberately not a real RPC name, so RPC-side wrappers never see it
#: and ``any`` rules (which span the four RPCs) never match it.
COMPUTE_OP = "__compute__"

#: Pseudo-RPC names for the replica tier's two wire directions
#: (comms/replica.py): the subscription poll replica->primary (client
#: side) and the replica's own fetch-serving handler (server side). Both
#: are FetchParameters on the real wire, but a chaos schedule must be
#: able to partition the SUBSCRIPTION without touching serve traffic
#: (and vice versa), so each direction decides under its own op name.
REFRESH_OP = "__replica_refresh__"
SUBSCRIBE_OP = "__replica_subscribe__"

#: op name (spec vocabulary) -> RPC method name (None = 'any').
FAULT_OPS = {
    "push": "PushGradrients",  # quirk 1 typo is the wire contract
    "fetch": "FetchParameters",
    "register": "RegisterWorker",
    "finish": "JobFinished",
    "any": None,
    "compute": COMPUTE_OP,  # worker-loop per-step hook, not an RPC
    "reshard": "Reshard",  # admin-plane migration protocol
    "refresh": REFRESH_OP,  # replica subscription poll (client side)
    "subscribe": SUBSCRIBE_OP,  # replica's serving handler (server side)
}

#: RPC/pseudo-op names an 'any' rule never matches. 'any' has always
#: meant "the four worker RPCs"; keeping the admin plane and the replica
#: tier out preserves every pre-existing seeded schedule byte-for-byte
#: (an 'any.kill@n=40' chaos soak must not start counting reshard ops).
ANY_EXCLUDED = frozenset({COMPUTE_OP, "Reshard", REFRESH_OP,
                          SUBSCRIBE_OP})

FAULT_KINDS = ("unavailable", "deadline", "delay", "drop_reply", "kill",
               "delay_compute", "partition", "corrupt")

_STATUS = {
    "unavailable": grpc.StatusCode.UNAVAILABLE,
    "deadline": grpc.StatusCode.DEADLINE_EXCEEDED,
    "drop_reply": grpc.StatusCode.UNAVAILABLE,  # a lost reply looks transient
    "partition": grpc.StatusCode.UNAVAILABLE,  # a dropped packet looks down
}


class InjectedRpcError(grpc.RpcError):
    """Client-side injected failure, shaped like a live-channel error (the
    retry layer only reads ``.code()``)."""

    def __init__(self, code: grpc.StatusCode, detail: str):
        super().__init__()
        self._code = code
        self._detail = detail

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._detail

    def __str__(self) -> str:
        return f"injected {self._code.name}: {self._detail}"


@dataclass(frozen=True)
class FaultRule:
    op: str                        # key of FAULT_OPS
    kind: str                      # one of FAULT_KINDS
    value: float = 0.0             # delay seconds (kind='delay')
    prob: float | None = None      # when := p=
    at: frozenset | None = None    # when := n= (1-based per-op call index)
    every: int | None = None       # when := every=

    def matches_rpc(self, rpc_name: str) -> bool:
        target = FAULT_OPS[self.op]
        if target is None:
            # 'any' spans the four worker RPCs; compute, the admin
            # plane, and the replica tier are only ever hit by their own
            # explicit op rules (ANY_EXCLUDED — schedule stability).
            return rpc_name not in ANY_EXCLUDED
        return target == rpc_name


def corrupt_request(data: bytes, salt: int) -> bytes:
    """Flip ONE byte of an envelope's tensor-payload region,
    deterministically chosen from ``salt`` (the rule's per-hit counter) —
    same spec, same call sequence, same flipped byte, so a corrupt drill
    is as replayable as every other kind. Falls back to the meta JSON for
    header-only envelopes (still a corrupt request — the server's
    envelope parse fails loud instead of the CRC check)."""
    buf = bytearray(data)
    if len(buf) <= 4:
        return bytes(buf)  # no envelope to speak of; nothing to flip
    start = 4
    try:
        (hlen,) = struct.unpack_from("<I", data, 0)
    except struct.error:
        hlen = 0
    if 0 < hlen <= len(buf) - 4 - 1:
        # Flip inside the tensor payload (after the meta JSON) when one
        # exists — the case the wire CRC trailer must catch.
        start = 4 + hlen
    off = start + (salt * 2654435761) % (len(buf) - start)
    buf[off] ^= 0xFF
    return bytes(buf)


def parse_fault_spec(spec: str) -> tuple[int, list[FaultRule]]:
    """Parse a spec string -> (seed, rules). Raises ValueError with the
    offending fragment on any malformed rule — a typo'd chaos schedule must
    fail the run at startup, not silently inject nothing."""
    seed = 0
    rules: list[FaultRule] = []
    for raw in spec.split(";"):
        part = raw.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
            continue
        try:
            head, when = part.split("@", 1)
            op, _, kind_val = head.partition(".")
            kind, _, val = kind_val.partition("=")
            if op not in FAULT_OPS:
                raise ValueError(f"unknown op {op!r}")
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown kind {kind!r}")
            if (kind == "delay_compute") != (op == "compute"):
                # delay_compute is the compute pseudo-op's ONLY kind: a
                # compute slowdown on an RPC op (or an RPC fault on the
                # compute op) is a typo'd schedule, and a typo'd chaos
                # schedule must fail at startup.
                raise ValueError(
                    "delay_compute pairs with op 'compute' (and "
                    "'compute' supports only delay_compute)")
            value = float(val) if val else 0.0
            prob = at = every = None
            if when.startswith("p="):
                prob = float(when[2:])
                if not 0.0 <= prob <= 1.0:
                    raise ValueError(f"p={prob} outside [0, 1]")
            elif when.startswith("n="):
                at = frozenset(int(x) for x in when[2:].split(","))
                if not at or min(at) < 1:
                    raise ValueError("n= wants 1-based call indices")
            elif when.startswith("every="):
                every = int(when[6:])
                if every < 1:
                    raise ValueError("every= wants a positive int")
            else:
                raise ValueError(f"unknown trigger {when!r}")
            rules.append(FaultRule(op=op, kind=kind, value=value,
                                   prob=prob, at=at, every=every))
        except ValueError as e:
            raise ValueError(f"bad fault rule {part!r}: {e}") from None
    if not rules:
        raise ValueError(f"fault spec {spec!r} contains no rules")
    return seed, rules


class FaultInjector:
    """Decides, per RPC call, which fault (if any) to inject.

    One injector instance per process side; thread-safe (RPCs arrive on
    gRPC's thread pool / the worker's comms thread). Decisions consume
    per-rule state (call counters, seeded RNG draws), so two injectors
    built from the same spec replay identical schedules for identical
    call sequences.
    """

    def __init__(self, spec: str, side: str = "client",
                 _telemetry: bool = True):
        self.spec = spec
        self.side = side
        self.seed, self.rules = parse_fault_spec(spec)
        self._lock = threading.Lock()
        # Per-op call counters (1-based at decision time) + one RNG per
        # rule: a probabilistic rule's draw sequence must not shift when an
        # unrelated rule is added or another op is called.
        self._op_calls: dict[str, int] = {}
        self._rngs = [random.Random((self.seed << 8) ^ (i * 2654435761))
                      for i in range(len(self.rules))]
        # Partition windows: rule index -> wall-clock deadline. While a
        # rule's window is open EVERY matching call drops (both
        # directions dead), not just the triggering one — that is what
        # makes it a partition rather than a point failure.
        self._partition_until: dict[int, float] = {}  # guarded by: self._lock
        # Per-rule hit counters salting the corrupt byte-flip offset.
        self._rule_hits: dict[int, int] = {}  # guarded by: self._lock
        # _telemetry=False (schedule_preview's probe) keeps phantom
        # counters out of the process registry: a preview replays the
        # schedule without claiming injections happened on the wire.
        if _telemetry:
            from ..telemetry import get_registry
            reg = get_registry()
            self._tm = {
                (op, kind): reg.counter("dps_fault_injections_total",
                                        side=side, op=op, kind=kind)
                for op in FAULT_OPS for kind in FAULT_KINDS
            }
        else:
            class _Noop:
                def inc(self, n=1):
                    pass
            noop = _Noop()
            self._tm = {(op, kind): noop
                        for op in FAULT_OPS for kind in FAULT_KINDS}

    def decide(self, rpc_name: str) -> FaultRule | None:
        """One decision per RPC call: the first rule that matches and
        triggers wins; None = no fault this call."""
        with self._lock:
            n = self._op_calls.get(rpc_name, 0) + 1
            self._op_calls[rpc_name] = n
            for i, rule in enumerate(self.rules):
                if not rule.matches_rpc(rpc_name):
                    continue
                if rule.kind == "partition" and \
                        time.time() < self._partition_until.get(i, 0.0):
                    # Open window: the call drops without consuming the
                    # rule's trigger state — the window IS the fault.
                    self._tm[(rule.op, rule.kind)].inc()
                    return rule
                if rule.at is not None:
                    hit = n in rule.at
                elif rule.every is not None:
                    hit = n % rule.every == 0
                else:
                    # The draw happens on every matching call (hit or not)
                    # so the sequence is reproducible regardless of which
                    # draws land.
                    hit = self._rngs[i].random() < (rule.prob or 0.0)
                if hit:
                    if rule.kind == "partition":
                        self._partition_until[i] = \
                            time.time() + (rule.value or 1.0)
                    self._rule_hits[i] = self._rule_hits.get(i, 0) + 1
                    self._tm[(rule.op, rule.kind)].inc()
                    return rule
        return None

    def corrupt_salt(self, rule: FaultRule) -> int:
        """The number of times ``rule`` has triggered so far (1-based at
        the moment of a hit) — the deterministic salt
        :func:`corrupt_request` flips with."""
        for i, r in enumerate(self.rules):
            if r is rule:
                with self._lock:
                    return self._rule_hits.get(i, 0)
        return 0

    def maybe_delay_compute(self) -> float:
        """Worker-loop hook (``ps/worker.py``): one decision per local
        step against the compute pseudo-op; sleeps and returns the
        injected seconds on a hit, 0.0 otherwise. The deterministic
        straggler knob — ``compute.delay_compute=0.25@every=1`` slows
        every step by 250 ms, same seed -> same schedule."""
        rule = self.decide(COMPUTE_OP)
        if rule is None or rule.kind != "delay_compute":
            return 0.0
        if rule.value > 0:
            time.sleep(rule.value)
        return rule.value

    def schedule_preview(self, rpc_name: str, calls: int) -> list:
        """The schedule a FRESH injector with this spec would produce for
        ``calls`` consecutive ``rpc_name`` calls — determinism evidence for
        tests and for the chaos artifact's provenance record."""
        probe = FaultInjector(self.spec, side=f"{self.side}-preview",
                              _telemetry=False)
        out = []
        for _ in range(calls):
            rule = probe.decide(rpc_name)
            out.append(None if rule is None else (rule.kind, rule.value))
        return out

    # -- server side ---------------------------------------------------------

    def wrap_handler(self, rpc_name: str, fn):
        """Wrap one service RPC body. ``delay`` sleeps then runs;
        ``unavailable``/``deadline`` abort BEFORE the store is touched;
        ``drop_reply`` runs the body (the apply happens) then aborts — the
        reply is lost after the side effect, the exactly-once crucible;
        ``kill`` hard-exits mid-handler (the chaos soak's crash point)."""

        def wrapped(request: bytes, ctx) -> bytes:
            rule = self.decide(rpc_name)
            if rule is None:
                return fn(request, ctx)
            if rule.kind == "delay":
                time.sleep(rule.value)
                return fn(request, ctx)
            if rule.kind == "corrupt":
                # Ingress corruption: the handler sees a byte-flipped
                # request, exactly as if the wire damaged it — the CRC
                # refusal path (comms/service.py) is what's under test.
                return fn(corrupt_request(request,
                                          self.corrupt_salt(rule)), ctx)
            if rule.kind == "kill":
                print(f"fault injection: killing server mid-{rpc_name}",
                      flush=True)
                os._exit(137)  # SIGKILL-alike: no flush, no atexit
            if rule.kind == "drop_reply":
                fn(request, ctx)  # the apply HAPPENS; the reply does not
                self._abort(ctx, "drop_reply", rpc_name)
            # unavailable / deadline / partition: nothing executes. For
            # partition the abort doubles as "request never arrived" —
            # and the open window keeps dropping follow-ups both ways.
            self._abort(ctx, rule.kind, rpc_name)

        return wrapped

    def _abort(self, ctx, kind: str, rpc_name: str):
        code = _STATUS[kind]
        if ctx is not None:
            ctx.abort(code, f"injected {kind} ({rpc_name})")
        raise InjectedRpcError(code, f"server-side {kind} ({rpc_name})")


class _FaultyCall:
    """Client-side wrapper over one raw gRPC callable."""

    def __init__(self, inner, injector: FaultInjector, rpc_name: str):
        self._inner = inner
        self._injector = injector
        self._rpc_name = rpc_name

    def __call__(self, request, timeout=None):
        rule = self._injector.decide(self._rpc_name)
        if rule is None:
            return self._inner(request, timeout=timeout)
        if rule.kind == "delay":
            time.sleep(rule.value)
            return self._inner(request, timeout=timeout)
        if rule.kind == "corrupt":
            # Egress corruption: the wire damages this client's request
            # in flight; the server's CRC check must refuse it.
            return self._inner(
                corrupt_request(request, self._injector.corrupt_salt(rule)),
                timeout=timeout)
        if rule.kind == "kill":
            print(f"fault injection: killing client mid-{self._rpc_name}",
                  flush=True)
            os._exit(137)
        if rule.kind == "drop_reply":
            self._inner(request, timeout=timeout)  # server saw it...
            raise InjectedRpcError(_STATUS["drop_reply"],
                                   f"reply dropped ({self._rpc_name})")
        raise InjectedRpcError(_STATUS[rule.kind],
                               f"client-side {rule.kind} "
                               f"({self._rpc_name})")


def install_client_faults(remote_store, injector: FaultInjector) -> None:
    """Interpose the injector between RemoteStore and its channel. The
    wrappers sit UNDER the retry layer, so injected transients exercise
    the same backoff/reconnect paths a real flaky network would."""
    remote_store._call = {
        name: _FaultyCall(call, injector, name)
        for name, call in remote_store._call.items()
    }
