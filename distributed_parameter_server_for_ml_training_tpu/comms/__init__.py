"""Multi-host communication: wire codec + gRPC parameter service.

The reference's L2 (src/communication/): a 4-RPC gRPC service with tensors as
opaque pickled bytes (ps.proto:4-19, worker.py:289). Here the same lifecycle
is exposed over gRPC for DCN/multi-host deployments — but with a safe
length-prefixed tensor codec instead of pickle, and the TPU-native sync path
(XLA collectives over ICI) not using this service at all.

The sharded tier (docs/SHARDING.md) lives here too: ShardedRemoteStore
fans a worker's pushes/fetches out across consistent-hash shard
primaries, and ReplicaServer is the delta-fed read-only cache that
serves the fetch path behind each shard.
"""

from .wire import encode_tensor_dict, decode_tensor_dict
from .service import ParameterService, RawJSON, serve
from .client import RemoteStore, SessionLostError
from .sharded import ShardedRemoteStore
from .replica import ReplicaServer
from .faults import FaultInjector, install_client_faults

__all__ = [
    "encode_tensor_dict",
    "decode_tensor_dict",
    "FaultInjector",
    "install_client_faults",
    "ParameterService",
    "RawJSON",
    "ReplicaServer",
    "serve",
    "RemoteStore",
    "SessionLostError",
    "ShardedRemoteStore",
]
