"""TPU-native distributed data-parallel training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
parameter-server system (Jjjing2023/Distributed-Parameter-Server-for-ML-Training):

Design mapping (see README.md for implementation status per subsystem):

- sync data-parallel SGD      -> SPMD `shard_map` + `lax.pmean` over a named
                                 ``data`` mesh axis (ref: src/parameter_server/
                                 server.py:145-169 collapses into a compiled
                                 all-reduce; no server process exists)
- async bounded-staleness SGD -> host-CPU parameter store with per-worker device
                                 steps (ref: server.py:171-186, 290-304)
- gradient compression        -> reduced-precision all-reduce + quantization ops
                                 (ref: worker.py:264-268 fp16 cast)
- worker lifecycle            -> register/fetch/push/finished in-process API and
                                 gRPC service for multi-host (ref:
                                 src/communication/ps.proto:4-19)

Import as::

    import distributed_parameter_server_for_ml_training_tpu as dps
"""

__version__ = "0.1.0"
