"""CIFAR-100 input pipeline, TPU-first.

Capability parity with the reference worker data path
(src/workers/worker.py:140-197):

- CIFAR-100 with train-time augmentation RandomCrop(32, padding=4) +
  RandomHorizontalFlip + per-channel normalization (worker.py:145-155),
- contiguous equal sharding by worker id with the LAST worker taking the
  remainder (worker.py:166-179) — reproduced bit-for-bit by
  :func:`shard_range`,
- per-epoch shuffling within the shard (worker.py:182-187 used
  ``DataLoader(shuffle=True)``).

TPU-first differences: augmentation runs *on device* inside the jitted train
step (vectorized pad + dynamic-slice crop + flip under ``vmap``) instead of in
Python dataloader workers, and batches are delivered as whole device arrays.

Because this environment has no network egress, :func:`load_cifar100` reads
the standard ``cifar-100-python`` pickle layout when present on disk and
otherwise falls back to :func:`synthetic_cifar100` — a deterministic,
class-structured dataset a model can genuinely learn (used by tests and
benchmarks).
"""

from __future__ import annotations

import os
import pickle
import tarfile
from dataclasses import dataclass
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

# torchvision's CIFAR-100 normalization constants, as used by the reference
# (src/workers/worker.py:149-154).
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)

NUM_CLASSES = 100


@dataclass
class Dataset:
    """In-memory image-classification dataset (uint8 HWC images)."""

    x_train: np.ndarray  # [N, 32, 32, 3] uint8
    y_train: np.ndarray  # [N] int32
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int = NUM_CLASSES
    synthetic: bool = False


def _read_cifar_pickle(path: str) -> tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d[b"fine_labels"], np.int32)
    return np.ascontiguousarray(data, np.uint8), labels


def load_cifar100(data_dir: str | None = None,
                  allow_synthetic: bool = True) -> Dataset:
    """Load CIFAR-100 from ``data_dir`` (or $CIFAR100_DIR, ./data).

    Looks for the standard ``cifar-100-python/{train,test}`` pickles, or the
    ``cifar-100-python.tar.gz`` archive, matching what torchvision would have
    downloaded for the reference (worker.py:158-164). Falls back to a
    deterministic synthetic dataset when the real data is unavailable.
    """
    candidates = [data_dir, os.environ.get("CIFAR100_DIR"), "data", "./data",
                  os.path.expanduser("~/data")]
    for root in candidates:
        if not root:
            continue
        base = os.path.join(root, "cifar-100-python")
        if os.path.isfile(os.path.join(base, "train")):
            x_tr, y_tr = _read_cifar_pickle(os.path.join(base, "train"))
            x_te, y_te = _read_cifar_pickle(os.path.join(base, "test"))
            return Dataset(x_tr, y_tr, x_te, y_te)
        tar = os.path.join(root, "cifar-100-python.tar.gz")
        if os.path.isfile(tar):
            with tarfile.open(tar) as tf:
                tf.extractall(root, filter="data")
            return load_cifar100(root, allow_synthetic=False)
    if not allow_synthetic:
        raise FileNotFoundError("CIFAR-100 not found in: %r" % (candidates,))
    return synthetic_cifar100()


def synthetic_cifar100(n_train: int = 50_000, n_test: int = 10_000,
                       num_classes: int = NUM_CLASSES,
                       seed: int = 0, template_amp: float = 0.18,
                       noise: float = 0.12) -> Dataset:
    """Deterministic class-structured stand-in for CIFAR-100.

    Each class gets a smooth random color/gradient template; samples are the
    template plus pixel noise. With the defaults the classes are cleanly
    separable (models reach ~100% within an epoch — good for fast
    convergence checks); lowering ``template_amp`` and raising ``noise``
    (e.g. 0.06/0.45) gives a CIFAR-like *gradual* learning curve, used by
    the recorded 'hard' experiment artifacts to compare curve shapes
    against the reference's real-data runs.
    """
    rng = np.random.default_rng(seed)
    # Low-frequency class templates: random 4x4x3 upsampled to 32x32x3.
    coarse = rng.normal(0.0, 1.0, size=(num_classes, 4, 4, 3)).astype(np.float32)
    templates = coarse.repeat(8, axis=1).repeat(8, axis=2)  # [C,32,32,3]
    templates = 0.5 + template_amp * templates

    def make_split(n: int, split_seed: int):
        r = np.random.default_rng(seed * 1000 + split_seed)
        y = np.arange(n, dtype=np.int32) % num_classes
        r.shuffle(y)
        x = templates[y] + r.normal(
            0.0, noise, size=(n, 32, 32, 3)).astype(np.float32)
        x = np.clip(x, 0.0, 1.0)
        return (x * 255.0).astype(np.uint8), y

    x_tr, y_tr = make_split(n_train, 1)
    x_te, y_te = make_split(n_test, 2)
    return Dataset(x_tr, y_tr, x_te, y_te, num_classes=num_classes,
                   synthetic=True)


def compositional_cifar100(n_train: int = 50_000, n_test: int = 10_000,
                           num_classes: int = NUM_CLASSES, seed: int = 0,
                           n_motifs: int = 48, motifs_per_class: int = 3,
                           motif_px: int = 10, motif_amp: float = 0.20,
                           template_amp: float = 0.024,
                           bg_noise: float = 0.25, n_distractors: int = 2,
                           amp_jitter: float = 0.5,
                           label_noise: float = 0.22) -> Dataset:
    """Synthetic CIFAR-100 stand-in calibrated to the reference's difficulty.

    :func:`synthetic_cifar100`'s fixed class template + iid pixel noise is a
    nearly linear problem — ResNet-18 solves it within one epoch, so the
    recorded learning curves were trivially steep (round-2 VERDICT item 1).
    The reference's real-data curve (epoch-1 test acc 11.95%, ~65% reached
    only after both MultiStepLR drops — /root/reference/baseline/results/
    baseline_summary.json, README.md:446) needs a task whose structure is
    *earned over many epochs*. This generator composes three signal sources
    whose learning speeds differ:

    - a weak per-class global template (``template_amp``) — the linear
      component; drives the slow early-epoch gains above chance;
    - **compositional motifs**: class identity = WHICH ``motifs_per_class``
      motifs (from a shared bank of ``n_motifs``) appear in the image, at
      uniformly random positions per sample. Position-invariant motif
      detection + co-occurrence logic is genuinely nonlinear for a CNN and
      dominates mid-training;
    - ``n_distractors`` random extra motifs per sample and ±``amp_jitter``
      amplitude jitter for confusability, plus symmetric ``label_noise``
      (applied to train AND test labels) as the irreducible-error term that
      caps the plateau near the reference's ~65-70%.

    Defaults are the calibrated operating point recorded in
    ``experiments/results/calibrated/`` (chosen by the sweep in
    experiments/calibrate_dataset.py so the reference recipe — batch 128,
    SGD momentum, MultiStepLR([10,15]) — lands near the reference curve:
    measured epoch-1 test acc 7.8% vs the reference's 11.95%, 65% first
    crossed at epoch 11 (right after the first lr drop), plateau 70.5%
    vs the reference's ~65-70%).
    """
    rng = np.random.default_rng(seed + 31)
    # Motif bank: smooth zero-mean patterns, unit RMS, motif_px square.
    coarse_px = max(2, motif_px // 3)
    coarse = rng.normal(0.0, 1.0, size=(n_motifs, coarse_px, coarse_px, 3))
    reps = -(-motif_px // coarse_px)  # ceil
    motifs = coarse.repeat(reps, axis=1).repeat(reps, axis=2)
    motifs = motifs[:, :motif_px, :motif_px, :].astype(np.float32)
    motifs -= motifs.mean(axis=(1, 2, 3), keepdims=True)
    motifs /= np.sqrt((motifs ** 2).mean(axis=(1, 2, 3), keepdims=True))

    # Class -> distinct motif combination (sorted for determinism).
    combos = set()
    class_motifs = np.empty((num_classes, motifs_per_class), np.int64)
    for c in range(num_classes):
        while True:
            pick = tuple(sorted(rng.choice(n_motifs, motifs_per_class,
                                           replace=False)))
            if pick not in combos:
                combos.add(pick)
                class_motifs[c] = pick
                break

    # Weak global templates (same construction as synthetic_cifar100).
    t_coarse = rng.normal(0.0, 1.0, size=(num_classes, 4, 4, 3)
                          ).astype(np.float32)
    templates = template_amp * t_coarse.repeat(8, axis=1).repeat(8, axis=2)

    span = 32 - motif_px + 1

    def make_split(n: int, split_seed: int):
        r = np.random.default_rng(seed * 1000 + split_seed + 13)
        y = np.arange(n, dtype=np.int32) % num_classes
        r.shuffle(y)
        x = 0.5 + templates[y] + r.normal(
            0.0, bg_noise, size=(n, 32, 32, 3)).astype(np.float32)
        idx_n = np.arange(n)[:, None, None]
        grid = np.arange(motif_px)
        slots = np.concatenate(
            [class_motifs[y],
             r.integers(0, n_motifs, size=(n, n_distractors))], axis=1)
        for j in range(slots.shape[1]):
            pos = r.integers(0, span, size=(n, 2))
            amps = motif_amp * (1.0 + amp_jitter * r.uniform(-1, 1, n)
                                ).astype(np.float32)
            rows = pos[:, 0, None] + grid          # [n, motif_px]
            cols = pos[:, 1, None] + grid
            patch = motifs[slots[:, j]] * amps[:, None, None, None]
            x[idx_n, rows[:, :, None], cols[:, None, :]] += patch
        if label_noise > 0.0:
            flip = r.uniform(size=n) < label_noise
            y = np.where(flip, r.integers(0, num_classes, n).astype(np.int32),
                         y)
        x = np.clip(x, 0.0, 1.0)
        return (x * 255.0).astype(np.uint8), y

    x_tr, y_tr = make_split(n_train, 1)
    x_te, y_te = make_split(n_test, 2)
    return Dataset(x_tr, y_tr, x_te, y_te, num_classes=num_classes,
                   synthetic=True)


def synthetic_imagenet(n_train: int = 10_000, n_test: int = 1_000,
                       num_classes: int = 1000, image_size: int = 224,
                       seed: int = 0) -> Dataset:
    """ImageNet-shaped synthetic data for the ResNet-50 pod-scale config
    (BASELINE.json configs[3]); same class-template construction as
    :func:`synthetic_cifar100` at configurable resolution."""
    rng = np.random.default_rng(seed + 77)
    coarse_px = max(4, image_size // 8)
    coarse = rng.normal(0.0, 1.0, size=(num_classes, coarse_px, coarse_px, 3)
                        ).astype(np.float32)
    rep = image_size // coarse_px
    templates = 0.5 + 0.18 * coarse.repeat(rep, axis=1).repeat(rep, axis=2)

    def make_split(n: int, split_seed: int):
        r = np.random.default_rng(seed * 1000 + split_seed + 7)
        y = np.arange(n, dtype=np.int32) % num_classes
        r.shuffle(y)
        x = templates[y] + r.normal(
            0.0, 0.12, size=(n, image_size, image_size, 3)).astype(np.float32)
        return (np.clip(x, 0.0, 1.0) * 255.0).astype(np.uint8), y

    x_tr, y_tr = make_split(n_train, 1)
    x_te, y_te = make_split(n_test, 2)
    return Dataset(x_tr, y_tr, x_te, y_te, num_classes=num_classes,
                   synthetic=True)


def shard_range(n: int, worker_id: int, total_workers: int) -> tuple[int, int]:
    """Contiguous [start, end) shard for ``worker_id``.

    Bit-for-bit the reference split: equal ``n // total_workers`` chunks, and
    the LAST worker additionally takes the remainder
    (src/workers/worker.py:166-179).
    """
    if not 0 <= worker_id < total_workers:
        raise ValueError(f"worker_id {worker_id} not in [0, {total_workers})")
    per = n // total_workers
    start = worker_id * per
    end = n if worker_id == total_workers - 1 else start + per
    return start, end


def to_float(x: jax.Array) -> jax.Array:
    """uint8 -> float32 in [0, 1] (torchvision ToTensor equivalent)."""
    return x.astype(jnp.float32) / 255.0


def standardize(x01: jax.Array) -> jax.Array:
    """[0,1] float -> per-channel standardized (worker.py:149-154 Normalize)."""
    return (x01 - CIFAR100_MEAN) / CIFAR100_STD


def normalize(x: jax.Array) -> jax.Array:
    """uint8 [.,32,32,3] -> standardized float (ToTensor + Normalize)."""
    return standardize(to_float(x))


def augment_batch(key: jax.Array, x: jax.Array) -> jax.Array:
    """On-device RandomCrop(32, padding=4) + RandomHorizontalFlip.

    Matches the reference's torchvision transforms (worker.py:145-150) but
    runs vectorized inside the compiled step: zero-pad to 40x40, per-image
    dynamic-slice crop, per-image flip. ``x`` is RAW-scale [B,32,32,3] —
    uint8 or float in [0,1]; every op here is a pure index permutation
    with zero padding, so augmenting the uint8 pixels and casting after
    produces bit-identical floats to casting first, at 1/4 the gather
    bandwidth (the hot-path callers in train/steps.py exploit that).
    torchvision applies RandomCrop BEFORE Normalize, so the zero padding
    means black pixels, not mean-color pixels; call :func:`standardize`
    AFTER this (and after :func:`to_float` for uint8 inputs) to preserve
    that parity.
    """
    b, h, w, c = x.shape
    k_crop, k_flip = jax.random.split(key)
    pad = 4
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    offsets = jax.random.randint(k_crop, (b, 2), 0, 2 * pad + 1)

    # Per-image crop as two batched take_along_axis gathers (one per spatial
    # axis) — much faster on TPU than B separate dynamic slices (the vmap'd
    # form cost ~45% of the whole ResNet-18 train step).
    rows = offsets[:, 0:1] + jnp.arange(h)[None, :]          # [B, h]
    cols = offsets[:, 1:2] + jnp.arange(w)[None, :]          # [B, w]
    x = jnp.take_along_axis(xp, rows[:, :, None, None], axis=1)
    x = jnp.take_along_axis(x, cols[:, None, :, None], axis=2)

    flip = jax.random.bernoulli(k_flip, 0.5, (b,))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)


def make_batches(x: np.ndarray, y: np.ndarray, batch_size: int, *,
                 seed: int = 0, shuffle: bool = True,
                 drop_remainder: bool = True) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Host-side batch iterator over one epoch (shard-local shuffling)."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    stop = (n // batch_size) * batch_size if drop_remainder else n
    for i in range(0, stop, batch_size):
        take = idx[i:i + batch_size]
        yield x[take], y[take]
