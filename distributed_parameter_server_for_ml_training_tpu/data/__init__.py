"""Input pipelines: CIFAR-100 (disk or synthetic), sharding, augmentation."""

from .cifar import (
    CIFAR100_MEAN,
    CIFAR100_STD,
    Dataset,
    augment_batch,
    compositional_cifar100,
    load_cifar100,
    make_batches,
    normalize,
    shard_range,
    standardize,
    synthetic_cifar100,
    to_float,
)

__all__ = [
    "CIFAR100_MEAN",
    "CIFAR100_STD",
    "Dataset",
    "augment_batch",
    "compositional_cifar100",
    "load_cifar100",
    "make_batches",
    "normalize",
    "shard_range",
    "standardize",
    "synthetic_cifar100",
    "to_float",
]
