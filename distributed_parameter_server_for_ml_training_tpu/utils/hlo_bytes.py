"""Per-device collective wire-byte accounting from compiled HLO.

The reference measured its communication cost at the wire (pickled payload
sizes through gRPC, server.py logs); the SPMD analogue is the set of
collective ops XLA actually emitted. This module parses a compiled
executable's HLO text and applies the standard per-device traffic model of
each collective, giving a comparable "bytes over ICI per step per device"
number for the compression modes (parallel/sync_dp.py) without needing a
hardware profiler. Used by tests/test_quantize.py (asserts the int8 ring
moves fewer bytes than bf16 pmean) and experiments/measure_comm_bytes.py
(records the bytes-vs-N model in PERF.md).

Traffic model (ring algorithms, the TPU/ICI default):
- collective-permute: result bytes (one neighbor send per device)
- all-reduce:        2 x (N-1)/N x result bytes (reduce-scatter + all-gather)
- all-gather:        (N-1)/N x result bytes (each device receives all
                     other shards)
- reduce-scatter:    (N-1) x result bytes ((N-1)/N of the N-x-larger input)
"""

from __future__ import annotations

import re
from collections import defaultdict

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_SHAPE_RE = re.compile(r"(pred|[suf]\d+|bf16)\[([\d,]*)\]")
# Lazy match after '=' up to the op keyword: tuple result shapes may
# contain '/*index=5*/' comments, so the shape text itself can hold '='.
_OP_RE = re.compile(
    r"=\s+(.*?)\s*"
    r"(collective-permute|all-reduce|all-gather|reduce-scatter)"
    r"(-start)?\(")


def _shape_bytes(shape_text: str, pick: str = "sum") -> int:
    """Bytes of all typed shapes in ``shape_text`` (or just the largest).

    ``pick`` handles async ``-start`` forms of collectives, whose result
    tuple aliases the operand alongside the result buffer — summing both
    would double-count the wire bytes. All four collective kinds can lower
    to ``-start``/``-done`` pairs on TPU: for collective-permute /
    all-gather / all-reduce the RESULT is the largest member
    (``pick='largest'``); for reduce-scatter the result is 1/N of the
    operand, so the result is the SMALLEST member (``pick='smallest'``) —
    the (N-1) ring factor in :func:`collective_wire_bytes` is calibrated
    for result bytes. Scalar tuple members (``u32[]`` context handles some
    start forms carry) are excluded from the pick so 'smallest' lands on
    the result, not a 4-byte handle. Scope: single-tensor collectives (the
    forms this codebase emits); a variadic start would undercount.
    """
    sizes, scalars = [], []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        (scalars if dims == "" else sizes).append(n * _DTYPE_BYTES[dtype])
    if pick == "sum":
        return sum(sizes) + sum(scalars)
    if not sizes:
        return 0
    return max(sizes) if pick == "largest" else min(sizes)


def collective_wire_bytes(hlo_text: str, n_devices: int) -> dict:
    """Sum per-device wire bytes of every collective in ``hlo_text``.

    Returns ``{"total": int, "by_op": {op: bytes}, "count": {op: int}}``.
    ``-done`` halves of async pairs are skipped (the ``-start`` carries
    the shape); small scalar reductions count like any other.
    """
    by_op: dict[str, int] = defaultdict(int)
    count: dict[str, int] = defaultdict(int)
    frac = (n_devices - 1) / n_devices
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        shape_text, op, is_start = m.group(1), m.group(2), bool(m.group(3))
        if is_start:
            pick = "smallest" if op == "reduce-scatter" else "largest"
        else:
            pick = "sum"
        b = _shape_bytes(shape_text, pick=pick)
        if op == "collective-permute":
            moved = b
        elif op == "all-reduce":
            moved = 2 * frac * b
        elif op == "all-gather":
            moved = frac * b
        else:  # reduce-scatter: result is 1/N of the reduced input
            moved = (n_devices - 1) * b
        by_op[op] += int(moved)
        count[op] += 1
    return {"total": sum(by_op.values()), "by_op": dict(by_op),
            "count": dict(count)}


def sync_grad_mean_bytes(n_devices: int, size: int,
                         modes=("none", "bf16", "int8")) -> dict:
    """Per-device wire bytes of the sync-DP gradient mean per compression
    mode, measured from compiled HLO on an ``n_devices`` mesh.

    The single measurement harness behind tests/test_quantize.py and
    experiments/measure_comm_bytes.py. CPU XLA widens bf16 collectives to
    f32; when detected, the bf16 number is bounded by half the f32
    measurement (same op, half-width dtype on TPU) and
    ``bf16_widened_on_cpu`` is set.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.compression import (compress_for_allreduce,
                                   decompress_from_allreduce)
    from ..parallel import make_mesh
    from ..parallel.sync_dp import _int8_ring_allreduce_mean

    mesh = make_mesh(n_devices)
    g = jnp.ones((size,), jnp.float32)
    key = jax.random.PRNGKey(0)

    def mean_none(g, key):
        return jax.lax.pmean(g, "data")

    def mean_bf16(g, key):
        c = compress_for_allreduce(g, "bf16")
        return decompress_from_allreduce(jax.lax.pmean(c, "data"), "bf16")

    def mean_int8(g, key):
        return _int8_ring_allreduce_mean(g, "data", n_devices, key)

    fns = {"none": mean_none, "bf16": mean_bf16, "int8": mean_int8}
    out: dict = {}
    for name in modes:
        from ..parallel.mesh import shard_map
        sm = shard_map(fns[name], mesh=mesh, in_specs=(P(), P()),
                       out_specs=P(), check_vma=False)
        hlo = jax.jit(sm).lower(g, key).compile().as_text()
        out[name] = collective_wire_bytes(hlo, n_devices)
    if ("bf16" in out and "none" in out
            and out["bf16"]["total"] > 0.9 * out["none"]["total"]):
        total = out["none"]["total"] // 2
        out["bf16"] = {"total": total, "by_op": {"all-reduce": total},
                       "count": out["bf16"]["count"],
                       "widened_on_cpu": True}
    return out
