"""Utilities: pytree<->flat-dict conversion, timing, structured metrics."""

from .pytree import flatten_params, unflatten_params, tree_bytes

__all__ = ["flatten_params", "unflatten_params", "tree_bytes"]
