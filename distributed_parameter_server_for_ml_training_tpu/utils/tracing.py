"""Tracing/profiling — a first-class gap-fill (SURVEY.md §5.1).

The reference's only instrumentation is ``time.time()`` deltas kept in a
``deque(maxlen=100)`` (server.py:121, 140-141). Here:

- :class:`StepTimer` reproduces that rolling-window timing (for parity in
  the store/trainers),
- :func:`trace` exposes real XLA-level profiling via ``jax.profiler`` —
  the produced trace directory opens in TensorBoard/Perfetto and shows MXU
  utilization, HBM traffic, and collective time per step,
- :func:`annotate` tags host-side regions so they appear on the trace.
"""

from __future__ import annotations

import contextlib
import time
from collections import deque

import jax


class StepTimer:
    """Rolling-window step timing (server.py:121 deque(maxlen=100))."""

    def __init__(self, window: int = 100):
        self.times = deque(maxlen=window)
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0

    @property
    def last(self) -> float:
        return self.times[-1] if self.times else 0.0


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler trace around a code region::

        with trace('/tmp/trace'):
            for _ in range(10):
                state, m = step(state, batch, key)
            jax.block_until_ready(state)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)
