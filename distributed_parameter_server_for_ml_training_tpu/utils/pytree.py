"""Pytree <-> flat ``{name: ndarray}`` dict conversion.

The reference's canonical parameter format is a flat ``{param_name:
np.ndarray}`` dict derived from a torch ``state_dict`` (server.py:96,
worker.py:274-279); the wire format is that dict pickled. The async store
keeps the same flat-dict shape (names are '/'-joined pytree paths), so
store contents and payload logs are directly comparable to the reference.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np
from flax import traverse_util

PyTree = Any


def flatten_params(tree: PyTree, *, as_numpy: bool = True
                   ) -> dict[str, np.ndarray]:
    """Nested params pytree -> flat {'a/b/c': np.ndarray} dict.

    ``as_numpy=False`` keeps the leaves as-is (device arrays stay on device
    — required by the zero-copy DeviceParameterStore path).
    """
    flat = traverse_util.flatten_dict(tree, sep="/")
    if not as_numpy:
        return dict(flat)
    return {k: np.asarray(v) for k, v in flat.items()}


def unflatten_params(flat: Mapping[str, np.ndarray]) -> PyTree:
    """Inverse of :func:`flatten_params`."""
    return traverse_util.unflatten_dict(dict(flat), sep="/")


def tree_bytes(flat: Mapping[str, np.ndarray]) -> int:
    """Total payload size in bytes (the reference logs compressed sizes at
    worker.py:292)."""
    return sum(np.asarray(v).nbytes for v in flat.values())
