"""Structured metrics emission/parsing: the METRICS_JSON convention.

The reference's entire observability pipeline is stdout prints plus ONE
structured line per process at exit — ``METRICS_JSON: {...}`` (server.py:367,
worker.py:435) — scraped from CloudWatch by regex
(scripts/parse_cloudwatch_logs.py:100: ``r'METRICS_JSON:\\s*(\\{.*\\})'``).
Emitters and the parser here keep that exact wire convention so the
reference's downstream ETL/plots work unchanged against our logs.
"""

from __future__ import annotations

import json
import re
import sys
import time
from typing import IO, Iterable

METRICS_RE = re.compile(r"METRICS_JSON:\s*(\{.*\})")


def emit_metrics_json(payload: dict, stream: IO | None = None) -> str:
    """Print the one structured line (server.py:367 / worker.py:435)."""
    line = "METRICS_JSON: " + json.dumps(payload)
    print(line, file=stream or sys.stdout, flush=True)
    return line


def parse_metrics_lines(text: str | Iterable[str]) -> list[dict]:
    """Extract all METRICS_JSON payloads from log text
    (parse_cloudwatch_logs.py:100-121 equivalent)."""
    if not isinstance(text, str):
        text = "\n".join(text)
    out = []
    for m in METRICS_RE.finditer(text):
        try:
            out.append(json.loads(m.group(1)))
        except json.JSONDecodeError:
            continue
    return out


class Stopwatch:
    """Coarse wall-clock timing, the reference's only 'profiler'
    (SURVEY.md §5.1: time.time() deltas). For real tracing use
    utils/tracing.py (jax.profiler)."""

    def __init__(self):
        self.t0 = time.time()

    def elapsed(self) -> float:
        return time.time() - self.t0

    def lap(self) -> float:
        now = time.time()
        dt = now - self.t0
        self.t0 = now
        return dt
