"""ctypes bindings for native/ps_core.cpp (builds on demand with make)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()

# Search order for libps_core.so: explicit override, the source checkout's
# native/ dir, or alongside this module (where installed images copy it —
# a pip-installed package has no ../../native).
_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native"))
_SO_CANDIDATES = [
    os.environ.get("DPS_NATIVE_LIB", ""),
    os.path.join(_NATIVE_DIR, "libps_core.so"),
    os.path.join(os.path.dirname(__file__), "libps_core.so"),
]


def _find_so() -> str | None:
    for p in _SO_CANDIDATES:
        if p and os.path.isfile(p):
            return p
    return None


def _build() -> bool:
    if not os.path.isfile(os.path.join(_NATIVE_DIR, "Makefile")):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return _find_so() is not None
    except (subprocess.SubprocessError, OSError):
        return False


# Every symbol the bindings below resolve; _stale() probes these directly.
_REQUIRED_SYMBOLS = (
    "dps_fp32_to_fp16", "dps_fp16_to_fp32",
    "dps_fp32_to_bf16", "dps_bf16_to_fp32",
    "dps_store_create", "dps_store_destroy", "dps_store_step",
    "dps_store_rejected", "dps_store_fetch", "dps_store_load",
    "dps_store_push_fp16", "dps_store_push_fp32", "dps_store_push_int8",
    "dps_store_stash_fp16", "dps_store_stash_fp32", "dps_store_stash_int8",
    "dps_store_apply_mean", "dps_store_free_slot",
)


def _stale(so: str) -> bool:
    """True when the found .so doesn't export every symbol these bindings
    need (i.e. it predates the current source). Probed directly rather than
    via mtimes — git checkout order makes source-vs-.so timestamps
    meaningless, and a false 'stale' would disable the prebuilt library on
    exactly the toolchain-less machines it was committed for."""
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return True
    try:
        return any(not hasattr(lib, sym) for sym in _REQUIRED_SYMBOLS)
    finally:
        # Release the probe handle: dlopen dedups by pathname, so if make
        # rebuilds the SAME path, a still-open stale mapping would be what
        # the post-build CDLL returns (ADVICE r3). dlclose only drops a
        # refcount; the loader unmaps once no handle remains.
        try:
            import _ctypes

            _ctypes.dlclose(lib._handle)
        except (AttributeError, OSError):
            pass


def load_library() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        so = _find_so()
        if (so is None or _stale(so)) and not _build():
            # Missing OR stale-and-unbuildable: a stale .so lacks newer
            # symbols, and binding it would raise AttributeError below —
            # report the native backend unavailable instead.
            return None
        lib = ctypes.CDLL(_find_so())

        u16p = ctypes.POINTER(ctypes.c_uint16)
        f32p = ctypes.POINTER(ctypes.c_float)
        i64 = ctypes.c_int64

        lib.dps_fp32_to_fp16.argtypes = [f32p, u16p, i64]
        lib.dps_fp16_to_fp32.argtypes = [u16p, f32p, i64]
        lib.dps_fp32_to_bf16.argtypes = [f32p, u16p, i64]
        lib.dps_bf16_to_fp32.argtypes = [u16p, f32p, i64]
        lib.dps_store_create.argtypes = [i64, f32p, ctypes.c_float]
        lib.dps_store_create.restype = ctypes.c_void_p
        lib.dps_store_destroy.argtypes = [ctypes.c_void_p]
        lib.dps_store_step.argtypes = [ctypes.c_void_p]
        lib.dps_store_step.restype = i64
        lib.dps_store_rejected.argtypes = [ctypes.c_void_p]
        lib.dps_store_rejected.restype = i64
        lib.dps_store_fetch.argtypes = [ctypes.c_void_p, f32p]
        lib.dps_store_fetch.restype = i64
        lib.dps_store_load.argtypes = [ctypes.c_void_p, f32p, i64]
        lib.dps_store_push_fp16.argtypes = [ctypes.c_void_p, u16p, i64, i64]
        lib.dps_store_push_fp16.restype = i64
        lib.dps_store_push_fp32.argtypes = [ctypes.c_void_p, f32p, i64, i64]
        lib.dps_store_push_fp32.restype = i64
        i64p = ctypes.POINTER(i64)
        i8p = ctypes.POINTER(ctypes.c_int8)
        lib.dps_store_push_int8.argtypes = [
            ctypes.c_void_p, i8p, f32p, i64p, i64, i64, i64]
        lib.dps_store_push_int8.restype = i64
        lib.dps_store_stash_fp16.argtypes = [ctypes.c_void_p, i64, u16p]
        lib.dps_store_stash_fp32.argtypes = [ctypes.c_void_p, i64, f32p]
        lib.dps_store_stash_int8.argtypes = [
            ctypes.c_void_p, i64, i8p, f32p, i64p, i64]
        lib.dps_store_apply_mean.argtypes = [ctypes.c_void_p, i64p, i64]
        lib.dps_store_apply_mean.restype = i64
        lib.dps_store_free_slot.argtypes = [ctypes.c_void_p, i64]
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return load_library() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u16p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def _i8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int8))


def fp32_to_fp16(src: np.ndarray) -> np.ndarray:
    """Multithreaded fp32->fp16 cast (worker.py:264-268's compression, in
    C++). Falls back to numpy when the library is absent."""
    lib = load_library()
    src = np.ascontiguousarray(src, np.float32)
    if lib is None:
        return src.astype(np.float16)
    out = np.empty(src.shape, np.uint16)
    lib.dps_fp32_to_fp16(_f32p(src.reshape(-1)), _u16p(out.reshape(-1)),
                         src.size)
    return out.view(np.float16)


def fp16_to_fp32(src: np.ndarray) -> np.ndarray:
    lib = load_library()
    src = np.ascontiguousarray(src)
    if src.dtype != np.float16:
        raise TypeError(src.dtype)
    if lib is None:
        return src.astype(np.float32)
    out = np.empty(src.shape, np.float32)
    lib.dps_fp16_to_fp32(_u16p(src.view(np.uint16).reshape(-1)),
                         _f32p(out.reshape(-1)), src.size)
    return out


def fp32_to_bf16(src: np.ndarray) -> np.ndarray:
    """Multithreaded fp32->bfloat16 cast (RNE, bit-for-bit ml_dtypes) for
    the fetch-side codec; ml_dtypes fallback when the library is absent."""
    import ml_dtypes

    lib = load_library()
    src = np.ascontiguousarray(src, np.float32)
    if lib is None:
        return src.astype(ml_dtypes.bfloat16)
    out = np.empty(src.shape, np.uint16)
    lib.dps_fp32_to_bf16(_f32p(src.reshape(-1)), _u16p(out.reshape(-1)),
                         src.size)
    return out.view(ml_dtypes.bfloat16)


def bf16_to_fp32(src: np.ndarray) -> np.ndarray:
    import ml_dtypes

    lib = load_library()
    src = np.ascontiguousarray(src)
    if src.dtype != ml_dtypes.bfloat16:
        raise TypeError(src.dtype)
    if lib is None:
        return src.astype(np.float32)
    out = np.empty(src.shape, np.float32)
    lib.dps_bf16_to_fp32(_u16p(src.view(np.uint16).reshape(-1)),
                         _f32p(out.reshape(-1)), src.size)
    return out
